"""LogisticRegression application.

TPU-native re-build of the reference LR trainer
(ref: Applications/LogisticRegression/ — src/main.cpp entry, src/logreg.cpp
Train/Test driver, src/configure.h key=value config, src/model/ps_model.cpp
PS sync/pipeline logic). Capability parity:

* key=value config file with the reference's keys (input_size, output_size,
  objective_type, updater_type, regular_type, minibatch_size, learning_rate,
  train_epoch, sync_frequency, pipeline, use_ps, reader_type, train_file,
  test_file, output_file)
* params in an ArrayTable; worker premultiplies the LR; server updater applies
* ``sync_frequency``: pull the model every N minibatches
  (ref ps_model.cpp DoesNeedSync :172-182)
* ``pipeline``: double-buffered async pull overlapping compute
  (ref ps_model.cpp GetPipelineTable :236-271) via AsyncBuffer
* background ring-buffer sample reader (ref reader.cpp)

Two execution paths:
* ``use_ps`` host loop — faithful to the reference flow (per-minibatch host
  dispatch). Good for parity and multi-process ASGD.
* ``fused`` in-graph loop — the TPU-first path: the whole epoch runs as one
  ``lax.scan`` over device-resident minibatches; PS semantics preserved via
  ``table.functional_add``. This is where the MXU roofline lives.

Usage: ``python -m multiverso_tpu.apps.logistic_regression <config file>``
(same one-arg shape as ref src/main.cpp:7-13).
"""

from __future__ import annotations

import sys
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

import multiverso_tpu as mv
from multiverso_tpu.io.sample_reader import SampleReader
from multiverso_tpu.models import logreg as model_lib
from multiverso_tpu.telemetry import profiler as _prof
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils import config as config_lib
from multiverso_tpu.utils import log
from multiverso_tpu.utils.async_buffer import AsyncBuffer
from multiverso_tpu.utils.dashboard import monitor


class LogRegConfig:
    """ref src/configure.h:9-111 key=value schema (subset that has TPU
    meaning; FTRL keys parsed, FTRL objective arrives with the sparse path)."""

    def __init__(self, pairs: Dict[str, str]):
        g = pairs.get

        def b(key, default="false"):
            # accept the same truthy spellings as the WE argv parser so
            # "-async_ps 1"-style configs behave identically across apps
            return g(key, default).lower() in ("true", "1", "yes")

        self.input_size = int(g("input_size", "0"))
        self.output_size = int(g("output_size", "2"))
        self.sparse = b("sparse")
        self.objective_type = g("objective_type", "softmax")
        self.updater_type = g("updater_type", "sgd")
        self.regular_type = g("regular_type", "none")
        self.regular_coef = float(g("regular_coef", "0.0"))
        self.minibatch_size = int(g("minibatch_size", "64"))
        self.learning_rate = float(g("learning_rate", "0.1"))
        self.train_epoch = int(g("train_epoch", "1"))
        self.sync_frequency = int(g("sync_frequency", "1"))
        # bounded staleness (SSP): -1 = off (pure async between barriers),
        # 0 = BSP lockstep, s > 0 = at most s minibatches ahead of the
        # slowest worker; needs ssp_dir on shared storage (see ssp.py).
        # heartbeat_dir additionally excludes dead workers from the bound
        # (elastic.failed); ssp_timeout bounds every wait.
        self.staleness = int(g("staleness", "-1"))
        self.ssp_dir = g("ssp_dir", "")
        self.ssp_timeout = float(g("ssp_timeout", "600"))
        self.heartbeat_dir = g("heartbeat_dir", "")
        self.pipeline = b("pipeline")
        self.use_ps = b("use_ps", "true")
        # uncoordinated async tables (multiverso_tpu.ps) for the dense PS
        # path: workers push/pull at independent rates, no collectives
        self.async_ps = b("async_ps")
        self.fused = b("fused")
        # reader_type accepts BOTH this app's format names (libsvm |
        # dense) and the reference's reader factory names (ref
        # reader.cpp:222-237 Get): "weight" = per-sample importance
        # weights (format follows the sparse flag), "bsparse" = binary
        # presence-only sparse records
        rt = g("reader_type", "libsvm")
        if rt == "weight":
            rt = "weight" if self.sparse else "weight_dense"
        self.reader_type = rt
        self.mnist_dir = g("mnist_dir", "")  # BASELINE config 1: idx files
        self.train_file = g("train_file", "")
        self.test_file = g("test_file", "")
        self.output_file = g("output_file", "")
        self.show_time_per_sample = int(g("show_time_per_sample", "10000"))
        if self.staleness >= 0 and not self.ssp_dir:
            raise ValueError("staleness is set but ssp_dir is empty — the "
                             "bound would be silently unenforced; set "
                             "ssp_dir to shared storage")
        if self.staleness >= 0 and not self.use_ps:
            raise ValueError("staleness needs use_ps=true (there is no "
                             "parameter server to be stale against)")
        if self.async_ps and self.mnist_dir:
            raise ValueError("async_ps trains through the use_ps host loop "
                             "(train_file=...); the mnist_dir route uses "
                             "the fused in-graph path, which async tables "
                             "do not expose")

    @classmethod
    def from_file(cls, path: str) -> "LogRegConfig":
        return cls(config_lib.parse_config_file(path))


class LogReg:
    """ref src/logreg.cpp LogReg<EleType>: config-driven trainer."""

    def __init__(self, cfg: LogRegConfig):
        if cfg.input_size <= 0:
            raise ValueError("config must set input_size")
        self.cfg = cfg
        if not mv.Zoo.get().started:
            mv.init()
        n_params = model_lib.param_count(cfg.input_size, cfg.output_size)
        if cfg.sparse and cfg.async_ps:
            # the reference's flagship sparse workload: hash-keyed rows on
            # the UNCOORDINATED plane, FTRL z/n living as shard updater
            # state (ref model/ps_model.cpp:24-41 creates SparseTable /
            # FTRL table; util/sparse_table.h, util/ftrl_sparse_table.h)
            self.sparse_table = mv.AsyncSparseKVTable(
                cfg.output_size, updater=cfg.updater_type,
                name="logreg_sparse", num_row=cfg.input_size + 1)
            self.table = None
        elif cfg.sparse:
            # feature-major layout: row = feature (last row = bias), col =
            # class, in a SparseMatrixTable so only active-feature rows cross
            # the wire (ref custom SparseWorkerTable + per-chunk key sets,
            # Applications/LogisticRegression/src/util/sparse_table.h)
            self.sparse_table = mv.SparseMatrixTable(
                cfg.input_size + 1, cfg.output_size,
                updater=cfg.updater_type, name="logreg_sparse")
            self.table = None
        elif cfg.async_ps:
            # the reference's default (async) server mode: deltas land on
            # the owning shard as they arrive (ref src/server.cpp:36-58)
            self.sparse_table = None
            self.table = mv.AsyncArrayTable(
                n_params, updater=cfg.updater_type, name="logreg_params")
        else:
            self.sparse_table = None
            self.table = mv.ArrayTable(n_params, updater=cfg.updater_type,
                                       name="logreg_params")
        self._local_w = np.zeros(n_params, dtype=np.float32)
        self._grad_fn = jax.jit(
            lambda w, x, y: model_lib.loss_and_grad(
                w, x, y, cfg.objective_type, cfg.regular_type,
                cfg.regular_coef))
        self._acc_fn = jax.jit(model_lib.accuracy)
        self._sparse_grad_jit = {}

    # ------------------------------------------------------------------ #
    def _weights(self) -> jax.Array:
        return jnp.asarray(model_lib.unflatten(
            jnp.asarray(self._local_w), self.cfg.input_size,
            self.cfg.output_size))

    def _sync_model(self) -> None:
        if self.cfg.sparse:
            # feature-major (D+1, C) -> class-major flat (C*(D+1),)
            w = self.sparse_table.get()
            self._local_w[:] = w.T.reshape(-1)
        else:
            self.table.get(out=self._local_w)

    def train_file(self) -> Dict[str, float]:
        """Epoch loop over the sample reader (ref logreg.cpp Train :41-87)."""
        cfg = self.cfg
        losses, seen, t0 = [], 0, time.perf_counter()
        pull_buffer: Optional[AsyncBuffer] = None
        if cfg.pipeline and not cfg.sparse:
            pull_buffer = AsyncBuffer(self.table.get)
        ssp_clock = None
        if cfg.staleness >= 0:
            from multiverso_tpu.ssp import SSPClock
            ignore = None
            if cfg.heartbeat_dir:
                from multiverso_tpu import elastic
                ignore = lambda: elastic.failed(cfg.heartbeat_dir)
            ssp_clock = SSPClock(cfg.ssp_dir, staleness=cfg.staleness,
                                 timeout=cfg.ssp_timeout, ignore=ignore)
        # the sparse path trains against the table's row ops directly —
        # _local_w is only read by test/save (which sync themselves), and a
        # dense pull of a hash-sharded table would materialize every
        # possible key for nothing
        if not cfg.sparse:
            self._sync_model()
        # pipelined SPARSE pulls need overlapped-sparse-get support (the
        # async plane's _SparseGetMixin); the sync sparse table's pull is
        # a device op with no wire to hide, so it stays blocking
        sparse_pipeline = (cfg.sparse and cfg.pipeline
                           and hasattr(self.sparse_table,
                                       "get_rows_sparse_async"))
        for epoch in range(cfg.train_epoch):
            reader = SampleReader(cfg.train_file, cfg.input_size,
                                  cfg.minibatch_size, fmt=cfg.reader_type)
            batches = (self._sparse_lookahead(reader) if sparse_pipeline
                       else reader)
            # WE-shaped step bracketing (flag step_profile, no-op
            # otherwise): each step consumes the CURRENT minibatch and
            # fetches the NEXT one, so the reader's io_wait phase (and
            # the producer thread's io.produce intervals) land on the
            # training step they stalled/overlapped
            batches_it = iter(batches)
            item = next(batches_it, None)
            batch_idx = 0
            while item is not None:
                with _prof.step("lr.minibatch"):
                    if sparse_pipeline:
                        y_len = len(item["y"])
                        loss = self._train_sparse_prepared(item)
                    elif cfg.sparse:
                        x, y, keys = item
                        y_len = len(y)
                        loss = self._train_minibatch_sparse(x, y, keys)
                    else:
                        x, y, keys = item
                        y_len = len(y)
                        loss = self._train_minibatch(x, y, batch_idx,
                                                     pull_buffer)
                    item = next(batches_it, None)
                batch_idx += 1
                losses.append(float(loss))
                if ssp_clock is not None:
                    ssp_clock.tick()
                seen += y_len
                if seen % cfg.show_time_per_sample < cfg.minibatch_size:
                    log.info("epoch %d, samples %d, loss %.4f",
                             epoch, seen, losses[-1])
            mv.barrier()
            if not cfg.sparse:
                self._sync_model()
        if pull_buffer is not None:
            pull_buffer.stop()
        dt = time.perf_counter() - t0
        return {"loss": float(np.mean(losses[-10:])) if losses else 0.0,
                "samples_per_sec": seen / dt if dt > 0 else 0.0,
                "seconds": dt}

    def _train_minibatch(self, x, y, batch_idx: int,
                         pull_buffer: Optional[AsyncBuffer]) -> float:
        """ref ps_model.cpp UpdateTable :185-203 + DoesNeedSync :172-182."""
        cfg = self.cfg
        with monitor("logreg.minibatch"):
            loss, grad = self._grad_fn(self._weights(), x, y)
            delta = np.zeros(self.table.size, np.float32)
            delta[: grad.size] = np.asarray(grad).reshape(-1) * cfg.learning_rate
            self.table.add_async(
                delta, AddOption(learning_rate=cfg.learning_rate))
            if (batch_idx + 1) % cfg.sync_frequency == 0:
                if pull_buffer is not None:
                    # double-buffer: consume the overlapped pull, kick the next
                    # (copy: the pull result is a read-only device view)
                    np.copyto(self._local_w, pull_buffer.get())
                else:
                    self._sync_model()
        return float(loss)

    def _sparse_grad_fn(self, k: int):
        """Jitted sparse-feature gradient: only the pulled weight rows
        participate (ref sparse LR: per-chunk key sets feed sparse pulls,
        Applications/LogisticRegression/src/reader.h:21-146)."""
        fn = self._sparse_grad_jit.get(k)
        if fn is None:
            obj = self.cfg.objective_type
            num_classes = self.cfg.output_size

            def _g(wsub, xa, y):
                logits = xa @ wsub                       # (B, C)
                onehot = jax.nn.one_hot(y, num_classes, dtype=wsub.dtype)
                if obj == "sigmoid":
                    p = jax.nn.sigmoid(logits)
                    eps = 1e-7
                    loss = -jnp.mean(jnp.sum(
                        onehot * jnp.log(p + eps)
                        + (1 - onehot) * jnp.log(1 - p + eps), axis=-1))
                    diff = p - onehot
                else:
                    logp = jax.nn.log_softmax(logits, axis=-1)
                    loss = -jnp.mean(jnp.sum(onehot * logp, axis=-1))
                    diff = jax.nn.softmax(logits, axis=-1) - onehot
                grad = xa.T @ diff / xa.shape[0]         # (k, C)
                return loss, grad

            fn = self._sparse_grad_jit[k] = jax.jit(_g)
        return fn

    def _prep_sparse(self, x: np.ndarray, y: np.ndarray,
                     keys: Optional[np.ndarray], dispatch: bool) -> Dict:
        """Build the padded key set + feature submatrix for one sparse
        minibatch; with ``dispatch``, also START the stale-only pull (the
        is_pipeline overlap — ref src/table/matrix.cpp:407-418; safe here
        because overlapped sparse pulls are first-class on the async
        plane, ps/tables._SparseGetMixin)."""
        cfg = self.cfg
        D = cfg.input_size
        with monitor("logreg.sparse_prep"):
            if keys is None:
                keys = np.nonzero(np.any(x != 0, axis=0))[0]
            keys = np.asarray(keys, dtype=np.int64).reshape(-1)
            keys_b = np.append(keys, D)              # + bias row
            k = keys_b.size
            kb = 8
            while kb < k:
                kb *= 2
            pad = kb - k
            keys_p = np.concatenate([keys_b, np.full(pad, D, np.int64)])
            wid = None if cfg.async_ps else mv.worker_id()
            # dispatch BEFORE the xa build so the wire round-trip hides
            # under the submatrix host work
            pull = (self.sparse_table.get_rows_sparse_async(keys_p,
                                                            worker_id=wid)
                    if dispatch else None)
            # pad with the bias row; its padded xa columns are zero, so
            # the padded slots contribute exactly zero gradient
            xa = np.concatenate(
                [x[:, keys], np.ones((len(y), 1), np.float32),
                 np.zeros((len(y), pad), np.float32)], axis=1)
        return {"keys_p": keys_p, "xa": xa, "y": y, "kb": kb, "wid": wid,
                "pull": pull}

    def _train_sparse_prepared(self, prep: Dict) -> float:
        """Consume a prepared sparse minibatch: pull (or collect the
        overlapped pull), compute on the submatrix, push row deltas.
        FTRL receives the raw gradient (its alpha owns the step size,
        ref app updater.cpp FTRL branch); other updaters get lr*grad."""
        cfg = self.cfg
        with monitor("logreg.sparse_minibatch"):
            if prep["pull"] is not None:
                wsub = self.sparse_table.wait(prep["pull"])
            else:
                wsub = self.sparse_table.get_rows_sparse(
                    prep["keys_p"], worker_id=prep["wid"])
            loss, grad = self._sparse_grad_fn(prep["kb"])(
                jnp.asarray(wsub), jnp.asarray(prep["xa"]),
                jnp.asarray(prep["y"]))
            grad = np.asarray(grad)
            if self.sparse_table.updater.name != "ftrl":
                grad = grad * cfg.learning_rate
            self.sparse_table.add_rows(prep["keys_p"], grad)
        return float(loss)

    def _train_minibatch_sparse(self, x: np.ndarray, y: np.ndarray,
                                keys: Optional[np.ndarray]) -> float:
        return self._train_sparse_prepared(
            self._prep_sparse(x, y, keys, dispatch=False))

    def _sparse_lookahead(self, reader):
        """One-batch lookahead: dispatch batch N+1's sparse pull before
        training batch N (ref ps_model.cpp GetPipelineTable's double
        buffer, applied to the SPARSE path). The pull can miss batch N's
        own push — the same one-step staleness the reference's pipeline
        accepted."""
        prev = None
        try:
            for x, y, keys in reader:
                cur = self._prep_sparse(x, y, keys, dispatch=True)
                if prev is not None:
                    out, prev = prev, cur
                    yield out
                else:
                    prev = cur
            if prev is not None:
                out, prev = prev, None
                yield out
        finally:
            # consumer raised/abandoned us with a pull in flight: drain it
            # so the msg id doesn't sit in the table's pending map forever
            # (a later flush() would otherwise block on a pull nobody owns)
            if prev is not None and prev["pull"] is not None:
                try:
                    self.sparse_table.wait(prev["pull"])
                except Exception:
                    pass

    def train_arrays(self, x: np.ndarray, y: np.ndarray,
                     epochs: Optional[int] = None) -> Dict[str, float]:
        """In-graph fused path: whole epoch as one lax.scan on device."""
        cfg = self.cfg
        if cfg.async_ps:
            raise ValueError("async_ps trains through the use_ps host loop "
                             "(train_file / train_minibatches); the fused "
                             "in-graph path needs the functional table "
                             "plane, which async tables do not expose")
        epochs = epochs or cfg.train_epoch
        n = (len(y) // cfg.minibatch_size) * cfg.minibatch_size
        xb = jnp.asarray(x[:n]).reshape(-1, cfg.minibatch_size, cfg.input_size)
        yb = jnp.asarray(y[:n]).reshape(-1, cfg.minibatch_size)
        step = model_lib.make_train_step(
            self.table, cfg.input_size, cfg.output_size, cfg.objective_type,
            cfg.regular_type, cfg.regular_coef, cfg.learning_rate)

        @jax.jit
        def epoch_fn(state, xb, yb):
            return jax.lax.scan(step, state, (xb, yb))

        t0 = time.perf_counter()
        state = self.table.state
        losses = None
        for _ in range(epochs):
            state, losses = epoch_fn(state, xb, yb)
        jax.block_until_ready(state["data"])
        dt = time.perf_counter() - t0
        self.table.adopt(state)
        self._sync_model()
        return {"loss": float(jnp.mean(losses[-10:])),
                "samples_per_sec": epochs * n / dt if dt > 0 else 0.0,
                "seconds": dt}

    # ------------------------------------------------------------------ #
    def test_arrays(self, x: np.ndarray, y: np.ndarray) -> float:
        """ref logreg.cpp Test :121-173 — accuracy on held-out data."""
        self._sync_model()
        return float(self._acc_fn(self._weights(), jnp.asarray(x),
                                  jnp.asarray(y)))

    def test_file(self) -> float:
        cfg = self.cfg
        correct, total = 0, 0
        reader = SampleReader(cfg.test_file, cfg.input_size,
                              cfg.minibatch_size, fmt=cfg.reader_type)
        self._sync_model()
        w = self._weights()
        for x, y, _ in reader:
            acc = float(self._acc_fn(w, jnp.asarray(x), jnp.asarray(y)))
            correct += acc * len(y)
            total += len(y)
        return correct / total if total else 0.0

    @property
    def param_table(self):
        return self.sparse_table if self.cfg.sparse else self.table

    def save_model(self, path: Optional[str] = None) -> None:
        """ref model.cpp Store :147-205 — worker-side pull then write."""
        from multiverso_tpu.io.stream import open_stream
        path = path or self.cfg.output_file
        if not path:
            return
        with open_stream(path, "wb") as s:
            self.param_table.store(s)

    def load_model(self, path: str) -> None:
        from multiverso_tpu.io.stream import open_stream
        with open_stream(path, "rb") as s:
            self.param_table.load(s)
        self._sync_model()


def main(argv=None) -> int:
    # honor JAX_PLATFORMS/XLA_FLAGS even under a site-registered
    # accelerator plugin (same contract as the harness)
    from multiverso_tpu.utils.platform import apply_platform_env
    apply_platform_env()
    argv = argv if argv is not None else sys.argv[1:]
    # "-key=value" entries are runtime flags routed through mv.init exactly
    # like the reference's MV_Init argv flow (ref src/multiverso.cpp:10,
    # src/util/configure.cpp:9-54) — e.g. -ps_rank=0 -ps_world=4
    rest = config_lib.consume_runtime_flags(argv)
    if len(rest) != 1:
        print("usage: python -m multiverso_tpu.apps.logistic_regression "
              "<config file> [-flag=value ...]", file=sys.stderr)
        return 2
    cfg = LogRegConfig.from_file(rest[0])
    mv.init()
    if cfg.mnist_dir:
        # BASELINE config 1 (ref example/run.sh): mnist_dir=<idx dir> uses
        # real MNIST files; mnist_dir=auto takes the best REAL digit data
        # available (idx via $MV_MNIST_DIR, else sklearn's bundled UCI
        # digits — io/mnist.load_real records the provenance)
        from multiverso_tpu.io import mnist
        if cfg.mnist_dir != "auto" and not mnist.available(cfg.mnist_dir):
            # explicit dir must exist — a typo'd path silently training on
            # different data would report a meaningless accuracy
            log.fatal("mnist_dir %s has no idx files (use mnist_dir=auto "
                      "for the best available real digit data)",
                      cfg.mnist_dir)
        data = mnist.load_real(
            None if cfg.mnist_dir == "auto" else cfg.mnist_dir)
        cfg.input_size = int(data["x_train"].shape[1])
        cfg.output_size = 10
        lr = LogReg(cfg)
        stats = lr.train_arrays(data["x_train"], data["y_train"])
        log.info("train done on %s: %s", data["provenance"], stats)
        log.info("test accuracy: %.4f",
                 lr.test_arrays(data["x_test"], data["y_test"]))
    else:
        if not cfg.train_file:
            log.fatal("config needs train_file=<path> (or mnist_dir=) — "
                      "nothing to train on")
        lr = LogReg(cfg)
        stats = lr.train_file()
        log.info("train done: %s", stats)
        if cfg.test_file:
            acc = lr.test_file()
            log.info("test accuracy: %.4f", acc)
    lr.save_model()
    mv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
