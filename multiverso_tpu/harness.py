"""Tier-2 integration battery: the reference ``Test/main.cpp`` dispatcher as a
runnable module.

The reference builds one binary whose argv[1] selects a test and runs it under
``mpirun -np N`` — the MPI world *is* the fixture (ref Test/main.cpp:497-518;
the Docker CI battery runs kv/array/net/ip/checkpoint/restore/allreduce at
np=4, ref deploy/docker/Dockerfile). Here the same battery runs as::

    python -m multiverso_tpu.harness <cmd> [-key=value ...]

with cmd in {kv, array, net, ip, matrix, checkpoint, restore, allreduce,
async, ftrl_sparse, dense_perf, sparse_perf, all}. ``-nprocs=N``
relaunches the chosen test as N
coordinated JAX processes on this host (the ``mpirun -np N`` analogue used by
tests/test_multiprocess.py); inside each process the battery is identical, so
single- and multi-process behavior are asserted by the same code.

Every test *asserts* its expected values (the reference printed-and-eyeballed
or had its exits commented out, Test/main.cpp:110-119) and prints one
``HARNESS PASS <cmd>`` line on success.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import List, Optional

import numpy as np

from multiverso_tpu.utils import config, log

config.define_int("nprocs", 1, "relaunch the battery as N coordinated "
                  "processes (mpirun -np N analogue)")
config.define_string("coordinator", "", "internal: coordinator address of a "
                     "relaunched battery process")
config.define_int("procid", -1, "internal: process id of a relaunched "
                  "battery process")
config.define_int("local_devices", 2, "virtual CPU devices per battery "
                  "process in -nprocs mode")
config.define_bool("cpu", False, "force the single-process battery onto a "
                   "virtual 8-device CPU mesh instead of the default "
                   "platform (use when the TPU tunnel is unavailable)")
config.define_int("rows", 100_000, "num_row for the perf tests (ref default "
                  "1000000, Test/main.cpp:357)")
config.define_int("iters", 3, "outer iterations for array/matrix tests")
config.define_string("checkpoint_dir", "/tmp/mv_harness_ckpt",
                     "where the checkpoint/restore battery writes")


def _init(**kw):
    import multiverso_tpu as mv
    mv.init(**kw)
    return mv


# --------------------------------------------------------------------------- #
# battery (each mirrors one Test/main.cpp entry)
# --------------------------------------------------------------------------- #
def test_kv() -> None:
    """ref TestKV (Test/main.cpp:31-83): get-miss is 0, add accumulates;
    multi-process: allreduce merges every worker's adds."""
    mv = _init()
    kv = mv.KVTable(name="harness_kv")
    assert kv.get([0])[0] == 0, "unwritten key must read 0"
    kv.add([0], [1])
    assert kv.get([0])[0] == 1
    merged = kv.allreduce()
    assert merged[0] == mv.size(), f"key 0 = {merged[0]} != size {mv.size()}"
    log.info("kv: key0=%s over %d processes", merged[0], mv.size())
    mv.shutdown()


def test_array() -> None:
    """ref TestArray (Test/main.cpp:85-124): sync mode, delta[i]=i, three adds
    per iter; after iter i the table holds 3*(i+1)*num_workers*delta."""
    mv = _init(sync=True)
    n = 500
    t = mv.create_table(mv.ArrayTableOption(n), name="harness_array")
    mv.barrier()
    delta = np.arange(n, dtype=np.float32)
    iters = config.get_flag("iters")
    for i in range(iters):
        for _ in range(3):
            t.add(delta)
        data = t.get()
        expect = delta * 3 * (i + 1) * mv.num_workers()
        np.testing.assert_allclose(data, expect, rtol=1e-6)
    log.info("array: %d iters verified (workers=%d)", iters, mv.num_workers())
    mv.shutdown()


def test_net() -> None:
    """ref TestNet (Test/main.cpp:126-200): raw transport echo. The TPU
    transport is XLA collectives over the mesh, so the echo is a broadcast
    from rank 0 + an all_gather identity check on every device."""
    mv = _init()
    from multiverso_tpu.parallel import collectives as coll

    zoo = mv.Zoo.get()
    n_shards = int(mv.mesh().shape[zoo.shard_axis()])
    chunk = 4
    msg = np.arange(chunk * n_shards, dtype=np.float32)
    # echo: scatter the message over the mesh, gather it back unchanged
    np.testing.assert_allclose(np.asarray(coll.all_gather(msg)), msg)
    # broadcast: every shard adopts shard 0's chunk
    np.testing.assert_allclose(np.asarray(coll.broadcast(msg)), msg[:chunk])
    # allreduce: chunks sum across shards
    np.testing.assert_allclose(np.asarray(coll.all_reduce(msg)),
                               msg.reshape(n_shards, chunk).sum(axis=0))
    log.info("net: gather/broadcast/allreduce echo over %d shards OK",
             n_shards)
    mv.shutdown()


def test_ip() -> None:
    """ref TestIP → net::GetLocalIPAddress, which the reference implements
    for Windows only (src/util/net_util.cpp:70-74 is CHECK(false) on Linux).
    Topology discovery here is the JAX runtime — and works everywhere."""
    import jax
    mv = _init()
    log.info("ip/topology: process %d/%d, %d local devices, mesh %s",
             jax.process_index(), jax.process_count(),
             jax.local_device_count(), dict(mv.mesh().shape))
    assert jax.process_count() >= 1
    mv.shutdown()


def test_matrix() -> None:
    """ref TestMatrix (Test/main.cpp:203-291): dense whole-table Add/Get plus
    row-batch Add/Get on rows {0,1,3,7}; after `count` rounds the expected
    value doubles on the row-batch rows. Also asserts the sparse stale-row
    protocol (ref matrix.cpp up_to_date bits) that TestMatrix exercises via
    is_sparse tables."""
    mv = _init(sync=True)
    num_row, num_col = 11, 64
    size = num_row * num_col
    t = mv.create_table(mv.MatrixTableOption(num_row, num_col),
                        name="harness_matrix")
    mv.barrier()
    v = [0, 1, 3, 7]
    delta = (np.arange(size, dtype=np.float32) + 1).reshape(num_row, num_col)
    w = mv.num_workers()
    iters = config.get_flag("iters")
    for count in range(1, iters + 1):
        t.add(delta)
        t.add_rows(v, delta[v])
        data = t.get()
        expect = delta * count * w
        expect[v] *= 2
        np.testing.assert_allclose(data, expect, rtol=1e-6)
        rows = t.get_rows(v)
        np.testing.assert_allclose(rows, expect[v], rtol=1e-6)

    # sparse stale-row protocol on the same shape
    st = mv.SparseMatrixTable(num_row, num_col, name="harness_sparse")
    all_rows = list(range(num_row))
    first = st.get_rows_sparse(all_rows, worker_id=0)
    np.testing.assert_allclose(first, 0.0)
    assert st.stale_fraction(all_rows, worker_id=0) == 0.0, \
        "everything fresh after a full pull"
    st.add_rows([2, 5], np.ones((2, num_col), np.float32))
    frac = st.stale_fraction(all_rows, worker_id=0)
    assert 0 < frac <= 2 / num_row + 1e-6, f"stale fraction {frac}"
    got = st.get_rows_sparse(all_rows, worker_id=0)
    np.testing.assert_allclose(got[2], w)
    np.testing.assert_allclose(got[5], w)
    log.info("matrix: %d rounds + sparse staleness verified (workers=%d)",
             iters, w)
    mv.shutdown()


def test_checkpoint(restore: bool = False) -> None:
    """ref TestCheckPoint (Test/main.cpp:292-330) — and the MV_LoadTable
    resume API the reference planned but never landed (:302-316 comments) is
    real here: `restore` reloads table + updater state and training continues.
    """
    import multiverso_tpu as mv_mod
    from multiverso_tpu import checkpoint

    mv = _init()
    num_row, num_col = 11, 10
    size = num_row * num_col
    t = mv.MatrixTable(num_row, num_col, name="harness_ckpt")
    mv.barrier()
    delta = np.arange(size, dtype=np.float32).reshape(num_row, num_col)
    ckpt_dir = config.get_flag("checkpoint_dir")
    w = mv.num_workers()
    if not restore:
        for _ in range(50):
            t.add(delta)
        checkpoint.save(ckpt_dir, tag="harness")
        np.testing.assert_allclose(t.get(), delta * 50 * w, rtol=1e-6)
        log.info("checkpoint: 50 adds stored to %s", ckpt_dir)
    else:
        n = checkpoint.restore(ckpt_dir, tag="harness")
        assert n >= 1, "no tables restored"
        np.testing.assert_allclose(t.get(), delta * 50 * w, rtol=1e-6)
        t.add(delta)  # resume: training continues on restored state
        np.testing.assert_allclose(t.get(), delta * (50 * w + w), rtol=1e-6)
        log.info("restore: state verified, training resumed")
    mv.shutdown()


def test_allreduce() -> None:
    """ref TestAllreduce (Test/main.cpp:331-339): -ma mode MV_Aggregate."""
    prev_ma = config.get_flag("ma")
    config.set_flag("ma", True)
    try:
        mv = _init()
        a = np.ones(1, dtype=np.float32)
        mv.aggregate(a)
        assert a[0] == mv.size(), f"aggregate: {a[0]} != {mv.size()}"
        log.info("allreduce: a = %s (size %d)", a[0], mv.size())
        mv.shutdown()
    finally:
        config.set_flag("ma", prev_ma)  # don't poison later battery entries


def _perf(sparse: bool) -> None:
    """ref TestmatrixPerformance (Test/main.cpp:340-452): get-all, add a
    growing fraction of rows, get-all again, verify, Dashboard dump."""
    from multiverso_tpu.utils.dashboard import Dashboard

    mv = _init()
    num_row, num_col = config.get_flag("rows"), 50
    wid, wnum = mv.worker_id(), mv.num_workers()
    delta = np.arange(num_row * num_col,
                      dtype=np.float32).reshape(num_row, num_col)
    for percent in range(0, 10, 3):
        cls = mv.SparseMatrixTable if sparse else mv.MatrixTable
        t = cls(num_row, num_col, name=f"perf_{percent}")
        mv.barrier()

        t0 = time.perf_counter()
        data = (t.get_rows_sparse(range(num_row), worker_id=wid)
                if sparse else t.get())
        log.info("%.3fs: get all rows first time (worker %d)",
                 time.perf_counter() - t0, wid)

        # ref splits rows across workers (i % worker_num == worker_id);
        # collective add_rows needs identical id sets per process, so every
        # worker pushes the full fraction and the sum scales by num_workers
        rows = [i for i in range(num_row) if i % 10 <= percent]
        if rows:
            t.add_rows(rows, delta[rows])
        mv.barrier()

        t0 = time.perf_counter()
        data = (t.get_rows_sparse(range(num_row), worker_id=wid)
                if sparse else t.get())
        log.info("%.3fs: get all rows after adding %d0%% (worker %d)",
                 time.perf_counter() - t0, percent + 1, wid)

        touched = np.zeros(num_row, bool)
        touched[rows] = True
        np.testing.assert_allclose(data[touched], delta[touched] * wnum,
                                   rtol=1e-6)
        np.testing.assert_allclose(data[~touched], 0.0)
    Dashboard.display()
    mv.shutdown()


def test_async() -> None:
    """Uncoordinated async-PS plane (no reference analogue in Test/main.cpp
    — the reference could only exercise async through full apps; here the
    plane is its own battery entry): per-worker disjoint row sets at
    per-worker rates over PSService shards, plus hash-sharded KV."""
    mv = _init()
    rank, world = mv.rank(), mv.size()
    t = mv.AsyncMatrixTable(8 * max(world, 1), 4, name="harness_async")
    kv = mv.AsyncKVTable(name="harness_async_kv")
    my_rows = np.arange(8) * max(world, 1) + rank
    for i in range(rank + 1):
        t.add_rows(my_rows, np.ones((8, 4), np.float32))
        kv.add([rank], [1.0])
    t.flush()
    mv.barrier()   # determinism fence for the asserts, not the plane
    got = t.get_rows(np.arange(8 * max(world, 1)))
    total = float(got.sum())
    expect = sum((r + 1) for r in range(world)) * 8 * 4
    assert total == expect, (total, expect)
    counts = kv.get()
    assert counts == {r: float(r + 1) for r in range(world)}, counts
    log.info("async: %d workers, row mass %.0f, kv %s", world, total, counts)
    mv.shutdown()


def test_ftrl_sparse() -> None:
    """Hash-sharded sparse keys + FTRL z/n on the uncoordinated plane (ref
    Applications/LogisticRegression/src/util/{sparse_table,
    ftrl_sparse_table}.h; no Test/main.cpp analogue — the reference never
    exercised its sparse tables outside the LR app)."""
    mv = _init()
    rank, world = mv.rank(), mv.size()
    from multiverso_tpu.ps.tables import AsyncSparseKVTable
    t = AsyncSparseKVTable(4, updater="ftrl", name="harness_ftrl")
    keys = np.array([7, 1_000_003, 1_000 + rank])  # shared + per-rank keys
    for _ in range(10):
        t.add_rows(keys, np.full((3, 4), 0.5, np.float32))
    t.flush()
    mv.barrier()   # determinism fence for the asserts, not the plane
    w = t.get_rows([7, 1_000_003])
    # steady +g gradients push the FTRL weight negative once |z| > lambda1
    assert np.all(w < 0) and np.all(np.isfinite(w)), w
    per_rank = t.get_rows([1_000 + r for r in range(world)])
    assert np.all(per_rank < 0), per_rank
    fresh = t.get_rows([555])
    np.testing.assert_allclose(fresh, 0.0)   # untouched key = empty state
    log.info("ftrl_sparse: %d workers, shared w[0]=%.4f", world,
             float(w[0, 0]))
    mv.shutdown()


def test_readers() -> None:
    """Weighted + binary-sparse reader variants end-to-end (ref
    reader.h:96-114 WeightedSampleReader, :118-146 BSparseSampleReader):
    every rank writes its own weighted-text and binary shard of the same
    synthetic samples, reads both back through SampleReader, asserts the
    parsed batches agree bit-for-bit, and pushes its sample mass to a
    shared async KV table so the asserts span ranks."""
    import tempfile

    from multiverso_tpu.io.sample_reader import (SampleReader,
                                                 write_bsparse_sample)
    mv = _init()
    rank, world = mv.rank(), mv.size()
    rng = np.random.default_rng(100 + rank)
    dim, n = 32, 12
    samples = [(int(rng.integers(0, 2)),
                np.unique(rng.integers(0, dim, 5)),
                float(rng.uniform(0.5, 2.0)))
               for _ in range(n)]
    with tempfile.TemporaryDirectory(prefix="mv_readers_") as d:
        wpath, bpath = f"{d}/w_{rank}.txt", f"{d}/b_{rank}.bin"
        with open(wpath, "w") as f:
            for label, keys, w in samples:
                f.write(f"{label}:{w} "
                        + " ".join(f"{k}:1.0" for k in keys) + "\n")
        with open(bpath, "wb") as f:
            for label, keys, w in samples:
                write_bsparse_sample(f, label, keys, w)
        wbatches = list(SampleReader(wpath, dim, 4, fmt="weight"))
        bbatches = list(SampleReader(bpath, dim, 4, fmt="bsparse"))
    assert len(wbatches) == len(bbatches) == 3, len(wbatches)
    mass = 0.0
    for (wx, wy, wk), (bx, by, bk) in zip(wbatches, bbatches):
        np.testing.assert_allclose(wx, bx)     # weight folded into values
        np.testing.assert_array_equal(wy, by)
        np.testing.assert_array_equal(wk, bk)  # same active-key sets
        mass += float(wx.sum())
    kv = mv.AsyncKVTable(name="harness_readers")
    kv.add([rank], [round(mass, 3)])
    mv.barrier()
    counts = kv.get()
    assert set(counts) == set(range(world)) and all(
        v > 0 for v in counts.values()), counts
    log.info("readers: %d ranks, weighted==bsparse, mass %s", world, counts)
    mv.shutdown()


def test_dense_perf() -> None:
    _perf(sparse=False)


def test_sparse_perf() -> None:
    _perf(sparse=True)


_TESTS = {
    "kv": test_kv,
    "array": test_array,
    "net": test_net,
    "ip": test_ip,
    "matrix": test_matrix,
    "checkpoint": lambda: test_checkpoint(False),
    "restore": lambda: test_checkpoint(True),
    "allreduce": test_allreduce,
    "async": test_async,
    "ftrl_sparse": test_ftrl_sparse,
    "readers": test_readers,
    "dense_perf": test_dense_perf,
    "sparse_perf": test_sparse_perf,
}
# the Docker CI battery order (deploy/docker/Dockerfile) + the async plane
_ALL = ["kv", "array", "net", "ip", "matrix", "checkpoint", "restore",
        "allreduce", "async", "ftrl_sparse", "readers"]


def _spawn_cluster(cmd: str, nprocs: int, extra: List[str]) -> int:
    """Relaunch this harness as N coordinated processes (mpirun analogue)."""
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coordinator = f"127.0.0.1:{s.getsockname()[1]}"
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "multiverso_tpu.harness", cmd,
             f"-coordinator={coordinator}", f"-nprocs={nprocs}",
             f"-procid={pid}", *extra],
            env=env)
        for pid in range(nprocs)
    ]
    rc = 0
    for pid, p in enumerate(procs):
        code = p.wait()
        if code == 77 and rc == 0:
            rc = 77  # child couldn't bring up jax.distributed: skip, not fail
        elif code not in (0, 77):
            log.error("battery process %d failed (rc=%d)", pid, code)
            rc = 1
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    # A site hook may have force-registered an accelerator plugin; restore
    # the JAX_PLATFORMS/XLA_FLAGS intent (the battery is meant to run on the
    # virtual CPU mesh unless explicitly pointed at hardware).
    from multiverso_tpu.utils.platform import apply_platform_env
    apply_platform_env()
    argv = list(sys.argv[1:] if argv is None else argv)
    # accept the natural bare form of the boolean flag
    argv = ["-cpu=true" if a == "-cpu" else a for a in argv]
    cmds = [a for a in argv if not a.startswith("-")]
    flags = [a for a in argv if a.startswith("-")]

    def maybe_force_cpu() -> None:
        if config.get_flag("cpu"):
            from multiverso_tpu.utils.platform import force_cpu_mesh
            if not force_cpu_mesh(8):
                log.error("-cpu requested but a JAX backend is already "
                          "initialized; battery would run on the default "
                          "platform")
                raise SystemExit(3)

    if not cmds:
        # ref: argc==1 -> bare MV_Init/MV_ShutDown smoke (Test/main.cpp:500)
        config.parse_cmd_flags(["prog", *flags])
        maybe_force_cpu()
        mv = _init()
        mv.shutdown()
        print("HARNESS PASS init")
        return 0
    cmd = cmds[0]
    config.parse_cmd_flags(["prog", *flags])

    nprocs = config.get_flag("nprocs")
    procid = config.get_flag("procid")
    if nprocs > 1 and procid < 0:
        names = _ALL if cmd == "all" else cmds
        for name in names:
            rc = _spawn_cluster(name, nprocs, [f for f in flags
                                               if not f.startswith("-nprocs")])
            if rc == 77:
                print(f"HARNESS SKIP {name} (jax.distributed unavailable)")
                return 77
            if rc:
                return rc
            print(f"HARNESS PASS {name} (nprocs={nprocs})")
        return 0

    if procid >= 0:  # child of _spawn_cluster
        import jax

        from multiverso_tpu.utils.platform import (enable_cpu_collectives,
                                                   force_cpu_mesh)
        force_cpu_mesh(config.get_flag("local_devices"))
        enable_cpu_collectives()   # gloo: cross-process CPU computations
        try:
            jax.distributed.initialize(
                coordinator_address=config.get_flag("coordinator"),
                num_processes=nprocs, process_id=procid)
        except Exception as e:  # environment without jax.distributed
            log.error("jax.distributed unavailable: %s", e)
            return 77  # conventional skip code, consumed by _spawn_cluster

    if procid < 0:
        maybe_force_cpu()

    names = _ALL if cmd == "all" else cmds
    for name in names:
        if name not in _TESTS:
            log.error("unknown battery test %r (have: %s)", name,
                      " ".join(sorted(_TESTS)))
            return 2
        _TESTS[name]()
        if procid <= 0:
            print(f"HARNESS PASS {name}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
