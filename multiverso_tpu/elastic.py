"""Failure detection + elastic resume.

The reference has essentially none of this (SURVEY §5): static MPI/ZMQ
membership, no heartbeats, a `backup_worker_ratio` straggler flag that is
declared but dead (ref src/server.cpp:21), and a planned-but-abandoned
`MV_LoadTable` resume API (ref Test/main.cpp:302-316 comments). Recovery is
"checkpoint files only". Here that story is made real and first-class:

* **Heartbeat** — each process writes a small JSON beacon (rank, step,
  timestamp) to shared storage on a background thread; any process can list
  ``peers()``, detect ``failed()`` ranks by staleness, and identify
  ``stragglers()`` by step lag (the semantics `backup_worker_ratio` hinted
  at, actually implemented).
* **ElasticLoop** — wraps a training loop with periodic full-state
  checkpoints (checkpoint.py walks every registered table, data + updater
  state) and resume-from-latest on restart. A re-launched job calls
  ``resume()`` and continues from the last completed checkpoint step.

TPU note: inside a pod slice, worker liveness is the runtime's job (an ICI
collective fails fast if a chip drops); these beacons cover the *host/DCN*
plane — multi-process jobs, preemptible hosts — where the reference's MPI
world would simply hang.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Dict, List, Optional

from multiverso_tpu import checkpoint
from multiverso_tpu.utils import log
from multiverso_tpu.zoo import Zoo


class Heartbeat:
    """Periodic liveness beacon on shared storage (one file per rank)."""

    def __init__(self, directory: str, interval: float = 5.0,
                 rank: Optional[int] = None, addr: Optional[str] = None):
        """``addr`` stamps every beacon with this incarnation's
        identity (the rank's published PS address): a respawned rank's
        fresh beacon then clears its predecessor's tombstone by
        IDENTITY, not just by timestamp — see :func:`failed`."""
        self.directory = directory
        self.interval = interval
        self.rank = Zoo.get().rank() if rank is None else rank
        self.addr = addr
        self._step = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(directory, exist_ok=True)

    def set_addr(self, addr: Optional[str]) -> None:
        """Late-bind the incarnation address (a service constructed
        after the heartbeat started)."""
        self.addr = addr

    @property
    def path(self) -> str:
        return os.path.join(self.directory, f"heartbeat.{self.rank}.json")

    def set_step(self, step: int) -> None:
        self._step = int(step)

    def beat(self) -> None:
        """Write one beacon now (atomic rename so readers never see a
        torn write). The beacon carries ``last_health`` — the local
        watchdog's latest verdict (telemetry/watchdog.py) — whenever the
        watchdog has run: a beacon that keeps arriving with
        ``status="stuck"`` is ALIVE BUT WEDGED, which :func:`failed`'s
        staleness test alone can never distinguish from healthy."""
        entry = {"rank": self.rank, "step": self._step,
                 "ts": time.time()}
        if self.addr:
            entry["addr"] = self.addr
        try:
            from multiverso_tpu.telemetry import watchdog
            v = watchdog.last_verdict()
            if v.get("checked"):
                entry["last_health"] = {
                    "status": v["status"],
                    "oldest_inflight_s": v["oldest_inflight_s"],
                    "inflight": v["inflight"]}
        except Exception:   # noqa: BLE001 — liveness must not depend on
            pass            # the health plane
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(entry, f)
        os.replace(tmp, self.path)

    def start(self) -> "Heartbeat":
        self.beat()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"mv-heartbeat-{self.rank}")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.beat()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 1)
            self._thread = None


def peers(directory: str) -> Dict[int, Dict]:
    """All beacons currently present: {rank: {rank, step, ts}}."""
    out: Dict[int, Dict] = {}
    if not os.path.isdir(directory):
        return out
    for name in os.listdir(directory):
        if not (name.startswith("heartbeat.") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                raw = json.load(f)
            entry = {"rank": int(raw["rank"]), "step": int(raw["step"]),
                     "ts": float(raw["ts"])}
            if isinstance(raw.get("last_health"), dict):
                entry["last_health"] = raw["last_health"]
            if isinstance(raw.get("addr"), str):
                entry["addr"] = raw["addr"]
            out[entry["rank"]] = entry
        except (ValueError, KeyError, TypeError, json.JSONDecodeError,
                OSError):
            continue  # torn/foreign/old-schema file: not a liveness verdict
    return out


def mark_failed(directory: str, rank: int,
                addr: Optional[str] = None) -> None:
    """Tombstone ``rank`` as failed NOW — the PS plane's socket-death
    signal feeding the heartbeat view (see :func:`bind_ps`), so a peer
    death is visible immediately instead of after a heartbeat timeout.

    The tombstone records the rank's LAST-SEEN beacon timestamp (the
    subject's own clock) and the dead INCARNATION's address (``addr``,
    defaulting to the last beacon's). It clears as soon as a beacon
    newer than that timestamp appears — OR a beacon carrying a
    DIFFERENT address: a respawned rank is a fresh incarnation whatever
    its clock says, and its beacons must never be shadowed by its
    predecessor's tombstone (the predecessor may have kept beating
    while wedged, pushing the recorded timestamp past anything the
    replacement will ever write). Comparing subject-clock to
    subject-clock keeps the timestamp rule immune to cross-host
    wall-clock skew."""
    os.makedirs(directory, exist_ok=True)
    beacon = peers(directory).get(int(rank))
    seen_ts = float(beacon["ts"]) if beacon else float("-inf")
    if addr is None and beacon is not None:
        addr = beacon.get("addr")
    path = os.path.join(directory, f"failed.{int(rank)}.json")
    tmp = path + ".tmp"
    entry: Dict = {"rank": int(rank), "ts": time.time(),
                   "beacon_ts": seen_ts}
    if addr:
        entry["addr"] = addr
    with open(tmp, "w") as f:
        json.dump(entry, f)
    os.replace(tmp, path)


def _tombstones(directory: str) -> Dict[int, Dict]:
    """rank -> {"ts": last-seen beacon ts (subject clock), "addr":
    tombstoned incarnation address or None} at tombstone time."""
    out: Dict[int, Dict] = {}
    if not os.path.isdir(directory):
        return out
    for name in os.listdir(directory):
        if not (name.startswith("failed.") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                entry = json.load(f)
            out[int(entry["rank"])] = {
                "ts": float(entry.get("beacon_ts", entry["ts"])),
                "addr": entry.get("addr")}
        except (ValueError, KeyError, TypeError, json.JSONDecodeError,
                OSError):
            continue
    return out


def failed(directory: str, timeout: float = 30.0,
           beacons: Optional[Dict[int, Dict]] = None) -> List[int]:
    """Ranks considered dead: beacon older than ``timeout`` seconds, OR
    tombstoned by a PS-plane death (:func:`mark_failed`) with no
    exonerating beacon. A beacon exonerates its rank when it is newer
    than the one the tombstone recorded (both timestamps the subject's
    own clock — cross-host skew cannot pin a rejoined rank) or when it
    carries a DIFFERENT incarnation address than the tombstone: a
    respawned rank's fresh identity clears its predecessor's tombstone
    even if the predecessor's last (wedged) beacons out-stamp it.
    ``beacons`` lets a caller that already listed the directory
    (:func:`health`) skip the second scan of shared storage."""
    now = time.time()
    if beacons is None:
        beacons = peers(directory)
    out = {r for r, e in beacons.items() if now - float(e["ts"]) > timeout}
    for rank, tomb in _tombstones(directory).items():
        beacon = beacons.get(rank)
        if beacon is None:
            out.add(rank)
            continue
        fresh_incarnation = (tomb.get("addr") is not None
                             and beacon.get("addr") is not None
                             and beacon["addr"] != tomb["addr"])
        if not fresh_incarnation and float(beacon["ts"]) <= tomb["ts"]:
            out.add(rank)
    return sorted(out)


def health(directory: str, timeout: float = 30.0) -> Dict[int, str]:
    """Per-rank liveness verdict: ``"dead"`` (stale beacon or PS-death
    tombstone — exactly :func:`failed`'s set), ``"stuck"`` (beacon still
    FRESH but its ``last_health`` watchdog verdict says the PS plane is
    wedged), else ``"ok"``. The distinction :func:`failed` alone cannot
    make: a wedged rank heartbeats forever, so a supervisor keying
    restarts off staleness would never touch it, while one keying off
    this verdict can (and a flight-recorder dump is already on its disk
    — the watchdog trip that set the verdict wrote it)."""
    beacons = peers(directory)
    dead = set(failed(directory, timeout, beacons=beacons))
    out: Dict[int, str] = {r: "dead" for r in dead}
    for r, e in beacons.items():
        if r in dead:
            continue
        lh = e.get("last_health") or {}
        out[r] = "stuck" if lh.get("status") == "stuck" else "ok"
    return out


def bind_ps(directory: str, ctx=None) -> None:
    """Feed PS-plane peer deaths into this heartbeat directory: every
    socket-death the service observes writes a tombstone that
    :func:`failed` reports immediately. The two failure systems — file
    heartbeats (host liveness) and socket deaths (connection liveness) —
    stop being disjoint (VERDICT r2 weak #5)."""
    if ctx is None:
        from multiverso_tpu.ps.service import default_context
        ctx = default_context()
    ctx.service.add_death_hook(lambda rank: mark_failed(directory, rank))


def stragglers(directory: str, lag: int = 10) -> List[int]:
    """Ranks more than ``lag`` steps behind the front-runner — the
    working version of the reference's dead backup_worker_ratio knob."""
    entries = peers(directory)
    if not entries:
        return []
    front = max(int(e["step"]) for e in entries.values())
    return sorted(r for r, e in entries.items()
                  if front - int(e["step"]) > lag)


class ElasticLoop:
    """Checkpoint-every-N + resume-from-latest around any training loop.

    ::

        loop = ElasticLoop("/ckpt/run7", every=100)
        start = loop.resume()            # 0 on a fresh run
        for step in range(start, total):
            ...train...
            loop.completed(step)         # checkpoints at step % every == 0
        loop.stop()
    """

    TAG = "step_{step:09d}"

    def __init__(self, directory: str, every: int = 100,
                 keep: int = 2, heartbeat_interval: float = 5.0,
                 backend: str = "stream", block: bool = True):
        if backend not in checkpoint.BACKENDS:
            raise ValueError(f"unknown checkpoint backend {backend!r}; "
                             f"choose from {checkpoint.BACKENDS}")
        if not block and backend != "orbax":
            raise ValueError("block=False needs backend='orbax'")
        self.directory = directory
        self.every = max(1, int(every))
        self.keep = max(1, int(keep))
        self.backend = backend
        self.block = block
        self.heartbeat = Heartbeat(
            os.path.join(directory, "heartbeats"),
            interval=heartbeat_interval).start()

    def resume(self) -> int:
        """Restore the newest valid checkpoint; return the step to resume
        FROM (one past the checkpointed step; 0 if none)."""
        checkpoint.wait_pending()
        tag = checkpoint.latest(self.directory)
        if tag is None or not tag.startswith("step_"):
            return 0
        checkpoint.restore(self.directory, tag)
        step = int(tag.split("_", 1)[1])
        self.heartbeat.set_step(step)
        log.info("elastic resume from %s (next step %d)", tag, step + 1)
        return step + 1

    def completed(self, step: int) -> bool:
        """Record progress; checkpoint when due. Returns True if a
        checkpoint was written."""
        self.heartbeat.set_step(step)
        if (step + 1) % self.every:
            return False
        checkpoint.save(self.directory, self.TAG.format(step=step),
                        backend=self.backend, block=self.block)
        self._prune()
        return True

    def _prune(self) -> None:
        if Zoo.get().rank() != 0:
            return
        tags = sorted(t for t in os.listdir(self.directory)
                      if t.startswith("step_") and
                      os.path.exists(os.path.join(self.directory, t,
                                                  "manifest.json")))
        for tag in tags[: -self.keep]:
            # orbax checkpoints nest directories, so remove recursively
            shutil.rmtree(os.path.join(self.directory, tag))

    def stop(self) -> None:
        checkpoint.wait_pending()  # finalize an in-flight async save
        self.heartbeat.stop()
