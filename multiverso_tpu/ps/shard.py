"""RowShard: the owner-side storage of an async table's row range.

TPU-native equivalent of the reference ServerTable shard
(ref: src/server.cpp:36-58 ProcessAdd/ProcessGet dispatching into the
table's shard; src/table/matrix_table.cpp:98-141 server-side row storage +
Updater::Update over the received rows). The shard lives as a device array
on the owner process's local accelerator; Adds run the table's updater as a
jitted, donated program (gather touched rows -> updater -> scatter), so the
optimizer math happens on the TPU even though requests arrive over TCP.

Shape discipline: row batches are bucketed to the next power of two and
padded with a scratch row (same trick as the sync MatrixTable,
tables/matrix_table.py) so there is one compiled program per bucket size.

Thread-safety: requests arrive on per-connection service threads; a lock
serializes state transitions (JAX arrays are immutable, so readers always
see a consistent snapshot; the lock orders the donated updates).

Read path (off-lock snapshot serving): gets do NOT hold the lock across
the row gather, the device->host transfer, or the reply wire-encode.
A reader briefly takes the lock to PIN the current data epoch (a
refcounted handle on the buffer object, :meth:`RowShard._pin_data`) and
then computes outside it. The apply path donates its input buffer only
when no reader pins the current epoch; while pinned it updates into a
FRESH buffer instead (non-donating jit / numpy copy-on-write), so the
pinned snapshot stays valid and the last releasing reader simply drops
the retired buffer to the GC. Applies therefore never wait on a reader,
and a multi-hundred-ms gather/encode no longer serializes the shard —
the read/write symmetry the reference's one-Server-actor-thread design
never had. Shards registered with the native C++ server keep the locked
path: C++ holds the raw buffer pointer, so the buffer must never be
swapped (the punt path already serializes on the native shard mutex).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu.ps import service as svc
from multiverso_tpu.ps import wire
from multiverso_tpu.table import _ceil_to
from multiverso_tpu.telemetry import flightrec as _flight
from multiverso_tpu.telemetry import hotkeys as _hotkeys
from multiverso_tpu.telemetry import memstats as _memstats
from multiverso_tpu.telemetry import tenants as _tenants
from multiverso_tpu.tables.matrix_table import _bucket_size
from multiverso_tpu.telemetry import trace as _trace
from multiverso_tpu.updaters import AddOption, Updater
from multiverso_tpu.utils import config as _config
from multiverso_tpu.utils.dashboard import Dashboard

# updater classification (see updaters.STATELESS_LINEAR /
# OPT_INSENSITIVE): linear stateless updaters apply with in-place numpy
# on host-backed shards (~20 us vs ~60 us jit dispatch for a 128-row
# batch); opt-insensitive ones coalesce across senders.
from multiverso_tpu.updaters import (OPT_INSENSITIVE as _OPT_INSENSITIVE,
                                     ROW_LOCAL_STATE as _ROW_LOCAL_STATE,
                                     STATELESS_LINEAR as _LINEAR_SIGN)


class _SeqChannel:
    """Per-client applied-sequence tracker for exactly-once replay
    (docs/FAILOVER.md): ``floor`` means every sequence at or below it
    has applied; ``above`` is the sparse set of applied sequences past
    a gap. The gap shape exists because a frame re-sent across a
    connection change can arrive after a later frame sent on the fresh
    conn — a plain high-water mark would then dedupe the LATE frame as
    already-applied and lose it. Memory is bounded by the client's
    in-flight pipeline depth (the set drains into the floor as gaps
    close)."""

    __slots__ = ("floor", "above", "failed")

    # frames that applied with per-sub-op failures, kept so a DUP ack
    # can echo the same "failed" indices (a replayed batch whose first
    # ack was lost must not resolve its failed sub-ops as successes);
    # bounded — failures are rare and only the recent replay window
    # can ever be re-asked
    _MAX_FAILED = 64

    def __init__(self, floor: int = -1, above=(), failed=None):
        self.floor = int(floor)
        self.above = set(int(s) for s in above)
        self.failed: Dict[int, Dict] = {
            int(k): v for k, v in (failed or {}).items()}

    def seen(self, seq: int) -> bool:
        return seq <= self.floor or seq in self.above

    def note_failed(self, seq: int, rmeta: Dict) -> None:
        self.failed[int(seq)] = {"failed": list(rmeta.get("failed", ())),
                                 "error": rmeta.get("error", "")}
        while len(self.failed) > self._MAX_FAILED:
            del self.failed[min(self.failed)]

    @staticmethod
    def _max_above() -> int:
        """Gap-set bound: a client never has more frames outstanding
        than its retention cap (flag ``ps_replay_max_frames``), so a
        set larger than that means some sequence was permanently
        abandoned (the client dropped its frame after exhausting
        ``ps_replay_timeout`` — logged loudly there) and the gap will
        never fill. Floored at the flag's default so a tiny/zero knob
        can never make live out-of-order pipelines jump the floor."""
        try:
            return max(int(_config.get_flag("ps_replay_max_frames")),
                       4096)
        except Exception:   # noqa: BLE001 — flag registry unavailable
            return 4096     # (standalone channel use in tests/tools)

    def commit(self, seq: int) -> None:
        if seq == self.floor + 1:
            self.floor += 1
            while self.floor + 1 in self.above:
                self.floor += 1
                self.above.discard(self.floor)
        elif seq > self.floor:
            self.above.add(seq)
            if len(self.above) > self._max_above():
                # jump past the abandoned gap instead of growing the
                # set (and every checkpoint's replay block) forever
                self.floor = min(self.above) - 1
                while self.floor + 1 in self.above:
                    self.floor += 1
                    self.above.discard(self.floor)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"floor": self.floor,
                               "above": sorted(self.above)}
        if self.failed:
            out["failed"] = {str(k): v for k, v in self.failed.items()}
        return out

    @classmethod
    def from_dict(cls, d: Dict) -> "_SeqChannel":
        return cls(d.get("floor", -1), d.get("above", ()),
                   d.get("failed"))


class _DataPin:
    """A pinned read epoch of a shard's data buffer: holds the buffer
    object alive (plain Python reference) and marks it so the apply path
    neither donates nor mutates it in place while any reader computes on
    it. Release via :meth:`RowShard._release_data` — dropping the last
    pin of a retired epoch frees the buffer through ordinary GC."""

    __slots__ = ("data", "version")

    def __init__(self, data, version: int):
        self.data, self.version = data, version


class _PendingAdd:
    """One queued row-add awaiting the shard's applier (coalescing path).
    ``trace`` is the request's client-minted trace ID (wire meta "tr"),
    echoed into the apply-wave spans so a client enqueue span and the
    shard apply span stitch by ID; None = untraced (the default)."""

    __slots__ = ("local", "vals", "opt", "event", "error", "trace")

    def __init__(self, local: np.ndarray, vals: np.ndarray, opt: AddOption,
                 trace: Optional[int] = None):
        self.local, self.vals, self.opt = local, vals, opt
        self.event = threading.Event()
        self.error: Optional[Exception] = None
        self.trace = trace


class RowShard:
    """Rows ``[lo, hi)`` of a logical ``(num_row, num_col)`` table."""

    def __init__(self, lo: int, hi: int, num_col: int, dtype,
                 updater: Updater, name: str,
                 init: Optional[np.ndarray] = None,
                 seed: Optional[int] = None, init_scale: float = 0.0,
                 num_workers: int = 0):
        """``num_workers > 0`` enables per-worker dirty-bit tracking for the
        sparse stale-row protocol (ref src/table/matrix.cpp:432-572 — the
        reference's ASYNC server kept up_to_date_[worker][row] bits; a
        sparse Get returns only rows stale for the asking worker and an Add
        marks its rows stale for everyone). Bits live host-side on the
        owner: they are control metadata consulted per request, not tensor
        math."""
        self.lo, self.hi = int(lo), int(hi)
        self.n = self.hi - self.lo
        self.num_col = int(num_col)
        self.name = name
        self.dtype = jnp.dtype(dtype)
        self.updater = updater
        # mesh-stacked group membership (ps/spmd.py, flag
        # ps_spmd_stack): when a plane adopts this shard, its storage
        # lives as one lane of the group's (S, R, C) stacked device
        # array and the _data/_ustate properties below serve lazily
        # materialized per-epoch slab views; None = classic standalone
        # storage. Set/cleared by MeshStack.admit/evict under this
        # shard's lock.
        self._plane = None
        self._plane_slot: Optional[int] = None
        self._view_cache = None
        self._view_epoch = -1
        self._ustate_view_cache = None
        self._mem_state_bytes = 0
        # shard this process's rows over its LOCAL devices: on a real
        # multi-host TPU every host owns several chips, and its row range
        # should live (and its updater run) across all of them — the
        # process-level partition (ps/tables.py) composes with this
        # device-level one. Rows pad to a device multiple (>= +1 scratch).
        # Tiny shards stay single-device: GSPMD partitioning would cost
        # more (compile + per-op overhead) than it buys below ~1 MB
        # (ps_local_shard_min_mb).
        local = jax.local_devices()
        min_bytes = _config.get_flag("ps_local_shard_min_mb") * 1e6
        self._local_sharding = None
        if (len(local) > 1
                and self.n * self.num_col * self.dtype.itemsize
                >= min_bytes):
            from jax.sharding import Mesh, NamedSharding, PartitionSpec
            padded_rows = _ceil_to(self.n + 1, len(local))
            mesh = Mesh(np.asarray(local), ("rows",))
            self._local_sharding = NamedSharding(
                mesh, PartitionSpec("rows", None))
        else:
            padded_rows = self.n + 1
        self._padded = (padded_rows, self.num_col)
        host = np.zeros(self._padded, self.dtype)
        if init is not None:
            host[: self.n] = np.asarray(init, self.dtype)
        elif seed is not None and init_scale != 0.0:
            # random init of exactly this shard's rows, seeded by (seed, lo)
            # so the global init is deterministic for a given partition
            # (ref src/table/matrix_table.cpp:372-384 server-side init)
            rng = np.random.default_rng([seed, self.lo])
            host[: self.n] = rng.uniform(
                -init_scale, init_scale, (self.n, self.num_col)
            ).astype(self.dtype)
        # host-backed single-device shards (CPU backend: tests, loopback
        # serving, CPU parameter hosts) answer reads with numpy straight
        # off the zero-copy buffer view — a 128-row gather costs ~10 us
        # vs ~40 us XLA dispatch, and the view is safe even across
        # donation (the buffer protocol export pins the XLA buffer)
        self._host_serve = (self._local_sharding is None
                            and jax.default_backend() == "cpu")
        # ...and when the updater is a stateless signed accumulate, the
        # shard stores plain numpy and applies adds in place — no XLA in
        # the loop at all (the reference server was exactly this: a C++
        # array += over received rows, src/table/matrix_table.cpp:98-141)
        self._np_mode = (self._host_serve
                         and type(updater) in _LINEAR_SIGN)
        self._data = self._place_rows(host)
        self._ustate = updater.init_state(self._padded, self.dtype)
        if self._local_sharding is not None:
            self._ustate = jax.tree.map(self._place_state_local,
                                        self._ustate)
        # RLock: HashShard wraps handle() in the same lock to make its
        # key->slot translation atomic with the update it guards
        self._lock = threading.RLock()
        self._jit: Dict[Any, Any] = {}
        # request-coalescing apply queue (flag ps_coalesce): adds arriving
        # on concurrent connection threads enqueue here; whichever thread
        # finds the queue idle becomes the applier and drains it, merging
        # everything queued meanwhile into one batched update. Self-
        # clocking: at low load each add applies immediately (no added
        # latency), under contention batch size grows with the backlog.
        self._addq: List[_PendingAdd] = []
        self._addq_lock = threading.Lock()
        self._addq_draining = False
        # observability: adds received vs. jitted updates actually run —
        # the coalescing ratio the bench asserts on. Python-path counters;
        # the stat_adds/stat_applies properties add the native server's
        # counters when the shard is natively registered.
        self._stat_adds = 0
        self._stat_applies = 0
        # first-class server-side stats (MSG_STATS / exporter):
        # _version counts applied mutations (the owner-side analogue of
        # the client get-cache version in table.py); _wave_ops is the
        # merged-ops-per-apply distribution in power-of-two buckets
        # (batch waves AND queue-coalesce groups — the realized server-
        # side batching the mean hides). Both mutate under self._lock.
        self._version = 0
        self._wave_ops: Dict[int, int] = {}
        self._wave_max = 0
        # off-lock read epochs: _cur_pins counts readers pinning _pin_buf
        # (identity-checked against the live _data, so a buffer swap
        # implicitly retires the count — no per-site bookkeeping). The
        # counters feed stats(): cow_applies = applies that had to copy /
        # skip donation because a reader held the epoch; served gets and
        # streamed chunks measure the read plane.
        self._pin_buf: Optional[Any] = None
        self._cur_pins = 0
        self._stat_cow = 0
        self._stat_gets = 0
        self._stat_chunks = 0
        # replica snapshot pulls served (MSG_SNAPSHOT; serving plane) —
        # counted apart from gets: a full-table replica pull must not
        # read as row-get traffic in rates/skew, and its ids never feed
        # the hot-key sketch (a periodic full sweep would drown the
        # workload's zipf signal the sketch exists to surface)
        self._stat_snapshots = 0
        self._stat_snapshot_unchanged = 0
        # wire-traffic byte counters (stats()["get_bytes"/"add_bytes"]):
        # the cluster aggregator derives wire bytes/s from their deltas.
        # Benign-race increments, same tolerance as _stat_gets above.
        self._stat_get_bytes = 0
        self._stat_add_bytes = 0
        # heavy-hitter sketch over served GLOBAL row ids (telemetry/
        # hotkeys.py): always-on like the flight recorder, bounded
        # memory, O(1) per recorded op. Feeds stats()["hotkeys"] and the
        # aggregator's cluster top-K + cache-hit-if-cached curve — the
        # sizing input for a device-resident hot-row cache. Python-plane
        # only (natively-served ops bypass it, same rule as tracing).
        cap = _config.get_flag("hotkeys_capacity")
        self._hotkeys = (_hotkeys.SpaceSaving(cap) if cap > 0 else None)
        # tenant attribution (telemetry/tenants.py): per-tenant op/byte
        # counters at the same chokepoints as the byte counters above.
        # Default-tenant path is one attribute read + one dict increment
        # (benign-race, same tolerance as _stat_gets); named tenants —
        # the wire-stamped minority — pay the meter's lock and feed its
        # Space-Saving ranking. Python-plane only, same rule as the
        # hot-key sketch (stamped frames always punt).
        self._tenants = _tenants.TenantMeter()
        # apply latency histogram (the p50/p99 of one updater dispatch)
        self._mon_apply = Dashboard.get(f"ps[{name}].apply")
        # native shard PIN once the native server serves this shard's hot
        # ops (service._try_register_native); Python then only sees punted
        # messages for it, already holding the native shard mutex. The pin
        # addresses this exact shard object in C++ and outlives the server
        # (freed in __del__, along with pins retired by re-registration).
        self._native_ref: Optional[int] = None
        self._retired_pins: List[int] = []
        # dirty[worker, local_row]: starts all-True so a worker's first
        # sparse Get pulls everything (ref matrix.cpp up_to_date_ = false)
        self._dirty = (np.ones((num_workers, self.n), bool)
                       if num_workers > 0 else None)
        # exactly-once replay plane (docs/FAILOVER.md): per-client
        # applied-sequence channels. _replay_seq tracks which stamped
        # frames each client has APPLIED (a frame already in its
        # channel is a duplicate — replay racing a late ack, or a
        # survivor re-flushing to this restored incarnation — and is
        # acked without applying); _durable_floor is the channel floor
        # at the last CHECKPOINT (ShardCheckpointer.mark_durable),
        # echoed in every stamped reply as the client's retention-prune
        # signal. _stamp_lock makes (dup check, apply, commit) atomic
        # against checkpoint_state()'s snapshot: without it a frame
        # could apply before the snapshot but commit its mark after,
        # and the restored state would replay-apply it twice.
        self._replay_seq: Dict[str, _SeqChannel] = {}
        self._durable_floor: Dict[str, int] = {}
        self._stamp_lock = threading.Lock()
        self._stat_dup_frames = 0
        # memory ledger (telemetry/memstats.py): live pins by identity,
        # id(pin) -> (t0 mono, buffer bytes, id(buffer)). The registry
        # records bytes AT PIN TIME and never references the buffer —
        # a ledger entry keeping a retired epoch alive would be this
        # plane's own leak. One dict store/pop per get, under the lock
        # the pin already takes; the gauges themselves are pull-only.
        self._pin_reg: Dict[int, Tuple[float, int, int]] = {}
        # last successful gauge pull, served when the shard lock is
        # contended (see memory_stats): the LIVENESS sweep drives the
        # ledger, and a sweep that blocked on a wedged apply would
        # hang the watchdog on exactly the wedge it exists to report
        self._mem_cache: Dict[str, Any] = {
            "table_bytes": int(getattr(self._data, "nbytes", 0)),
            "ustate_bytes": 0, "dtype": str(self.dtype),
            "pins": 0, "pinned_epochs": 0, "retired_epochs": 0,
            "retired_bytes": 0, "oldest_pin_age_s": 0.0}
        _memstats.register(f"shard[{name}:{self.lo}-{self.hi}]", self)

    # ------------------------------------------------------------------ #
    # storage indirection (mesh-stacked groups, ps/spmd.py): classic
    # shards read/write `_data_raw`/`_ustate_raw` straight through these
    # properties; a grouped shard's storage lives as one lane of its
    # plane's stacked array, and reads materialize a lazily-sliced slab
    # view (cached per plane epoch — a slice is its own buffer, so
    # pinned views survive the stack's donated swaps untouched). Every
    # existing read/rebind site keeps its spelling.
    # ------------------------------------------------------------------ #
    @property
    def _data(self):
        p = getattr(self, "_plane", None)
        if p is not None:
            return p.view(self)
        return self._data_raw

    @_data.setter
    def _data(self, v):
        self._data_raw = v

    @property
    def _ustate(self):
        p = getattr(self, "_plane", None)
        if p is not None:
            return p.ustate_view(self)
        return self._ustate_raw

    @_ustate.setter
    def _ustate(self, v):
        self._ustate_raw = v

    def _plane_lock(self):
        """The plane's lock as a context when grouped (nests INSIDE the
        shard lock — the one global order), else a no-op. Read paths
        that must see (bytes, version) atomically vs grouped applies
        hold it across both reads."""
        import contextlib
        p = self._plane
        return p.lock if p is not None else contextlib.nullcontext()

    def _plane_evict(self) -> None:
        """Fall back to classic per-shard storage before an exotic
        mutation (set_rows / whole-table add / state restore) — the
        always-safe path; row add/get traffic never needs it."""
        p = self._plane
        if p is not None:
            p.evict(self)

    def _place_rows(self, host):
        """Place a row buffer honoring the size-gated local-device sharding
        (numpy-mode shards keep a writable host buffer instead)."""
        if self._np_mode:
            return np.ascontiguousarray(np.asarray(host, self.dtype))
        if self._local_sharding is not None:
            return jax.device_put(host, self._local_sharding)
        return jnp.asarray(host)

    def _place_state_local(self, x):
        """Shard updater-state leaves over the local device mesh where the
        shape lines up (per-worker adagrad g² etc.), else replicate.
        Row-axis detection reuses :meth:`_state_row_axis` — one shape rule."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self._local_sharding.mesh
        axis = self._state_row_axis(x)
        if axis >= 0:
            nd = np.ndim(x)
            spec = P(*([None] * axis), "rows", *([None] * (nd - axis - 1)))
            return jax.device_put(x, NamedSharding(mesh, spec))
        return jax.device_put(x, NamedSharding(mesh, P()))

    # ------------------------------------------------------------------ #
    def bind_native(self, pin: int) -> None:
        if self._native_ref is not None:
            # re-registration: the OLD pin must not be freed yet — the
            # previously installed locked_handler closure still holds it
            # and may be mid-request; retire it and free at shard death
            self._retired_pins.append(self._native_ref)
        self._native_ref = pin

    def __del__(self):
        try:
            pins = getattr(self, "_retired_pins", [])
            if getattr(self, "_native_ref", None) is not None:
                pins = pins + [self._native_ref]
                self._native_ref = None
            if pins:
                from multiverso_tpu.ps import native as ps_native
                for p in pins:
                    ps_native.shard_pin_free(p)
        except Exception:   # noqa: BLE001 — interpreter teardown
            pass

    def _native_stats(self) -> Tuple[int, int]:
        if self._native_ref is None:
            return 0, 0
        from multiverso_tpu.ps import native as ps_native
        return ps_native.shard_pin_stats(self._native_ref)

    @property
    def stat_adds(self) -> int:
        return self._stat_adds + self._native_stats()[0]

    @property
    def stat_applies(self) -> int:
        return self._stat_applies + self._native_stats()[1]

    def stats(self) -> Dict[str, Any]:
        """First-class server-side stats (MSG_STATS reply / exporter):
        JSON-safe scalars + the wave distribution. Cheap — reads
        counters and queue lengths, never touches the data buffer."""
        with self._addq_lock:
            queue_depth = len(self._addq)
            pending_bytes = sum(e.local.nbytes + e.vals.nbytes
                                for e in self._addq)
        # ONE native crossing: the stat_adds/stat_applies properties
        # would each call shard_pin_stats again, and three racing reads
        # could mix counter states within one snapshot
        n_adds, n_applies = self._native_stats()
        adds = self._stat_adds + n_adds
        applies = self._stat_applies + n_applies
        native_applies = n_applies
        with self._lock:
            wave_ops = {str(k): v
                        for k, v in sorted(self._wave_ops.items())}
            wave_max = self._wave_max
            # natively-served applies never touch Python, so the zero-
            # Python C++ counter folds into the mutation version (both
            # only grow — monotonicity holds); the wave distribution
            # stays a python-path view by design (same rule as the
            # dashboard's native_served note)
            version = self._version + native_applies
            # rows stale for AT LEAST one worker (any-axis, not the raw
            # flag sum — a (workers, rows) flag count would exceed the
            # shard's row count and mislead staleness sizing)
            dirty_rows = (int(self._dirty.any(axis=0).sum())
                          if self._dirty is not None else None)
        out = {
            "kind": "row",
            "lo": self.lo, "rows": self.n, "cols": self.num_col,
            "bytes": int(self._padded[0] * self.num_col
                         * self.dtype.itemsize),
            "adds": adds, "applies": applies,
            "version": version,
            "queue_depth": queue_depth,
            "pending_bytes": pending_bytes,
            "wave_ops": wave_ops,       # pow2-bucketed ops-per-apply
            "wave_max_ops": wave_max,
            "apply": self._mon_apply.snapshot().hist_dict(),
            # read plane: gets served off-lock, chunks streamed, applies
            # that copied/skipped donation because a reader pinned the
            # epoch, and readers pinning it right now
            "gets": self._stat_gets,
            "get_chunks": self._stat_chunks,
            "cow_applies": self._stat_cow,
            "read_pins": self._cur_pins,
            # cumulative ENCODED wire bytes served/received (python
            # plane); the aggregator's wire-bytes/s comes from deltas
            "get_bytes": self._stat_get_bytes,
            "add_bytes": self._stat_add_bytes,
            # replay plane (docs/FAILOVER.md): stamped frames dedup'd
            # as duplicates, and how many clients hold a sequence
            # channel here — non-zero dup_frames after a failover is
            # the exactly-once machinery WORKING, not an error
            "dup_frames": self._stat_dup_frames,
            "replay_clients": len(self._replay_seq),
            # serving plane: replica snapshot pulls answered (and how
            # many were since-version deduped to an 'unchanged' frame)
            "snapshots": self._stat_snapshots,
            "snapshots_unchanged": self._stat_snapshot_unchanged,
        }
        if dirty_rows is not None:
            out["dirty_rows"] = dirty_rows   # sparse-protocol staleness
        if self._hotkeys is not None:
            out["hotkeys"] = self._hotkeys.to_dict()
        # per-tenant op/byte counters (telemetry/tenants.py): omitted
        # until the meter counts — the aggregator sums these per rank,
        # unlike the process-global "tenants" MSG_STATS block
        tm = self._tenants.to_dict()
        if tm:
            out["tenants"] = tm
        # mesh-stacked group placement (ps/spmd.py): slot -> device plus
        # this shard's share of the plane's grouped applies — mvtop's
        # shard-placement panel renders skew from bad placement off it
        p = self._plane
        if p is not None:
            sp = p.stats_for(self)
            if sp is not None:
                out["spmd"] = sp
        return out

    def queue_depth(self) -> int:
        """Lock-free apply-queue depth for the health plane (len() is
        GIL-atomic; the verdict tolerates ±1). MSG_HEALTH must never
        take a shard lock — it answers precisely when the shard is
        wedged — so this is deliberately NOT the stats() path."""
        return len(self._addq)

    def memory_stats(self) -> Dict[str, Any]:
        """Byte-ledger gauges (telemetry/memstats.py, pull-only): the
        live data buffer, updater state, the pinned read epochs — how
        many DISTINCT buffers pins hold, how many of those are RETIRED
        (COW-swapped out, alive only through their pins: the exact
        hoard the ``_pin_buf`` anchor bug silently carried) and their
        deduped bytes, the oldest pin's age — and the apply queue's
        pending payload. Counters and attr reads only; never touches
        buffer contents.

        NON-BLOCKING on the shard lock: the watchdog's liveness sweep
        drives the verdict engine, and a pull that blocked behind a
        multi-second (or wedged) apply would hang the watchdog on
        exactly the condition it exists to report. A contended pull
        serves the last successful reading marked ``"stale": True`` —
        the ledger tolerates a one-sweep-old figure."""
        if self._lock.acquire(blocking=False):
            try:
                p = self._plane
                if p is not None:
                    # grouped (ps/spmd.py): report the slab SHARE of the
                    # pooled stack from cached static sizes — the pull
                    # must never materialize a view (that would pay a
                    # device slice per ledger sweep) nor block on the
                    # plane lock mid-apply. The stack itself has its own
                    # spmd[table] ledger component.
                    data_nb = int(self._padded[0] * self.num_col
                                  * self.dtype.itemsize)
                    vc = self._view_cache
                    live_id = id(vc) if vc is not None else -1
                    ustate_nb = int(self._mem_state_bytes)
                else:
                    data_nb = int(getattr(self._data_raw, "nbytes", 0))
                    live_id = id(self._data_raw)
                    ustate_nb = sum(
                        int(getattr(l, "nbytes", 0))
                        for l in jax.tree.leaves(self._ustate_raw))
                pins = list(self._pin_reg.values())
            finally:
                self._lock.release()
            now = time.monotonic()
            epochs: Dict[int, int] = {}
            for _t0, nb, buf_id in pins:
                epochs.setdefault(buf_id, nb)
            retired = {b: nb
                       for b, nb in epochs.items() if b != live_id}
            oldest = max((now - t0 for t0, _nb, _b in pins),
                         default=0.0)
            core = {
                "table_bytes": data_nb,
                "ustate_bytes": int(ustate_nb),
                "dtype": str(self.dtype),
                "pins": len(pins),
                "pinned_epochs": len(epochs),
                "retired_epochs": len(retired),
                "retired_bytes": int(sum(retired.values())),
                "oldest_pin_age_s": round(oldest, 3),
            }
            if self._plane is not None:
                # pooled storage: these bytes are the shard's SHARE of
                # the plane's stack (which carries its own spmd[table]
                # ledger component)
                core["spmd"] = True
            self._mem_cache = core
        else:
            core = dict(self._mem_cache)
            core["stale"] = True
        with self._addq_lock:   # short holds only — never spans a jit
            qd = len(self._addq)
            qb = sum(e.local.nbytes + e.vals.nbytes for e in self._addq)
        out = dict(core)
        out["queue_depth"] = qd
        out["queue_pending_bytes"] = int(qb)
        return out

    @property
    def scratch(self) -> int:
        return self.n

    def _note_rows(self, local: np.ndarray) -> None:
        """Feed the heavy-hitter sketch with this op's GLOBAL row ids
        (shard-local + ``lo``). Called on the get/add serve paths AFTER
        id validation; HashShard overrides — its inherited call sites
        carry slot ids, and the sketch wants the workload's keys."""
        if self._hotkeys is not None:
            self._hotkeys.observe(local, offset=self.lo)

    # ------------------------------------------------------------------ #
    # off-lock read epochs (snapshot serving)
    # ------------------------------------------------------------------ #
    def _pin_data_locked(self) -> _DataPin:
        """Pin the current data epoch (caller holds ``self._lock``): the
        returned handle references the live buffer, and the apply path
        will not donate/mutate that buffer in place while the pin count
        is non-zero. The count is tied to BUFFER IDENTITY (_pin_buf), so
        any site that rebinds ``self._data`` implicitly retires it —
        stale releases become no-ops and retired buffers free through
        the pins' own references."""
        if self._pin_buf is not self._data:
            self._pin_buf = self._data
            self._cur_pins = 0
        self._cur_pins += 1
        pin = _DataPin(self._data, self._version)
        self._pin_reg[id(pin)] = (time.monotonic(),
                                  int(getattr(self._data, "nbytes", 0)),
                                  id(self._data))
        return pin

    def _pin_data(self) -> _DataPin:
        with self._lock:
            return self._pin_data_locked()

    def _release_data(self, pin: _DataPin) -> None:
        with self._lock:
            self._pin_reg.pop(id(pin), None)
            if pin.data is self._pin_buf and self._cur_pins > 0:
                self._cur_pins -= 1
                if self._cur_pins == 0:
                    # drop the identity anchor too: after a copy-on-write
                    # swap it would otherwise keep the RETIRED buffer
                    # alive until the next pin — a full extra table of
                    # memory in an add-heavy, rarely-read workload
                    self._pin_buf = None
        pin.data = None   # last holder of a retired epoch frees it

    def _data_pinned(self) -> bool:
        """True when a reader pins the LIVE buffer (caller holds the
        lock): the apply must then swap to a fresh buffer instead of
        donating or mutating in place."""
        return self._pin_buf is self._data and self._cur_pins > 0

    def _writable_data(self):
        """The buffer an in-place numpy mutation may write (caller holds
        ``self._lock``): copy-on-write when a reader pins the current
        epoch. Natively-registered shards never swap — C++ holds the raw
        pointer — and never need to: every python-plane op on them runs
        under the native shard mutex, so a pin cannot coexist with an
        apply there."""
        if self._native_ref is None and self._data_pinned():
            self._data = self._data.copy()
            self._stat_cow += 1
        return self._data

    def _state_row_axis(self, leaf) -> int:
        """Axis of ``leaf`` matching the table row axis; -1 = row-free leaf
        (-1, not None: None is not a pytree leaf, so it would corrupt the
        row_axes tree structure)."""
        nd, pd = np.ndim(leaf), len(self._padded)
        if nd >= pd and tuple(np.shape(leaf)[nd - pd:]) == self._padded:
            return nd - pd
        return -1

    def _row_update_fn(self, bucket: int, donate: bool = True):
        """Jitted row update; ``donate=False`` compiles a variant that
        does NOT donate the data buffer (updater state still donates —
        no reader ever pins it) for applies racing a pinned read epoch:
        the pinned snapshot must survive the update."""
        key = ("row_update", bucket, donate)
        fn = self._jit.get(key)
        if fn is not None:
            return fn
        updater = self.updater

        def _update(data, ustate, ids, vals, opt):
            row_axes = jax.tree.map(self._state_row_axis, ustate)
            rows = jnp.take(data, ids, axis=0)

            def gather(leaf, axis):
                return jnp.take(leaf, ids, axis=axis) if axis >= 0 else leaf

            gstate = jax.tree.map(gather, ustate, row_axes)
            new_rows, new_gstate = updater.apply(rows, gstate, vals, opt)
            data = data.at[ids].set(new_rows)

            def scatter(leaf, new_leaf, axis):
                if axis < 0:
                    return new_leaf
                idx = (slice(None),) * axis + (ids,)
                return leaf.at[idx].set(new_leaf)

            ustate = jax.tree.map(scatter, ustate, new_gstate, row_axes)
            return data, ustate

        fn = jax.jit(_update, donate_argnums=(0, 1) if donate else (1,))
        self._jit[key] = fn
        return fn

    def _full_update_fn(self, donate: bool = True):
        key = ("full", donate)
        fn = self._jit.get(key)
        if fn is None:
            updater = self.updater

            def _update(data, ustate, delta, opt):
                return updater.apply(data, ustate, delta, opt)

            fn = self._jit[key] = jax.jit(
                _update, donate_argnums=(0, 1) if donate else (1,))
        return fn

    def _get_fn(self, bucket: int):
        key = ("get", bucket)
        fn = self._jit.get(key)
        if fn is None:
            fn = self._jit[key] = jax.jit(
                lambda data, ids: jnp.take(data, ids, axis=0))
        return fn

    def _pad_to_bucket(self, local: np.ndarray) -> np.ndarray:
        """Pad a local-id batch to its power-of-two bucket with the scratch
        row (the one shape-discipline rule, shared by every row path)."""
        b = _bucket_size(local.size, self.n + 1)
        if b > local.size:
            local = np.concatenate(
                [local, np.full(b - local.size, self.scratch, np.int64)])
        return local.astype(np.int32)

    def _localize_raw(self, ids: np.ndarray) -> np.ndarray:
        """Global ids -> validated local ids (unpadded)."""
        local = np.asarray(ids, np.int64) - self.lo
        if local.size == 0 or np.any((local < 0) | (local >= self.n)):
            raise IndexError(
                f"row ids outside shard [{self.lo}, {self.hi}) of "
                f"{self.name}")
        return local

    def _localize(self, ids: np.ndarray) -> Tuple[np.ndarray, int]:
        """Global ids -> bucket-padded local ids (+ true count)."""
        local = self._localize_raw(ids)
        return self._pad_to_bucket(local), local.size

    def _gather_rows(self, local: np.ndarray,
                     data: Optional[Any] = None) -> np.ndarray:
        """Gather shard rows for a reply from ``data`` (a pinned epoch
        buffer; defaults to the live buffer for callers that hold the
        lock). Host-backed shards read via numpy off the zero-copy view;
        device-backed shards run the bucketed jitted take. Always returns
        an OWNED host array (fancy indexing / np.asarray of a jit result
        copy), so the caller may release its pin before encoding."""
        if data is None:
            data = self._data
        if self._host_serve:
            return np.asarray(data)[local]
        padded = self._pad_to_bucket(local)
        return np.asarray(
            self._get_fn(padded.size)(data, padded))[: local.size]

    # ------------------------------------------------------------------ #
    # coalescing apply queue (ps_coalesce)
    # ------------------------------------------------------------------ #
    def _apply_add_group(self, entries: List[_PendingAdd],
                         opt: AddOption) -> int:
        """Apply one opt-group of queued adds as ONE jitted update (caller
        holds ``self._lock``). Cross-request duplicate rows sum their
        deltas (float64 accumulation, same rule as the client-side
        ``_dedupe_batch``) — semantically the deltas arrived in a single
        message, which is the associativity async mode already grants.
        Updaters with GLOBAL state (adam's step counter advances once per
        apply) never merge: K adds must count K steps. Returns the number
        of updates actually dispatched (the ``stat_applies`` unit, so the
        reported coalescing ratio stays honest for non-merging
        updaters)."""
        if len(entries) > 1 and type(self.updater) not in _ROW_LOCAL_STATE:
            # per-entry errors: entry k failing must not mark the k-1
            # already-committed entries lost (a blanket group error would
            # invite retries that double-apply; same contract as
            # _apply_batch_adds' per-wave failure reporting)
            applies = 0
            for e in entries:
                self._record_wave(1)
                try:
                    self._apply_rows(e.local, e.vals, e.opt)
                    applies += 1
                except Exception as err:  # noqa: BLE001 — per-entry
                    e.error = err
            return applies
        if len(entries) == 1:
            local, vals = entries[0].local, entries[0].vals
        else:
            cat_ids = np.concatenate([e.local for e in entries])
            local, inv = np.unique(cat_ids, return_inverse=True)
            acc = np.zeros((local.size, self.num_col), np.float64)
            np.add.at(acc, inv,
                      np.concatenate([e.vals for e in entries])
                      .astype(np.float64))
            vals = acc.astype(self.dtype)
        self._record_wave(len(entries))
        self._apply_rows(local, vals, opt)
        return 1

    def _record_wave(self, ops: int) -> None:
        """Merged-ops-per-apply distribution (under ``self._lock``):
        power-of-two buckets keep it a tiny exact dict — wave sizes are
        bounded by MAX_BATCH_OPS, so log-scale bucketing buys nothing."""
        b = 1 << max(ops - 1, 0).bit_length()
        self._wave_ops[b] = self._wave_ops.get(b, 0) + 1
        if ops > self._wave_max:
            self._wave_max = ops

    def _apply_rows(self, local: np.ndarray, vals: np.ndarray,
                    opt: AddOption) -> None:
        """One merged, deduped row-delta batch -> the updater (under
        ``self._lock``). Times itself into the ``ps[name].apply``
        histogram and bumps the shard mutation version."""
        p = self._plane
        if p is not None:
            # mesh-stacked group (ps/spmd.py): the update runs as one
            # lane of the plane's SPMD program — the plane owns the
            # version bump (under its lock, atomic with the stack swap),
            # the apply histogram sample, and the flight-recorder edge.
            # Wave/stat recording stays with this path's callers, who
            # hold self._lock exactly as they do classically.
            p.apply_rows(self, local, vals, opt)
            return
        t0 = time.perf_counter()
        if self._np_mode:
            data = self._writable_data()   # copy-on-write vs pinned reads
            sign = _LINEAR_SIGN[type(self.updater)]
            if sign > 0:
                data[local] += vals   # merged ids are unique
            else:
                data[local] -= vals
            if self._dirty is not None:
                self._dirty[:, local] = True
        else:
            ids = self._pad_to_bucket(local)
            if vals.shape[0] < ids.size:   # zero-pad to the bucket
                vals = np.concatenate(
                    [vals,
                     np.zeros((ids.size - vals.shape[0], self.num_col),
                              self.dtype)])
            # a pinned read epoch forbids donating the data buffer: the
            # non-donating variant writes a fresh buffer and the pinned
            # one retires to its readers (freed on their last release)
            donate = not self._data_pinned()
            if not donate:
                self._stat_cow += 1
            self._data, self._ustate = self._row_update_fn(
                ids.size, donate)(self._data, self._ustate, ids, vals, opt)
            if self._dirty is not None:
                self._dirty[:, local] = True   # stale for everyone
        self._version += 1
        self._mon_apply.observe_ms((time.perf_counter() - t0) * 1e3)
        # black box: one apply edge + the shard-liveness heartbeat (a
        # queue that stops draining shows up as a stale "apply" beat in
        # MSG_HEALTH even before any request ages past the watchdog)
        _flight.beat("apply")
        _flight.record(_flight.EV_APPLY, nbytes=vals.nbytes)

    # shared continuation pool for drain hand-off (class-level: shards are
    # many, the pool is one; drain passes never block on anything but the
    # shard lock, so two threads cannot deadlock across shards)
    _drain_pool: Optional[Any] = None
    _drain_pool_lock = threading.Lock()

    @classmethod
    def _handoff_pool(cls):
        with cls._drain_pool_lock:
            if cls._drain_pool is None:
                import concurrent.futures as cf
                cls._drain_pool = cf.ThreadPoolExecutor(
                    max_workers=2, thread_name_prefix="ps-drain")
            return cls._drain_pool

    def _drain_adds(self, rounds: int = 8) -> None:
        """Applier loop: drain everything queued, merging per opt-group,
        until the queue is observed empty (checked atomically with the
        drainer-slot release, so no entry is ever orphaned). Bounded at
        ``rounds`` passes: the drainer is usually a connection thread
        serving ONE rank's whole request stream, and under sustained
        cross-rank load the queue may never be observed empty — after the
        bound, the remaining backlog hands off to the shared drain pool so
        the captured thread can reply to its own rank again."""
        normal_exit = False
        try:
            while True:
                handoff = False
                with self._addq_lock:
                    if not self._addq:
                        self._addq_draining = False
                        normal_exit = True
                        return
                    if rounds <= 0:
                        handoff = True   # drainer slot stays claimed
                    else:
                        rounds -= 1
                        batch, self._addq = self._addq, []
                if handoff:
                    # outside the queue lock: a failed submit falls through
                    # to the finally, which needs that lock to fail the
                    # backlog rather than wedge it
                    self._handoff_pool().submit(self._drain_adds)
                    normal_exit = True
                    return
                # opt-insensitive updaters merge across senders (one
                # group); the rest group by the full AddOption so e.g.
                # per-worker AdaGrad g2 stays per-worker
                merge_all = type(self.updater) in _OPT_INSENSITIVE
                groups: Dict[Any, List[_PendingAdd]] = {}
                for e in batch:
                    groups.setdefault(
                        None if merge_all else e.opt, []).append(e)
                with self._lock:
                    applies = 0
                    for entries in groups.values():
                        try:
                            applies += self._apply_add_group(
                                entries, entries[0].opt)
                        except Exception as err:
                            for e in entries:
                                e.error = err
                    self._stat_adds += len(batch)
                    self._stat_applies += applies
                for e in batch:
                    e.event.set()
        finally:
            if not normal_exit:   # crashed out: fail queued entries rather
                with self._addq_lock:   # than wedge their waiters forever
                    self._addq_draining = False
                    orphans, self._addq = self._addq, []
                for e in orphans:
                    e.error = svc.PSError(
                        f"{self.name}: add applier died")
                    e.event.set()

    def _enqueue_add(self, local: np.ndarray, vals: np.ndarray,
                     opt: AddOption) -> None:
        """Queue a validated, shard-local add and block until applied (the
        reply must mean durably-applied, or a worker's add->get would not
        read its own write). MUST NOT be called holding ``self._lock``: a
        waiter holding it would deadlock the applier."""
        entry = _PendingAdd(local, vals, opt)
        with self._addq_lock:
            self._addq.append(entry)
            drainer = not self._addq_draining
            if drainer:
                self._addq_draining = True
        if drainer:
            self._drain_adds()
        entry.event.wait()
        if entry.error is not None:
            raise entry.error

    def _prep_add(self, meta: Dict, arrays: Sequence[np.ndarray]
                  ) -> Tuple[np.ndarray, np.ndarray, AddOption]:
        """Validate an ADD_ROWS request into (local ids, vals, opt). The
        value payload decodes ONCE here, straight from the frame blobs
        into the apply (wire.decode_payload) — there is no intermediate
        re-encode hop for compressed wires."""
        opt = AddOption(**meta.get("opt", {}))
        local = self._localize_raw(arrays[0])
        self._note_rows(local)   # one sketch record per add (plain+batch)
        wirem = meta.get("wire", "none")
        if wirem in ("none", "bf16"):   # single blob decodes implicitly
            vals = np.asarray(arrays[1], self.dtype)[: local.size]
        else:
            vals = wire.decode_payload(arrays[1:], wirem,
                                       (local.size, self.num_col),
                                       self.dtype)
        # ENCODED payload bytes (the blobs as they crossed the wire —
        # a 1bit add must not count as 4 bytes/element), per REQUEST
        # (like _stat_adds counts requests): the coalescing queue
        # merges K overlapping adds into one deduped apply, and
        # counting at apply time would underreport by up to Kx
        nbytes = sum(int(getattr(a, "nbytes", 0)) for a in arrays[1:])
        self._stat_add_bytes += nbytes
        # tenant attribution rides the SAME per-request chokepoint (an
        # unstamped frame is the default tenant — one dict increment)
        self._tenants.note(meta.get(wire.TENANT_META_KEY),
                           add_bytes=nbytes)
        return local, vals, opt

    def _prep_add_entry(self, meta: Dict, arrays: Sequence[np.ndarray]
                        ) -> _PendingAdd:
        """One MSG_BATCH sub-op -> a validated pending entry (HashShard
        overrides: its entries carry keys, translated at apply time)."""
        local, vals, opt = self._prep_add(meta, arrays)
        return _PendingAdd(local, vals, opt,
                           trace=meta.get(wire.TRACE_META_KEY))

    def _apply_batch_adds(self, entries: List[_PendingAdd]
                          ) -> Tuple[List[int], List[str]]:
        """Apply one window's adds as conflict-free WAVES: consecutive
        entries whose row sets are disjoint (and whose opts agree, unless
        the updater is opt-insensitive) concatenate into ONE bucketed
        scatter; a conflicting entry closes the wave, so overlapping rows
        still apply in arrival order with per-op arithmetic. Disjoint
        grouping is what keeps a batched window BIT-IDENTICAL to the same
        ops arriving as N separate frames — the queue's f64 duplicate
        merge (:meth:`_apply_add_group`) is reserved for genuinely
        concurrent senders, where no order was ever promised. Global-
        state updaters (adam: one step-counter bump per apply) never
        wave-merge: every entry applies alone, K adds = K steps.

        Returns ``(failed_indices, error_strings)``: a wave that fails
        marks ONLY its entries failed and the later waves still apply —
        exactly window-off semantics, where each op is an independent
        request and op K failing does not stop op K+1. The caller
        reports failures PER SUB-OP so the client can never mistake an
        applied delta for a lost one (a blanket error would invite a
        retry that double-applies the deltas that DID land)."""
        failed: List[int] = []
        errors: List[str] = []
        if not entries:
            return failed, errors
        mergeable = type(self.updater) in _ROW_LOCAL_STATE
        merge_all = type(self.updater) in _OPT_INSENSITIVE
        with self._lock:
            wave: List[Tuple[int, _PendingAdd]] = []
            seen: set = set()

            def flush_wave():
                if not wave:
                    return
                traced = (_trace.enabled()
                          and any(e.trace is not None for _, e in wave))
                t0 = time.time() if traced else 0.0
                self._record_wave(len(wave))
                _flight.record(_flight.EV_WAVE,
                               nbytes=sum(e.vals.nbytes for _, e in wave),
                               note=f"ops={len(wave)}")
                try:
                    if len(wave) == 1:
                        e = wave[0][1]
                        self._apply_rows(e.local, e.vals, e.opt)
                    else:
                        self._apply_rows(
                            np.concatenate([e.local for _, e in wave]),
                            np.concatenate([e.vals for _, e in wave]),
                            wave[0][1].opt)
                    self._stat_applies += 1
                except Exception as err:   # noqa: BLE001 — reported per op
                    failed.extend(i for i, _ in wave)
                    errors.append(f"{type(err).__name__}: {err}")
                if traced:
                    # ONE span per wave, correlated to every sub-op it
                    # applied: "trace" carries the first ID (timeline
                    # stitching), "traces" the full set
                    tids = [e.trace for _, e in wave
                            if e.trace is not None]
                    _trace.add_span(
                        "shard.wave_apply", t0, time.time(),
                        trace=tids[0],
                        args={"table": self.name, "ops": len(wave),
                              "traces": tids})
                wave.clear()
                seen.clear()

            for i, e in enumerate(entries):
                ids = e.local.tolist()
                if wave and (not mergeable
                             or any(x in seen for x in ids)
                             or (not merge_all
                                 and e.opt != wave[0][1].opt)):
                    flush_wave()
                wave.append((i, e))
                seen.update(ids)
            flush_wave()
            self._stat_adds += len(entries)
        return failed, errors

    def _handle_batch(self, meta: Dict, arrays: Sequence[np.ndarray]
                      ) -> Tuple[Dict, List[np.ndarray]]:
        """One MSG_BATCH frame: the client send window's sub-ops, applied
        in window order with one ack for the lot. Windows carry row adds
        only (gets fence the window client-side), so anything else in a
        batch is a framing error, not a dispatch case. Validation
        failures (unknown sub-op type, bad ids) raise BEFORE anything
        applies — a whole-frame error then means nothing landed; apply
        failures after that point come back per sub-op in the reply meta
        ("failed" indices), never as a blanket error."""
        subs = wire.unpack_batch(arrays)
        entries = []
        for mt, m, arrs in subs:
            if mt != svc.MSG_ADD_ROWS:
                raise svc.PSError(
                    f"{self.name}: batch frames carry MSG_ADD_ROWS only "
                    f"(got type {mt})")
            entries.append(self._prep_add_entry(m, arrs))
        failed, errors = self._apply_batch_adds(entries)
        rmeta: Dict = {"n": len(subs)}
        if failed:
            rmeta["failed"] = failed
            rmeta["error"] = "; ".join(errors[:3])
        return rmeta, []

    def _add_rows(self, local: np.ndarray, vals: np.ndarray,
                  opt: AddOption) -> None:
        if self._native_ref is not None:
            # natively-served shard: this is a PUNTED add (compressed wire
            # payload), already running under the native shard mutex via
            # the service's locked handler. Apply directly — the queue's
            # drain handoff runs on a pool thread that would NOT hold the
            # native mutex, racing C++ applies on the same buffer.
            with self._lock:
                self._apply_add_group([_PendingAdd(local, vals, opt)], opt)
                self._stat_adds += 1
                self._stat_applies += 1
        elif _config.get_flag("ps_coalesce"):
            self._enqueue_add(local, vals, opt)
        else:
            with self._lock:
                entry = _PendingAdd(local, vals, opt)
                self._apply_add_group([entry], opt)
                self._stat_adds += 1
                self._stat_applies += 1

    # ------------------------------------------------------------------ #
    # off-lock get serving (snapshot pin -> gather -> encode, all outside
    # the shard lock; applies keep flowing while a reply is computed)
    # ------------------------------------------------------------------ #
    def _serve_get_rows(self, meta: Dict, arrays: Sequence[np.ndarray]
                        ) -> Tuple[Dict, Any]:
        local = self._localize_raw(arrays[0])
        self._note_rows(local)
        tr = meta.get(wire.TRACE_META_KEY) if _trace.enabled() else None
        t0 = time.time() if tr is not None else 0.0
        pin = self._pin_data()
        if tr is not None:
            _trace.add_span("shard.get_pin", t0, time.time(), trace=tr,
                            args={"table": self.name,
                                  "rows": int(local.size)})
        return self._serve_rows_from_pin(pin, local, meta, tr)

    def _serve_rows_from_pin(self, pin: _DataPin, local: np.ndarray,
                             meta: Dict, tr: Optional[int]
                             ) -> Tuple[Dict, Any]:
        """The shared off-lock serve body once an epoch is pinned and
        ids resolved (RowShard localizes, HashShard translates key->slot
        atomically with its pin): flight edge, gather off-lock, release,
        counters, encode. ONE implementation, so new read-path
        instrumentation cannot drift between the planes."""
        _flight.record(_flight.EV_GET_SERVE,
                       nbytes=local.size * self.num_col
                       * self.dtype.itemsize)
        t1 = time.time() if tr is not None else 0.0
        try:
            rows = self._gather_rows(local, data=pin.data)
        finally:
            self._release_data(pin)
        self._stat_gets += 1
        if tr is not None:
            _trace.add_span("shard.get_gather", t1, time.time(), trace=tr,
                            args={"table": self.name})
        return self._encode_reply(rows, meta, tr)

    def _serve_get_full(self, meta: Dict) -> Tuple[Dict, Any]:
        tr = meta.get(wire.TRACE_META_KEY) if _trace.enabled() else None
        t0 = time.time() if tr is not None else 0.0
        pin = self._pin_data()
        _flight.record(_flight.EV_GET_SERVE,
                       nbytes=self.n * self.num_col * self.dtype.itemsize)
        try:
            # np_mode: the pin guarantees the buffer is not mutated in
            # place while held (copy-on-write applies swap instead), but
            # the reply outlives the pin — own the bytes. Device-backed:
            # np.asarray is already an owned host copy.
            full = (pin.data[: self.n].copy() if self._np_mode
                    else np.asarray(pin.data)[: self.n])
        finally:
            self._release_data(pin)
        self._stat_gets += 1
        if tr is not None:
            _trace.add_span("shard.get_gather", t0, time.time(), trace=tr,
                            args={"table": self.name, "full": True})
        return self._encode_reply(full, meta, tr)

    def export_snapshot(self, meta: Dict) -> Tuple[Dict, Any]:
        """Replica subscription snapshot (MSG_SNAPSHOT; the serving
        plane's pull primitive, docs/SERVING.md): the shard's committed
        rows plus the mutation version they correspond to, version and
        epoch pin taken atomically so the advertised version is exactly
        the copied bytes'. ``meta["since"]`` = the version the replica
        already holds — an unchanged shard answers a tiny meta-only
        frame instead of re-shipping its rows (the epoch cadence is
        then nearly free on an idle table). The copy runs OFF the shard
        lock under the pin (applies keep flowing, PR-5), and big
        snapshots chunk-stream when the request asked
        (``meta["chunk"]``). Natively-registered shards are safe here
        because MSG_SNAPSHOT always punts: the punt path's
        locked_handler holds the native shard mutex around this whole
        call, so C++ applies cannot mutate the buffer mid-copy (same
        argument as checkpoint_state, same lock order — native mutex
        first). Snapshot ids never feed the hot-key sketch: a periodic
        full sweep would drown the workload's zipf signal."""
        since = int(meta.get("since", -1))
        # the dedupe token is (generation, version), never version
        # alone: a respawned incarnation restores an older checkpoint
        # and re-applies different ops — its counter can coincide with
        # the replica's last-seen version while the CONTENT diverged.
        # The failover plane already stamps each incarnation
        # (ps_generation, PR 7); a replica holding a different
        # generation's version must be shipped rows, not "unchanged".
        gen = int(_config.get_flag("ps_generation"))
        since_gen = int(meta.get("since_gen", -1))
        tr = meta.get(wire.TRACE_META_KEY) if _trace.enabled() else None
        t0 = time.time() if tr is not None else 0.0
        with self._lock, self._plane_lock():
            # plane lock (grouped shards only): a cross-shard SPMD apply
            # bumps _version under the PLANE lock, so the pin and the
            # advertised version must be read under it to stay the same
            # epoch — serving new bytes under an old version only costs
            # a redundant re-pull, but old bytes under a NEW version
            # would let the replica dedupe real changes away
            version = self._version + self._native_stats()[1]
            if since >= 0 and version == since and since_gen == gen:
                self._stat_snapshots += 1
                self._stat_snapshot_unchanged += 1
                return {"version": version, "gen": gen, "lo": self.lo,
                        "rows": self.n, "cols": self.num_col,
                        "unchanged": True}, []
            pin = self._pin_data_locked()
        # serving traffic on the SAME tape as gets/adds (PR-8 coverage
        # gap): a replica refresh storm must be visible in a fault dump
        _flight.record(_flight.EV_SNAPSHOT_SERVE,
                       nbytes=self.n * self.num_col * self.dtype.itemsize)
        try:
            full = (pin.data[: self.n].copy() if self._np_mode
                    else np.asarray(pin.data)[: self.n])
        finally:
            self._release_data(pin)
        self._stat_snapshots += 1
        if tr is not None:
            _trace.add_span("snapshot.serve", t0, time.time(), trace=tr,
                            args={"table": self.name,
                                  "version": int(version)})
        rmeta = {"version": int(version), "gen": gen, "lo": self.lo,
                 "rows": self.n, "cols": self.num_col}
        emeta, payload = self._encode_reply(full, meta, tr)
        if isinstance(payload, wire.ChunkedReply):
            # the service sends ChunkedReply.meta as the closing OK —
            # the version must ride THAT frame
            payload.meta.update(rmeta)
            return payload.meta, payload
        emeta = dict(emeta)
        emeta.update(rmeta)
        return emeta, payload

    def _encode_reply(self, rows: np.ndarray, meta: Dict,
                      tr: Optional[int]) -> Tuple[Dict, Any]:
        """Wire-encode a gathered get reply — chunk-streamed when the
        client asked for it (meta["chunk"] rows per sub-frame) and the
        reply is big enough, one payload otherwise. Runs OFF the shard
        lock either way."""
        w = meta.get("wire", "none")
        chunk = int(meta.get("chunk", 0) or 0)
        if chunk > 0 and rows.shape[0] > chunk:
            return self._chunked_reply(rows, w, chunk, tr,
                                       meta.get(wire.TENANT_META_KEY))
        t0 = time.time() if tr is not None else 0.0
        payload = wire.encode_payload(rows, w)
        # ENCODED reply bytes (what actually crosses the wire — a topk/
        # 1bit reply is ~16-29x smaller than the gathered f32 rows);
        # feeds the aggregator's wire-bytes/s honestly
        nbytes = sum(int(a.nbytes) for a in payload)
        self._stat_get_bytes += nbytes
        # every reply-encoded read (get, full-get, snapshot pull) is one
        # tenant op at the same chokepoint as the byte counter above
        self._tenants.note(meta.get(wire.TENANT_META_KEY),
                           get_bytes=nbytes)
        if tr is not None:
            _trace.add_span("shard.get_encode", t0, time.time(), trace=tr,
                            args={"table": self.name, "wire": w})
        return {}, payload

    def _chunked_reply(self, rows: np.ndarray, w: str, chunk: int,
                       tr: Optional[int],
                       tn: Optional[str] = None) -> Tuple[Dict, Any]:
        """Stream a big get as self-describing sub-frames: the service
        sends each (MSG_REPLY_CHUNK) as the generator yields, so the
        client's decode + out= scatter overlaps the network receive
        instead of buffering one mega-frame. Encode is lazy per chunk —
        chunk k+1 encodes while chunk k drains into the socket."""
        n = rows.shape[0]
        nchunks = -(-n // chunk)
        self._stat_chunks += nchunks
        # one tenant op per streamed request (bytes ride per chunk below
        # — counted as they encode, same lazy cadence as the byte stat)
        self._tenants.note(tn)
        shard = self

        def gen():
            for i in range(nchunks):
                a, b = i * chunk, min((i + 1) * chunk, n)
                cmeta: Dict = {"seq": i, "row0": a, "rows": b - a}
                if w != "none":
                    cmeta["wire"] = w
                t0 = time.time() if tr is not None else 0.0
                payload = wire.encode_payload(rows[a:b], w)
                cbytes = sum(int(x.nbytes) for x in payload)
                shard._stat_get_bytes += cbytes
                shard._tenants.note(tn, ops=0, get_bytes=cbytes)
                if tr is not None:
                    _trace.add_span("shard.get_encode", t0, time.time(),
                                    trace=tr,
                                    args={"table": shard.name, "wire": w,
                                          "seq": i})
                yield cmeta, payload

        final = {"chunks": nchunks, "rows": n}
        if w != "none":
            final["wire"] = w
        return final, wire.ChunkedReply(final, gen())

    # ------------------------------------------------------------------ #
    # request handler (runs on service connection threads)
    # ------------------------------------------------------------------ #
    def handle(self, msg_type: int, meta: Dict,
               arrays: Sequence[np.ndarray]
               ) -> Tuple[Dict, List[np.ndarray]]:
        if (msg_type in (svc.MSG_ADD_ROWS, svc.MSG_BATCH)
                and wire.REPLAY_CLIENT_KEY in meta):
            return self._handle_stamped(msg_type, meta, arrays)
        return self._handle(msg_type, meta, arrays)

    def _handle_stamped(self, msg_type: int, meta: Dict,
                        arrays: Sequence[np.ndarray]
                        ) -> Tuple[Dict, List[np.ndarray]]:
        """Dedupe gate for replay-stamped add frames (wire.REPLAY_*
        meta): a frame at or below the client's applied high-water mark
        acks as a duplicate without touching the data — the exactly-
        once half of elastic failover (a survivor re-flushing its
        retained window to a restored incarnation must never double-
        apply the prefix the checkpoint already holds, and a replay
        racing a late ack on a live shard must apply once). Stamped
        frames serialize on ``_stamp_lock`` so the check, the apply,
        and the mark commit are one atomic unit against concurrent
        same-client replays AND against checkpoint_state()'s snapshot.
        Replies echo the DURABLE mark (wire.REPLAY_DURABLE_KEY) — the
        client prunes retained frames at or below it."""
        cl = str(meta[wire.REPLAY_CLIENT_KEY])
        seq = int(meta.get(wire.REPLAY_SEQ_KEY, -1))
        with self._stamp_lock:
            chan = self._replay_seq.get(cl)
            if chan is not None and chan.seen(seq):
                self._stat_dup_frames += 1
                _flight.record(_flight.EV_FAILOVER_REPLAY,
                               note=f"dup seq={seq}")
                dup: Dict = {wire.REPLAY_DUP_KEY: True,
                             wire.REPLAY_DURABLE_KEY:
                                 self._durable_floor.get(cl, -1)}
                # the original apply had per-sub-op failures: the dup
                # ack must repeat them, or a replay whose first ack was
                # lost would resolve the failed sub-ops as successes
                dup.update(chan.failed.get(seq, ()))
                return dup, []
            rmeta, rarrays = self._handle(msg_type, meta, arrays)
            # commit AFTER a successful apply: an apply that raised
            # must stay replayable (at-least-once on failure; the
            # client sees the error either way). A batch with per-
            # sub-op failures still consumes the frame — those are
            # REPORTED per op in the reply (and memoized for dup
            # acks), never silently retried.
            if chan is None:
                chan = self._replay_seq[cl] = _SeqChannel()
            chan.commit(seq)
            if rmeta.get("failed"):
                chan.note_failed(seq, rmeta)
            rmeta = dict(rmeta)
            rmeta[wire.REPLAY_DURABLE_KEY] = self._durable_floor.get(
                cl, -1)
        return rmeta, rarrays

    def mark_durable(self, floors: Dict[str, int]) -> None:
        """Advance the durable (checkpointed) channel floors — called
        by the ShardCheckpointer after a COMMITTED save whose snapshot
        carried exactly these channels. From here on stamped replies
        tell clients that sequences at or below their floor survive a
        crash, so their retention buffers may prune them."""
        with self._stamp_lock:
            self._durable_floor = dict(floors)

    # ------------------------------------------------------------------ #
    # failover checkpoint surface (checkpoint.save_shard_state):
    # one atomic (meta, arrays) snapshot of everything a restarted
    # incarnation needs — data rows, updater state, replay marks,
    # mutation version
    # ------------------------------------------------------------------ #
    def _native_mutex(self):
        """Context manager holding the native shard mutex when this
        shard is natively registered (C++ serving threads mutate the
        buffer under THAT mutex, not ``_lock`` — a checkpoint snapshot
        racing them would tear rows); no-op otherwise."""
        import contextlib
        if self._native_ref is None:
            return contextlib.nullcontext()
        from multiverso_tpu.ps import native as ps_native

        @contextlib.contextmanager
        def held(pin=self._native_ref):
            ps_native.shard_pin_lock(pin)
            try:
                yield
            finally:
                ps_native.shard_pin_unlock(pin)

        return held()

    def checkpoint_state(self) -> Tuple[Dict, List[np.ndarray]]:
        """Consistent shard snapshot for the per-shard failover
        checkpoint. Taken under ``_stamp_lock`` + the shard lock (plus
        the native shard mutex when C++ serves this shard) so the
        replay marks and the data agree exactly (see _handle_stamped);
        every array is an OWNED host copy — a donating apply right
        after release must not invalidate the bytes being written.
        Lock ORDER matters: the native mutex comes FIRST, matching the
        punt path (locked_handler holds it around handle(), which then
        takes _stamp_lock) — the reverse order deadlocks a stamped
        punted frame against a concurrent checkpoint."""
        with self._native_mutex(), self._stamp_lock:
            # grouped shards additionally hold the PLANE lock across the
            # (version, bytes) read: a cross-shard SPMD apply bumps the
            # version under the plane lock WITHOUT this shard's lock, so
            # the shard lock alone no longer makes the pair atomic
            with self._lock, self._plane_lock():
                chans = {k: v.to_dict()
                         for k, v in self._replay_seq.items()}
                version = self._version
                if self._np_mode:
                    data = self._data[: self.n].copy()
                else:
                    data = np.asarray(self._data)[: self.n].copy()
                leaves = [np.asarray(l).copy()
                          for l in jax.tree.leaves(self._ustate)]
        meta = {"kind": "row", "lo": self.lo, "rows": self.n,
                "cols": self.num_col, "dtype": str(self.dtype),
                "version": int(version), "replay": chans,
                "n_leaves": len(leaves)}
        return meta, [data] + leaves

    def restore_checkpoint(self, meta: Dict,
                           arrays: Sequence[np.ndarray]) -> None:
        """Adopt a :meth:`checkpoint_state` snapshot — the restore half
        of shard failover. Dirty bits reset to all-True (sparse workers
        re-pull everything; safe, never wrong), and the restored replay
        marks become BOTH the applied and the durable high-water marks:
        the restored state is by definition exactly what the checkpoint
        made durable."""
        if meta.get("kind") != "row":
            raise svc.PSError(f"{self.name}: checkpoint kind "
                              f"{meta.get('kind')!r} is not a row shard")
        if (int(meta["lo"]) != self.lo or int(meta["rows"]) != self.n
                or int(meta["cols"]) != self.num_col):
            raise svc.PSError(
                f"{self.name}: checkpoint shard [{meta['lo']}, "
                f"{int(meta['lo']) + int(meta['rows'])})x{meta['cols']} "
                f"!= live [{self.lo}, {self.hi})x{self.num_col} — "
                "partition changed since the save")
        data, leaves = arrays[0], list(arrays[1:])
        # a grouped shard restores into CLASSIC storage (the restore
        # rebinds the buffer wholesale — exactly the mutation shape the
        # stacked plane evicts on)
        self._plane_evict()
        # native mutex FIRST (same order rule as checkpoint_state)
        with self._native_mutex(), self._stamp_lock:
            with self._lock:
                flat, treedef = jax.tree.flatten(self._ustate)
                if len(leaves) != len(flat):
                    raise svc.PSError(
                        f"{self.name}: checkpoint has {len(leaves)} "
                        f"updater-state leaves, shard expects "
                        f"{len(flat)}")
                for got, want in zip(leaves, flat):
                    if tuple(np.shape(got)) != tuple(np.shape(want)):
                        raise svc.PSError(
                            f"{self.name}: updater-state leaf shape "
                            f"{np.shape(got)} != {np.shape(want)}")
                if self._np_mode:
                    # in place: a natively-registered shard's C++ side
                    # holds the raw pointer, so the buffer never swaps
                    self._data[: self.n] = np.asarray(data, self.dtype)
                else:
                    host = np.zeros(self._padded, self.dtype)
                    host[: self.n] = np.asarray(data, self.dtype)
                    self._data = self._place_rows(host)
                new = [jnp.asarray(np.asarray(a, np.asarray(w).dtype))
                       for a, w in zip(leaves, flat)]
                self._ustate = jax.tree.unflatten(treedef, new)
                if self._local_sharding is not None:
                    self._ustate = jax.tree.map(self._place_state_local,
                                                self._ustate)
                self._adopt_replay_channels(meta)
                self._version = int(meta.get("version", 0))
                if self._dirty is not None:
                    self._dirty[:] = True
        _flight.record(_flight.EV_FAILOVER_RESTORE,
                       note=f"{self.name} v{meta.get('version', 0)}")

    def _adopt_replay_channels(self, meta: Dict) -> None:
        """Rebuild the replay channels from a checkpoint's ``replay``
        block (caller holds ``_stamp_lock``). The restored channels are
        BOTH the applied and the durable marks: the restored state is
        by definition exactly what the checkpoint made durable."""
        self._replay_seq = {str(k): _SeqChannel.from_dict(v)
                            for k, v in (meta.get("replay")
                                         or {}).items()}
        self._durable_floor = {k: c.floor
                               for k, c in self._replay_seq.items()}

    # exotic mutations evict a grouped shard back to classic storage
    # first (always-safe; the stacked fast path is for row add/get
    # traffic — docs/HOSTPLANE.md "Mesh-sharded data plane")
    _EVICT_TYPES = frozenset()   # filled below, after svc constants

    def _handle(self, msg_type: int, meta: Dict,
                arrays: Sequence[np.ndarray]
                ) -> Tuple[Dict, List[np.ndarray]]:
        if self._plane is not None and msg_type in self._EVICT_TYPES:
            self._plane_evict()
        if msg_type == svc.MSG_ADD_ROWS:
            local, vals, opt = self._prep_add(meta, arrays)
            tr = (meta.get(wire.TRACE_META_KEY)
                  if _trace.enabled() else None)
            t0 = time.time() if tr is not None else 0.0
            self._add_rows(local, vals, opt)
            if tr is not None:
                # the plain-frame analogue of the batch path's
                # shard.wave_apply span (a 1-op window ships as a plain
                # MSG_ADD_ROWS frame, not a MSG_BATCH)
                _trace.add_span("shard.apply", t0, time.time(), trace=tr,
                                args={"table": self.name, "traces": [tr]})
            return {}, []
        if msg_type == svc.MSG_BATCH:
            # a client send window: N logical adds in one frame, one ack
            return self._handle_batch(meta, arrays)
        if msg_type == svc.MSG_GET_ROWS and meta.get("sparse"):
            # stale-only reply for meta["worker_id"] (ref matrix.cpp
            # :475-483 GetOption.worker_id + :540-572 stale filter)
            wid = int(meta.get("worker_id", 0))
            local = self._localize_raw(arrays[0])
            self._note_rows(local)
            with self._lock:
                if self._dirty is None:
                    raise svc.PSError(
                        f"{self.name} was not created with num_workers; "
                        "sparse gets need dirty-bit tracking")
                # mask snapshot + clear ATOMIC with the epoch pin: an add
                # applying after this lock releases re-SETS bits on rows
                # we serve from the pinned (older) epoch, so the next get
                # re-pulls them — nothing lost. Pinning outside this hold
                # (or clearing after it) would open a set-then-lose
                # window: an apply between clear and gather could mutate
                # rows whose cleared bits claim THIS reply carries them.
                mask = self._dirty[wid, local].copy()
                self._dirty[wid, local] = False
                pin = self._pin_data_locked()
            _flight.record(_flight.EV_GET_SERVE,
                           nbytes=int(mask.sum()) * self.num_col
                           * self.dtype.itemsize)
            try:
                stale = local[mask]
                if stale.size:
                    rows = self._gather_rows(stale, data=pin.data)
                else:
                    rows = np.zeros((0, self.num_col), self.dtype)
            finally:
                self._release_data(pin)
            self._stat_gets += 1
            # sparse replies ship [mask, stale rows] uncompressed: that
            # pair IS the wire payload
            self._stat_get_bytes += mask.nbytes + rows.nbytes
            self._tenants.note(meta.get(wire.TENANT_META_KEY),
                               get_bytes=mask.nbytes + rows.nbytes)
            return {}, [mask, rows]
        if msg_type == svc.MSG_GET_ROWS:
            return self._serve_get_rows(meta, arrays)
        if msg_type == svc.MSG_SET_ROWS:
            ids, k = self._localize(arrays[0])
            vals = np.asarray(arrays[1], self.dtype)[:k]
            with self._lock:
                if self._np_mode:
                    self._writable_data()[ids[:k]] = vals
                else:
                    # eager .at[].set: non-donating — pinned epochs stay
                    # valid; the rebind retires their pin count
                    self._data = self._data.at[ids[:k]].set(
                        jnp.asarray(vals))
                if self._dirty is not None:
                    self._dirty[:, ids[:k]] = True
                self._version += 1
            return {}, []
        if msg_type == svc.MSG_ADD_FULL:
            opt = AddOption(**meta.get("opt", {}))
            delta = wire.decode_payload(arrays, meta.get("wire", "none"),
                                        (self.n, self.num_col), self.dtype)
            with self._lock:
                if self._np_mode:
                    data = self._writable_data()
                    sign = _LINEAR_SIGN[type(self.updater)]
                    if sign > 0:
                        data[: self.n] += delta
                    else:
                        data[: self.n] -= delta
                else:
                    padded = np.zeros(self._padded, self.dtype)
                    padded[: self.n] = delta
                    donate = not self._data_pinned()
                    if not donate:
                        self._stat_cow += 1
                    self._data, self._ustate = self._full_update_fn(
                        donate)(self._data, self._ustate,
                                jnp.asarray(padded), opt)
                if self._dirty is not None:
                    self._dirty[:] = True
                self._version += 1
            return {}, []
        if msg_type == svc.MSG_GET_FULL:
            return self._serve_get_full(meta)
        if msg_type == svc.MSG_SNAPSHOT:
            # replica subscription pull (serving plane)
            return self.export_snapshot(meta)
        if msg_type == svc.MSG_GET_STATE:
            # updater-state leaves, full precision (checkpoint plumbing:
            # the sync table persists ustate, table.py store(); async
            # shards must too or a restore silently resets accumulators)
            with self._lock:
                leaves = [np.asarray(l)
                          for l in jax.tree.leaves(self._ustate)]
            return {"n_leaves": len(leaves)}, leaves
        if msg_type == svc.MSG_SET_STATE:
            with self._lock:
                flat, treedef = jax.tree.flatten(self._ustate)
                if len(arrays) != len(flat):
                    raise svc.PSError(
                        f"{self.name}: checkpoint has {len(arrays)} updater-"
                        f"state leaves, shard expects {len(flat)} (was the "
                        "table created with a different updater?)")
                for got, want in zip(arrays, flat):
                    if tuple(got.shape) != tuple(np.shape(want)):
                        raise svc.PSError(
                            f"{self.name}: updater-state leaf shape "
                            f"{got.shape} != {np.shape(want)} (partition "
                            "changed since the checkpoint?)")
                leaves = [jnp.asarray(np.asarray(a, dtype=np.asarray(w).dtype))
                          for a, w in zip(arrays, flat)]
                self._ustate = jax.tree.unflatten(treedef, leaves)
                if self._local_sharding is not None:
                    self._ustate = jax.tree.map(self._place_state_local,
                                                self._ustate)
                self._version += 1
            return {}, []
        raise svc.PSError(f"unknown message type {msg_type}")


RowShard._EVICT_TYPES = frozenset(
    (svc.MSG_SET_ROWS, svc.MSG_ADD_FULL, svc.MSG_SET_STATE))


class HashShard(RowShard):
    """Sparse-key shard: arbitrary non-negative int64 keys map to device
    row slots allocated on first touch — the owner-side storage of the
    reference's app-defined sparse tables (ref Applications/
    LogisticRegression/src/util/sparse_table.h:1-306 hash-stored
    SparseServerTable; util/ftrl_sparse_table.h:1-90 FTRL z/n payloads,
    which arrive here as updater state on the row axis). The slot buffer
    doubles on demand; a plain Get of a never-added key returns the
    initial row (zeros — exactly FTRL's w for empty z/n) WITHOUT
    allocating, so dense sweeps over a huge key space cost no server
    memory. Adds, set_rows, and sparse (dirty-bit) gets allocate — those
    are keys the workload actually touches."""

    def __init__(self, num_col: int, dtype, updater: Updater, name: str,
                 capacity: int = 1024, num_workers: int = 0):
        super().__init__(0, capacity, num_col, dtype, updater, name,
                         num_workers=num_workers)
        self._slot_of: Dict[int, int] = {}
        self._nw = num_workers

    @property
    def keys(self) -> List[int]:
        with self._lock:
            return list(self._slot_of)

    def stats(self) -> Dict[str, Any]:
        out = super().stats()
        out["kind"] = "hash"
        with self._lock:
            out["keys"] = len(self._slot_of)
        return out

    def export_snapshot(self, meta: Dict) -> Tuple[Dict, Any]:
        """Hash shards have no stable global row space to replicate —
        slot order is allocation order and changes across restores, so
        a positional snapshot would silently serve the wrong keys.
        Replica support for keyed tables means shipping (keys, rows)
        pairs and a keyed replica read path; refuse loudly until that
        exists rather than serve garbage."""
        raise svc.PSError(
            f"{self.name}: read replicas support row-partitioned "
            "shards only (hash-sharded tables have no stable "
            "positional row space)")

    def _note_rows(self, local: np.ndarray) -> None:
        """No-op: the inherited serve paths reach here with SLOT ids.
        Hash-shard traffic records through :meth:`_note_keys` at the
        key-validation sites instead — the sketch must rank the
        workload's KEYS (DLRM user ids etc.), not slot allocation
        order."""

    def _note_keys(self, keys: np.ndarray) -> None:
        if self._hotkeys is not None:
            self._hotkeys.observe(keys)

    def _grow(self, need: int) -> None:
        old_padded = self._padded
        old_rows = old_padded[0]
        new_n = max(self.n, 1)
        while new_n < need:
            new_n *= 2
        if self._local_sharding is not None:
            # keep the device-multiple row padding the GSPMD layout needs
            ndev = self._local_sharding.mesh.devices.size
            rows = _ceil_to(new_n + 1, ndev)
        else:
            rows = new_n + 1

        def grow(leaf):
            arr = np.asarray(leaf)
            nd, pd = arr.ndim, len(old_padded)
            if nd >= pd and arr.shape[nd - pd:] == old_padded:
                axis = nd - pd
                widths = [(0, 0)] * nd
                widths[axis] = (0, rows - old_rows)
                return np.pad(arr, widths)
            return leaf

        data = grow(self._data)
        ustate = jax.tree.map(grow, self._ustate)
        if self._dirty is not None:
            self._dirty = np.pad(
                self._dirty, [(0, 0), (0, new_n - self.n)],
                constant_values=True)
        self.n = self.hi = new_n
        self._padded = (rows, self.num_col)
        # re-place AFTER _padded is updated: the grown buffers must keep
        # the size-gated local-device sharding, not silently collapse to
        # one device exactly when the table gets big enough to matter
        self._data = self._place_rows(data)
        if self._local_sharding is not None:
            self._ustate = jax.tree.map(
                lambda l: (self._place_state_local(l)
                           if isinstance(l, np.ndarray) else l), ustate)
        else:
            self._ustate = jax.tree.map(
                lambda l: jnp.asarray(l) if isinstance(l, np.ndarray) else l,
                ustate)

    def _apply_rows(self, keys: np.ndarray, vals: np.ndarray,
                    opt) -> None:
        """Queued add entries carry KEYS; translate to slots here, under
        the same lock hold as the update itself (allocation, grow, and
        apply stay atomic — a restore rebuilding the slot map can never
        interleave between translation and apply)."""
        super()._apply_rows(self._slots_for(keys), vals, opt)

    def _validate_keys(self, arr) -> np.ndarray:
        """Shared key validation (per-op adds, batched sub-ops, gets)."""
        keys = np.asarray(arr, np.int64)
        if keys.size == 0:
            raise IndexError(f"{self.name}: empty key batch")
        if np.any(keys < 0):
            raise IndexError(f"{self.name}: negative keys")
        return keys

    def _prep_add_entry(self, meta: Dict, arrays: Sequence[np.ndarray]
                        ) -> _PendingAdd:
        """Batched sub-ops carry KEYS (validated here); key -> slot
        translation stays at apply time inside :meth:`_apply_rows`,
        atomic with the update (same rule as the coalescing queue)."""
        keys = self._validate_keys(arrays[0])
        self._note_keys(keys)
        opt = AddOption(**meta.get("opt", {}))
        vals = np.asarray(arrays[1], self.dtype)[: keys.size]
        # encoded request blobs, per request — same rule as _prep_add
        self._stat_add_bytes += sum(int(getattr(a, "nbytes", 0))
                                    for a in arrays[1:])
        return _PendingAdd(keys, vals, opt,
                           trace=meta.get(wire.TRACE_META_KEY))

    def _slots_for(self, keys: np.ndarray) -> np.ndarray:
        """key -> slot, allocating unseen keys (under the caller's lock)."""
        out = np.empty(keys.size, np.int64)
        fresh = [i for i, k in enumerate(keys.tolist())
                 if k not in self._slot_of]
        if len(self._slot_of) + len(fresh) > self.n:
            self._grow(len(self._slot_of) + len(fresh))
        for i, k in enumerate(keys.tolist()):
            slot = self._slot_of.get(k)
            if slot is None:
                slot = self._slot_of[k] = len(self._slot_of)
            out[i] = slot
        return out

    def checkpoint_state(self) -> Tuple[Dict, List[np.ndarray]]:
        """Hash-shard failover snapshot: the (keys, rows, state-leaf)
        dump plus replay marks/version, same atomicity as RowShard's."""
        with self._stamp_lock:
            with self._lock:
                chans = {k: v.to_dict()
                         for k, v in self._replay_seq.items()}
                version = self._version
                _, arrs = self._dump()
        meta = {"kind": "hash", "cols": self.num_col,
                "dtype": str(self.dtype), "version": int(version),
                "replay": chans, "n_leaves": max(len(arrs) - 2, 0)}
        return meta, [np.ascontiguousarray(a) for a in arrs]

    def restore_checkpoint(self, meta: Dict,
                           arrays: Sequence[np.ndarray]) -> None:
        if meta.get("kind") != "hash":
            raise svc.PSError(f"{self.name}: checkpoint kind "
                              f"{meta.get('kind')!r} is not a hash shard")
        with self._stamp_lock:
            with self._lock:
                self._restore(arrays)
                self._adopt_replay_channels(meta)
                self._version = int(meta.get("version", 0))
        _flight.record(_flight.EV_FAILOVER_RESTORE,
                       note=f"{self.name} v{meta.get('version', 0)}")

    def _handle(self, msg_type: int, meta: Dict,
                arrays: Sequence[np.ndarray]
                ) -> Tuple[Dict, List[np.ndarray]]:
        if msg_type in (svc.MSG_ADD_FULL, svc.MSG_GET_FULL):
            raise svc.PSError(
                f"{self.name}: hash-sharded table has no dense whole-table "
                "plane; use row/key ops")
        if msg_type == svc.MSG_ADD_ROWS:
            # adds ride the coalescing queue OUTSIDE the lock (a waiter
            # holding the RLock would deadlock the applier); entries carry
            # KEYS and _apply_rows translates key->slot at APPLY time,
            # atomic with the update — slots resolved at enqueue time
            # could go stale if a checkpoint restore rebuilds the slot map
            # in between
            entry = self._prep_add_entry(meta, arrays)
            t0 = (time.time()
                  if _trace.enabled() and entry.trace is not None else 0.0)
            self._add_rows(entry.local, entry.vals, entry.opt)
            if t0:
                _trace.add_span("shard.apply", t0, time.time(),
                                trace=entry.trace,
                                args={"table": self.name,
                                      "traces": [entry.trace]})
            return {}, []
        if msg_type == svc.MSG_GET_ROWS and not meta.get("sparse"):
            # allocation-free read: unknown keys gather the scratch row,
            # which is invariantly zeros (padded adds apply zero deltas
            # to it). Key->slot translation is atomic with the epoch pin
            # (one lock hold); the gather + encode run off-lock like the
            # range-sharded shard's.
            keys = self._validate_keys(arrays[0])
            self._note_keys(keys)
            tr = (meta.get(wire.TRACE_META_KEY) if _trace.enabled()
                  else None)
            t0 = time.time() if tr is not None else 0.0
            with self._lock:
                slots = np.array(
                    [self._slot_of.get(k, self.n)
                     for k in keys.tolist()], np.int64)
                pin = self._pin_data_locked()
            if tr is not None:
                _trace.add_span("shard.get_pin", t0, time.time(),
                                trace=tr, args={"table": self.name,
                                                "rows": int(keys.size)})
            return self._serve_rows_from_pin(pin, slots, meta, tr)
        keys = None
        if msg_type in (svc.MSG_GET_ROWS, svc.MSG_SET_ROWS):
            # validate + sketch-record OFF the shard lock, like every
            # other serve path: up to ~0.5 ms of sampled sketch work on
            # a big sparse key batch must not stall applies behind
            # telemetry (the reads-block-applies coupling PR 5 removed)
            keys = self._validate_keys(arrays[0])
            if msg_type == svc.MSG_GET_ROWS:   # sparse keyed get
                self._note_keys(keys)
        with self._lock:   # reentrant: key->slot stays atomic w/ the update
            if msg_type == svc.MSG_GET_STATE and meta.get("dump"):
                return self._dump()
            if msg_type == svc.MSG_SET_STATE and meta.get("dump"):
                return self._restore(arrays)
            if keys is not None:
                slots = self._slots_for(keys)
                arrays = [slots] + list(arrays[1:])
            # _handle, not handle: the replay gate already ran at this
            # request's entry point (HashShard.handle inherits it) —
            # re-entering it here would dup-check the frame twice
            return super()._handle(msg_type, meta, arrays)

    # ------------------------------------------------------------------ #
    # checkpoint: (keys, rows, per-key updater state) — the reference left
    # KV/sparse Store/Load stubbed (kv_table.h:101-119); here it is real
    # ------------------------------------------------------------------ #
    def _dump(self) -> Tuple[Dict, List[np.ndarray]]:
        keys = np.array(sorted(self._slot_of), np.int64)
        slots = np.array([self._slot_of[k] for k in keys.tolist()], np.int64)
        if keys.size:
            rows = self._gather_rows(slots)
        else:
            rows = np.zeros((0, self.num_col), self.dtype)
        leaves = []
        for leaf in jax.tree.leaves(self._ustate):
            axis = self._state_row_axis(leaf)
            arr = np.asarray(leaf)
            if axis >= 0:
                leaves.append(np.take(arr, slots, axis=axis))
            else:
                leaves.append(arr)
        return ({}, [keys, rows] + leaves)

    def _restore(self, arrays: Sequence[np.ndarray]
                 ) -> Tuple[Dict, List[np.ndarray]]:
        keys, rows = np.asarray(arrays[0], np.int64), arrays[1]
        leaves_in = list(arrays[2:])
        self._slot_of = {}
        self.n = self.hi = 0
        self._padded = (1, self.num_col)
        self._data = self._place_rows(np.zeros(self._padded, self.dtype))
        self._ustate = self.updater.init_state(self._padded, self.dtype)
        if self._dirty is not None:
            self._dirty = np.ones((self._nw, 0), bool)
        if keys.size == 0:
            return {}, []
        slots = self._slots_for(keys)
        data = np.array(self._data)   # writable copy
        data[slots] = np.asarray(rows, self.dtype)
        self._data = self._place_rows(data)
        flat, treedef = jax.tree.flatten(self._ustate)
        if len(leaves_in) != len(flat):
            raise svc.PSError(
                f"{self.name}: checkpoint has {len(leaves_in)} updater-state "
                f"leaves, expected {len(flat)}")
        out = []
        for got, want in zip(leaves_in, flat):
            arr = np.asarray(want).copy()
            axis = self._state_row_axis(want)
            if axis >= 0:
                idx = (slice(None),) * axis + (slots,)
                arr[idx] = np.asarray(got, arr.dtype)
            else:
                arr = np.asarray(got, arr.dtype)
            out.append(self._place_state_local(arr)
                       if self._local_sharding is not None
                       else jnp.asarray(arr))
        self._ustate = jax.tree.unflatten(treedef, out)
        if self._dirty is not None:
            self._dirty = np.ones((self._nw, self.n), bool)
        return {}, []


class KVShard:
    """Hash-sharded key-value shard (ref include/multiverso/table/
    kv_table.h:44-54 — ``key % num_servers`` routing; the server-side map
    holds the global aggregate for its keys). Host-side dict: scalar KV
    traffic has no business on the MXU."""

    def __init__(self, name: str):
        self.name = name
        self._store: Dict[int, float] = {}
        self._lock = threading.Lock()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"kind": "kv", "keys": len(self._store)}

    def handle(self, msg_type: int, meta: Dict,
               arrays: Sequence[np.ndarray]
               ) -> Tuple[Dict, List[np.ndarray]]:
        if msg_type == svc.MSG_KV_ADD:
            keys, vals = arrays
            with self._lock:
                for k, v in zip(keys.tolist(), vals.tolist()):
                    self._store[int(k)] = self._store.get(int(k), 0) + v
            return {}, []
        if msg_type == svc.MSG_KV_GET:
            with self._lock:
                if meta.get("all"):
                    items = sorted(self._store.items())
                    keys = np.array([k for k, _ in items], np.int64)
                    vals = np.array([v for _, v in items], np.float64)
                else:
                    keys = np.asarray(arrays[0], np.int64)
                    vals = np.array(
                        [self._store.get(int(k), 0) for k in keys],
                        np.float64)
            return {}, [keys, vals]
        raise svc.PSError(f"unknown message type {msg_type}")
