"""Elastic shard failover: checkpointed handoff + supervised recovery.

The detection plane already exists — watchdog ``dead|stuck`` verdicts
(telemetry/watchdog.py), heartbeat staleness and PS-death tombstones
(elastic.py), MSG_HEALTH one-shot probes that answer even against a
wedged data plane (ps/service.py) — but until now nothing *recovered*:
a dead rank stayed dead and its shard's rows went dark (ROADMAP open
item 5; the reference's whole story was "checkpoint files only",
SURVEY §5). This module closes the loop, the way classic PS systems do
(Li et al., OSDI'14 §4.3 — server state replicated/recovered, sender-
side logs replayed):

* :class:`ShardCheckpointer` — a per-rank background thread writing
  per-shard incremental checkpoints (``checkpoint.save_shard_state``:
  data rows + updater state + replay sequence channels + apply version,
  commit-marker-last so a torn save is invisible) every
  ``failover_ckpt_interval_s``. After each COMMITTED save it advances
  the shards' durable replay floors, which is what lets clients prune
  their retained send-window frames (ps/tables._ReplayBuffer).

* :class:`FailoverSupervisor` — polls ``elastic.health()`` (beacon
  staleness + tombstones + watchdog verdicts), confirms each
  ``dead|stuck`` suspect with a MSG_HEALTH one-shot probe at its
  published address (a half-written beacon must not kill a healthy
  rank), then drives recovery: kill the old incarnation (``kill``
  callback — a SIGSTOPPED process still owns its sockets), tombstone
  it, respawn the rank (``spawn`` callback: an OS process for real
  deployments, an in-process service for tests) at the next
  generation, and watch for the rejoin (a fresh beacon from the new
  incarnation clearing the tombstone). Every phase lands in the
  flight recorder (EV_FAILOVER_*) so ``tools/postmortem.py`` renders
  the recovery timeline.

* :func:`rejoin` — the restarted incarnation's first act: restore its
  own shards from the newest committed per-shard checkpoint, then
  announce liveness. Clients re-route through the existing per-rank
  reconnect path (rendezvous re-resolution after the backoff window)
  and their send windows re-flush the retained frame tail; the
  restored shard's sequence channels dedupe the prefix the checkpoint
  already holds — no acked op lost, no frame applied twice
  (docs/FAILOVER.md).

The supervisor is transport-free by design: it reads beacons and
``<rank>.addr`` files from shared directories and probes over one-shot
sockets, so it can run inside a worker, in a sidecar, or in the chaos
bench's parent process with equal fidelity.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from multiverso_tpu import checkpoint, elastic
from multiverso_tpu.telemetry import flightrec as _flight
from multiverso_tpu.utils import config, log

config.define_float("failover_timeout", 10.0,
                    "seconds of beacon staleness before the failover "
                    "supervisor treats a rank as dead (tombstoned PS "
                    "deaths short-circuit this; docs/FAILOVER.md)")
config.define_float("failover_poll_s", 0.5,
                    "failover supervisor poll interval seconds")
config.define_float("failover_ckpt_interval_s", 0.0,
                    "per-shard incremental checkpoint cadence seconds; "
                    "> 0 (with failover_dir set) starts a "
                    "ShardCheckpointer with each PSService — the "
                    "durable half of exactly-once replay. 0 = off")
config.define_string("failover_dir", "",
                     "directory for per-shard failover checkpoints "
                     "(local/NFS; shard-r<rank>/v<N> tags inside)")
config.define_int("failover_ckpt_keep", 2,
                  "committed per-shard checkpoint tags kept per rank")
config.define_int("ps_generation", 0,
                  "this process's shard incarnation generation; the "
                  "failover supervisor spawns each replacement at the "
                  "previous generation + 1, and MSG_HEALTH echoes it "
                  "so mvtop shows a restarted rank at a glance")


def read_addr(rendezvous_dir: str, rank: int) -> Optional[str]:
    """``rank``'s published address straight off a file-rendezvous
    directory (no PSService needed — the supervisor may live in a
    process that serves nothing)."""
    try:
        with open(os.path.join(rendezvous_dir, f"{rank}.addr")) as f:
            addr = f.read().strip()
        return addr or None
    except OSError:
        return None


def rejoin(directory: str, rank: int, tables,
           heartbeat: Optional["elastic.Heartbeat"] = None,
           service=None) -> int:
    """Restarted-incarnation boot: restore this rank's shards from its
    newest committed per-shard checkpoint (0 restored = cold start —
    a rank that died before its first save simply rejoins empty), THEN
    announce the new incarnation: publish the deferred rendezvous
    address (``service`` built with ``defer_publish=True`` — a
    survivor must not discover the address while the shard is still
    empty, or a replayed frame could apply, ack, and be wiped by this
    very restore) and beat the heartbeat so the supervisor and the
    tombstone plane see the fresh incarnation immediately. Returns
    shards restored."""
    n = checkpoint.restore_shard_state(directory, rank, tables)
    _flight.record(_flight.EV_FAILOVER_REJOIN,
                   note=f"rank {rank}: {n} shards restored")
    if service is not None:
        service.publish_addr()
    if heartbeat is not None:
        heartbeat.beat()
    return n


class ShardCheckpointer:
    """Periodic per-shard checkpointer for one rank (the durable half
    of failover). ``tables`` may be a list of async tables, a
    ``{name: shard}`` dict, or a zero-arg callable returning either —
    the service wiring passes a callable so shards registered after
    start are picked up."""

    def __init__(self, directory: str, rank: int, tables,
                 interval_s: float = 1.0, keep: int = 2):
        self.directory = directory
        self.rank = int(rank)
        self._tables = tables if callable(tables) else (lambda: tables)
        self.interval_s = float(interval_s)
        self.keep = int(keep)
        self.saves = 0
        self.errors = 0
        self.last_path: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # memory ledger (telemetry/memstats.py): on-disk footprint of
        # this rank's kept checkpoint tags. The walk runs ONCE here
        # and once after each committed save/prune — the only moments
        # the size changes — never on a ledger pull: pulls ride the
        # watchdog's 0.5 s liveness sweep, and repeated synchronous
        # directory walks (arbitrarily slow on NFS) would stall it
        self._disk_bytes = checkpoint._dir_bytes(
            checkpoint._shard_base(directory, self.rank))
        from multiverso_tpu.telemetry import memstats as _memstats
        _memstats.register(f"failover_ckpt[r{self.rank}]", self)

    def memory_stats(self) -> Dict[str, int]:
        return {"disk_bytes": int(self._disk_bytes),
                "saves": self.saves}

    def checkpoint_now(self) -> Optional[str]:
        """One committed save + prune; returns the tag path (None when
        the rank currently owns nothing checkpointable)."""
        tables = self._tables()
        if not tables:
            return None
        path = checkpoint.save_shard_state(self.directory, self.rank,
                                           tables)
        checkpoint.prune_shard_tags(self.directory, self.rank, self.keep)
        self.saves += 1
        self.last_path = path
        self._disk_bytes = checkpoint._dir_bytes(
            checkpoint._shard_base(self.directory, self.rank))
        return path

    def start(self) -> "ShardCheckpointer":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"mv-shardckpt-{self.rank}")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.checkpoint_now()
            except Exception as e:   # noqa: BLE001 — one failed save
                self.errors += 1     # must not kill the cadence
                log.error("shard checkpoint failed (rank %d): %s: %s",
                          self.rank, type(e).__name__, e)

    def stop(self, final: bool = True) -> None:
        """Stop the cadence; ``final=True`` writes one last committed
        save so a clean shutdown's tail of applies is never lost."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 10)
            self._thread = None
        if final:
            try:
                self.checkpoint_now()
            except Exception as e:   # noqa: BLE001
                log.error("final shard checkpoint failed (rank %d): %s",
                          self.rank, e)


class FailoverSupervisor:
    """Detect → confirm → kill → respawn → watch-rejoin, per rank.

    ``spawn(rank, generation)`` relaunches the rank (REQUIRED for
    recovery; without it the supervisor only detects and tombstones).
    ``kill(rank)`` terminates the old incarnation first — a SIGSTOPPED
    process still holds its listen socket and would fight its
    replacement for the published address. Both callbacks run on the
    supervisor thread; exceptions are logged, never raised into the
    loop. ``events`` is the recovery log the chaos bench and tests
    read: ``(wall_ts, phase, rank)`` with phase in
    detect|respawn|rejoin."""

    def __init__(self, heartbeat_dir: str, world: int,
                 rendezvous_dir: Optional[str] = None,
                 spawn: Optional[Callable[[int, int], None]] = None,
                 kill: Optional[Callable[[int], None]] = None,
                 timeout: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 confirm: bool = True,
                 respawn_grace: Optional[float] = None,
                 ranks: Optional[List[int]] = None):
        self.heartbeat_dir = heartbeat_dir
        self.rendezvous_dir = rendezvous_dir
        self.world = int(world)
        self.ranks = list(ranks) if ranks is not None \
            else list(range(self.world))
        self.spawn = spawn
        self.kill = kill
        self.timeout = (config.get_flag("failover_timeout")
                        if timeout is None else float(timeout))
        self.poll_s = (config.get_flag("failover_poll_s")
                       if poll_s is None else float(poll_s))
        self.confirm = confirm
        # a replacement needs real time to boot (a JAX worker imports
        # for seconds before its first beacon): re-declaring it dead on
        # the detection timeout would kill our own respawn in a storm
        self.respawn_grace = (max(3.0 * self.timeout, 15.0)
                              if respawn_grace is None
                              else float(respawn_grace))
        self.events: List[Tuple[float, str, int]] = []
        self._gen: Dict[int, int] = {}
        self._recovering: Dict[int, float] = {}   # rank -> respawn t0
        self._seen: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #
    def start(self) -> "FailoverSupervisor":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mv-failover")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_s + 10)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check_once()
            except Exception as e:   # noqa: BLE001 — the loop survives
                log.error("failover supervisor poll failed: %s: %s",
                          type(e).__name__, e)

    # ------------------------------------------------------------------ #
    def check_once(self) -> Dict[int, str]:
        """One poll: health verdicts in, recovery actions out. Returns
        the verdict map (tests assert on it)."""
        verdicts = elastic.health(self.heartbeat_dir,
                                  timeout=self.timeout)
        for r, v in verdicts.items():
            if v == "ok":
                self._seen.add(r)
        for r in self.ranks:
            v = verdicts.get(r)
            if v == "ok":
                self._note_rejoin(r)
                continue
            if v is None and r not in self._seen:
                continue   # never came up: not this supervisor's call
            with self._lock:
                if r in self._recovering:
                    # respawn in flight: give it the full grace to boot
                    # and publish a fresh beacon before declaring it
                    # dead AGAIN (a respawn storm would thrash
                    # checkpoints and kill its own replacements)
                    if (time.monotonic() - self._recovering[r]
                            < self.respawn_grace):
                        continue
                    del self._recovering[r]
            if self.confirm and not self._confirm_down(r):
                continue
            self._recover(r, v or "dead")
        return verdicts

    def _confirm_down(self, rank: int) -> bool:
        """MSG_HEALTH one-shot probe at the published address: only a
        probe that fails (or answers ``stuck``) confirms the verdict —
        heartbeat staleness alone can be a wedged NFS client, and a
        healthy rank must never be killed over it. No address on file
        counts as confirmation (nothing to probe)."""
        if self.rendezvous_dir is None:
            return True
        addr = read_addr(self.rendezvous_dir, rank)
        if addr is None:
            return True
        from multiverso_tpu.ps import service as svc
        try:
            # triage-scale budget, floored: a tiny/zero detection
            # timeout must not starve the probe into a false "down"
            h = svc.oneshot_probe(
                addr, svc.MSG_HEALTH,
                max(min(config.get_flag("ps_health_timeout"),
                        self.timeout), 0.5))
            return h.get("status") == "stuck"
        except Exception:   # noqa: BLE001 — unreachable IS the answer
            return True

    def _recover(self, rank: int, verdict: str) -> None:
        now = time.time()
        self.events.append((now, "detect", rank))
        _flight.record(_flight.EV_FAILOVER_DETECT, peer=rank,
                       note=f"verdict={verdict}")
        log.error("failover: rank %d is %s — recovering", rank, verdict)
        addr = (read_addr(self.rendezvous_dir, rank)
                if self.rendezvous_dir else None)
        try:
            elastic.mark_failed(self.heartbeat_dir, rank, addr=addr)
        except OSError as e:
            log.error("failover: tombstone for rank %d failed: %s",
                      rank, e)
        if self.kill is not None:
            try:
                self.kill(rank)
            except Exception as e:   # noqa: BLE001
                log.error("failover: kill(%d) failed: %s", rank, e)
        if self.spawn is None:
            return   # detection-only mode: operator drives the respawn
        gen = self._gen.get(rank, 0) + 1
        self._gen[rank] = gen
        self.events.append((time.time(), "respawn", rank))
        _flight.record(_flight.EV_FAILOVER_RESPAWN, peer=rank,
                       note=f"gen={gen}")
        with self._lock:
            self._recovering[rank] = time.monotonic()
        try:
            self.spawn(rank, gen)
        except Exception as e:   # noqa: BLE001
            log.error("failover: spawn(%d, gen %d) failed: %s",
                      rank, gen, e)

    def _note_rejoin(self, rank: int) -> None:
        with self._lock:
            if rank not in self._recovering:
                return
            del self._recovering[rank]
        self.events.append((time.time(), "rejoin", rank))
        _flight.record(_flight.EV_FAILOVER_REJOIN, peer=rank)
        log.info("failover: rank %d rejoined", rank)

    def recovery_spans(self) -> List[Dict]:
        """detect→rejoin durations per recovery episode (bench extra)."""
        out: List[Dict] = []
        open_at: Dict[int, float] = {}
        for ts, phase, rank in self.events:
            if phase == "detect":
                open_at[rank] = ts
            elif phase == "rejoin" and rank in open_at:
                out.append({"rank": rank, "detect_ts": open_at[rank],
                            "rejoin_ts": ts,
                            "detect_to_rejoin_s": round(
                                ts - open_at.pop(rank), 3)})
        return out


# ---------------------------------------------------------------------- #
# flag-gated per-service checkpointer (mirrors the aggregator wiring):
# PSService starts one when failover_ckpt_interval_s > 0 and
# failover_dir is set; service.close / Zoo.stop stop it (final save
# included — a clean shutdown's tail of applies stays durable)
# ---------------------------------------------------------------------- #
_ckptrs: Dict[int, ShardCheckpointer] = {}
_ckptrs_lock = threading.Lock()


def ensure_checkpointer(service) -> Optional[ShardCheckpointer]:
    interval = config.get_flag("failover_ckpt_interval_s")
    directory = config.get_flag("failover_dir")
    if interval <= 0 or not directory:
        return None
    with _ckptrs_lock:
        cur = _ckptrs.get(id(service))
        if cur is not None:
            return cur

        def shards(_svc=service):
            with _svc._handlers_cv:
                return dict(_svc._shards)

        ck = ShardCheckpointer(
            directory, service.rank, shards, interval_s=interval,
            keep=config.get_flag("failover_ckpt_keep")).start()
        _ckptrs[id(service)] = ck
        return ck


def stop_if_bound(service, final: bool = True) -> None:
    with _ckptrs_lock:
        ck = _ckptrs.pop(id(service), None)
    if ck is not None:
        ck.stop(final=final)


def stop_global(final: bool = False) -> None:
    """Stop every registered checkpointer (test teardown / Zoo.stop).
    ``final=False`` by default: a leaked checkpointer's service may
    already be gone, and teardown must not fail on a last save."""
    with _ckptrs_lock:
        cks = list(_ckptrs.values())
        _ckptrs.clear()
    for ck in cks:
        try:
            ck.stop(final=final)
        except Exception as e:   # noqa: BLE001
            log.error("shard checkpointer stop failed: %s", e)
