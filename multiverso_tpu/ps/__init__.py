"""Uncoordinated cross-process parameter-server plane.

This package is the TPU-native rebuild of the reference's *defining*
capability: workers that push (`Add`) and pull (`Get`) against sharded
parameter storage **at independent rates, with no peer coordination**
(ref: src/worker.cpp:30-76 partitions a request per server;
src/server.cpp:36-58 applies whatever arrives, whenever it arrives;
Applications/WordEmbedding/src/communicator.cpp:104-236 pulls *this
worker's* block vocabulary).

The synchronous table plane (multiverso_tpu.table) maps Add/Get onto XLA
collectives — correct BSP, but every multi-process op is lockstep. Here the
wire is a host-side RPC service instead:

* every process runs a :class:`~multiverso_tpu.ps.service.PSService` —
  a listener thread + per-connection handler threads (the reference's
  Communicator recv thread + Server actor, collapsed);
* every process *owns* a contiguous row range of each async table as a
  device-resident shard (:class:`~multiverso_tpu.ps.shard.RowShard`),
  itself sharded across the process's local chips; the
  shard's updater runs as a jitted program on the owner's local TPU device
  — the compute stays on the accelerator, only the row payloads ride TCP
  (the DCN-analogue wire; ICI collectives are the *sync* plane's wire);
* clients partition each Add/Get by owner rank and talk directly to the
  owners (ref Worker::Partition), local shards short-circuiting the socket
  (ref Communicator LocalForward, src/communicator.cpp:69-75);
* the wire's hot path is NATIVE (``native/mv_ps.cpp`` via
  :mod:`multiverso_tpu.ps.native`, flag ``ps_native``): C++ connection
  threads serve row ops on host-backed linear shards with zero Python in
  the loop, clients fan batches out per owner and scatter get replies in
  C, and anything the C++ side can't serve punts to the Python handlers
  synchronously under the same per-shard mutex — the reference's C++
  server/network layer (src/server.cpp, src/net/) rebuilt for this wire,
  2-3.8x the pure-Python plane's throughput on the loopback bench.

No barrier, no allgather: a straggler or dead worker never blocks peers —
requests to its shard fail with :class:`PSPeerError` after a timeout while
traffic to live shards proceeds. The failure story goes further than the
reference ever did: socket deaths tombstone the rank into
``elastic.failed()`` immediately (``elastic.bind_ps``), a RESTARTED rank
republishes through the rendezvous and reloads only its shard from the
last checkpoint (``load_local``), surviving clients re-resolve after
``ps_reconnect_backoff``, and ``mv.shutdown`` quiesces (each rank keeps
serving until live peers are done — the MV_ShutDown barrier,
ref src/zoo.cpp:103, rebuilt for an uncoordinated world).
"""

from multiverso_tpu.ps.service import (PSContext, PSError, PSPeerError,
                                       PSService, default_context,
                                       reset_default_context)
from multiverso_tpu.ps.tables import (AsyncArrayTable, AsyncArrayTableOption,
                                      AsyncKVTable, AsyncMatrixTable,
                                      AsyncMatrixTableOption,
                                      AsyncSparseKVTable,
                                      AsyncSparseMatrixTable)

__all__ = [
    "AsyncArrayTable", "AsyncArrayTableOption", "AsyncKVTable",
    "AsyncMatrixTable", "AsyncMatrixTableOption", "AsyncSparseKVTable",
    "AsyncSparseMatrixTable",
    "PSContext", "PSError", "PSPeerError", "PSService",
    "default_context", "reset_default_context",
]
