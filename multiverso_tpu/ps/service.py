"""PSService: the per-process async parameter-server runtime.

TPU-native re-design of the reference's actor/net runtime for the *async*
plane (ref: src/communicator.cpp — recv thread bridging net and actors;
src/server.cpp:36-58 — Server actor applying Adds/answering Gets as they
arrive; src/zoo.cpp:117-146 — Controller rendezvous assigning ranks).

One PSService per process:

* a listener thread accepts peer connections; each connection gets a
  handler thread that reads requests, dispatches to the owning table
  shard, and writes the reply (the reference's THREAD_MULTIPLE mode,
  communicator.cpp:39-48 — one recv loop per peer instead of one global);
* a client side (:class:`_Peer`) keeps one persistent connection per
  remote rank with a receiver thread completing per-``msg_id`` futures —
  the reference's msg_id -> Waiter bookkeeping (src/table.cpp:27-97) as
  ``concurrent.futures``;
* rendezvous: ranks find each other through a shared directory (flag
  ``ps_rendezvous``) or the JAX distributed coordinator's KV store when
  ``jax.distributed`` is live — the Controller's Register handshake with
  the coordinator already provided by the TPU runtime.

Local shards short-circuit the socket (ref LocalForward,
src/communicator.cpp:69-75) but still run on the service executor so
``add_async`` keeps fire-and-forget semantics.

Failure semantics: requests to a dead/unreachable rank raise
:class:`PSPeerError` (after ``ps_connect_timeout``/``ps_timeout``); the
service itself keeps serving live peers — no collective, so nobody hangs.
"""

from __future__ import annotations

import concurrent.futures as cf
import itertools
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from multiverso_tpu.ps import wire
# module-level like the exporter (no cycle: the aggregator and the
# failover plane import this module only lazily, inside functions), so
# their stats_poll_interval_s / failover_* flags are registered before
# any Zoo.start/argv parse reads them
from multiverso_tpu.ps import failover as _failover
# fault-injection wire plane (ISSUE 14): module-level so faults_spec /
# faults_seed register before argv parse AND so the plane is compiled
# into every build — the acceptance criterion is zero measurable
# hot-path cost with it present but disarmed (hook sites guard on
# one `_faults.PLANE.armed` attribute read; faults.py never imports
# this module at module scope, so no cycle)
from multiverso_tpu.ps import faults as _faults
# mesh data plane (ISSUE 15): process-colocation registry + stacked
# shard groups. Module-level so the ps_fanout/ps_spmd_stack flags
# register before argv parse and the plane is compiled into every
# build, disarmed by default (the fault-plane discipline); spmd.py
# never imports this module at module scope, so no cycle.
from multiverso_tpu.ps import spmd as _spmd
# serving plane (read replicas + admission): module-level for the same
# reason — its serving_* flags must exist before an argv parse, and its
# replica registry feeds the MSG_STATS "serving" block below. The
# serving package never imports ps at module scope (no cycle).
from multiverso_tpu.serving import replica as _serving_replica
from multiverso_tpu.telemetry import aggregator as _aggregator
from multiverso_tpu.telemetry import devstats as _devstats
from multiverso_tpu.telemetry import exporter as _exporter
from multiverso_tpu.telemetry import flightrec as _flight
from multiverso_tpu.telemetry import memstats as _memstats
from multiverso_tpu.telemetry import profiler as _profiler
from multiverso_tpu.telemetry import slo as _slo
from multiverso_tpu.telemetry import tenants as _tenants
from multiverso_tpu.telemetry import trace as _trace
from multiverso_tpu.telemetry import watchdog as _watchdog
from multiverso_tpu.utils import config, log, retry as _retry
from multiverso_tpu.utils.dashboard import monitor

# message types (request side; replies reuse the id space below 0x100)
MSG_REPLY_OK = 1
MSG_REPLY_ERR = 2
# one sub-frame of a chunk-streamed get reply (wire.ChunkedReply): N of
# these precede the stream's closing MSG_REPLY_OK, all under the
# request's msg_id (per-conn FIFO orders them). The client decodes and
# scatters each as it lands — reply decode overlaps the network receive
# instead of buffering one mega-frame. Sent only when the REQUEST asked
# (meta "chunk"), so a client never sees one it can't handle; the native
# C++ server punts chunk-requesting gets to Python (its meta whitelist
# rejects the "chunk" key), exactly like MSG_BATCH.
MSG_REPLY_CHUNK = 3
MSG_PING = 0x10
MSG_ADD_ROWS = 0x11
MSG_GET_ROWS = 0x12
MSG_SET_ROWS = 0x13
MSG_ADD_FULL = 0x14
MSG_GET_FULL = 0x15
MSG_KV_ADD = 0x16
MSG_KV_GET = 0x17
MSG_GET_STATE = 0x18
MSG_SET_STATE = 0x19
# multi-op frame: N logical sub-ops (each a complete inner frame with its
# own meta + codec wire, wire.pack_batch) delivered, dispatched, and acked
# as ONE request — the client send window's unit (ps/tables._SendWindow).
# Unknown to the native C++ server by design: it punts to the Python
# handler, which already holds the native shard mutex there.
MSG_BATCH = 0x1A
# remote-dashboard RPC: any worker pulls a rank's full telemetry
# snapshot — Dashboard monitor histograms, free-form notes, and the
# first-class per-shard server stats (queue depth, pending bytes, wave
# distribution, version) — as the REPLY META (pure JSON, no blobs).
# Surfaced as table.server_stats(rank) / PSService.stats(rank); the
# native C++ server punts it to Python like any unknown type.
MSG_STATS = 0x1B
# compact liveness verdict (flight-recorder plane, PR 4): serve-loop
# heartbeat age, shard queue depth, oldest in-flight op age, last
# watchdog verdict — as the REPLY META (pure JSON, no blobs). Cheap by
# construction (counter reads only, never a shard lock): it must answer
# even when the data plane is wedged, which is exactly when it is
# asked. Surfaced as table.server_health(rank) / PSService.health(rank);
# the native server punts it like MSG_STATS.
MSG_HEALTH = 0x1C
# replica subscription pull (serving plane, docs/SERVING.md): one
# committed full-shard row snapshot + the shard's mutation version as
# the reply. Request meta: {"table", "since": last seen version,
# "chunk": rows per sub-frame}. A shard whose version still equals
# "since" replies a tiny {"unchanged": true} frame — the epoch cadence
# costs an idle table almost nothing — and big snapshots stream as
# PR-5 chunked replies. Served off-lock under an epoch pin
# (shard.export_snapshot); the native C++ server punts it to Python
# like MSG_STATS (and its meta whitelist rejects "since" regardless).
MSG_SNAPSHOT = 0x1D
# multi-owner super-frame (mesh data plane, ps/spmd.py; flag
# ps_fanout): N complete inner frames — each a full wire.encode output
# whose meta names its OWNING rank under "ow" (wire.OWNER_META_KEY) —
# delivered, dispatched across ALL the colocated shards of the
# destination process, and acked as ONE request. The reply is the
# inner REPLY frames packed the same way (one per sub-op, OK or ERR,
# in order). This is the reference's worker-side Partition fan-out
# collapsed to one round trip per destination process instead of one
# per shard; colocated plain row adds/gathers additionally collapse
# server-side into ONE SPMD dispatch over the mesh-stacked shard
# group (_handle_multi). Unknown to the native C++ server by design:
# it punts, like MSG_BATCH.
MSG_MULTI = 0x1E

config.define_string("ps_rendezvous", "",
                     "directory for async-PS rank rendezvous (empty = use "
                     "the jax.distributed KV store when available)")
config.define_int("ps_rank", -1,
                  "async-PS rank override (-1 = jax.process_index); lets "
                  "the async plane run without a JAX coordinator, like the "
                  "reference PS needed only its own transport")
config.define_int("ps_world", 0,
                  "async-PS world-size override (0 = jax.process_count)")
config.define_int("ps_port", 0, "async-PS listen port (0 = ephemeral)")
config.define_string("ps_host", "127.0.0.1",
                     "async-PS bind host. Single-host runs keep the "
                     "loopback default; multi-host runs set 0.0.0.0 (the "
                     "published address is then the auto-detected routable "
                     "IP) or this machine's explicit routable IP")
config.define_float("ps_local_shard_min_mb", 1.0,
                    "shard an owned row range over the process's local "
                    "devices only when it is at least this big (tiny "
                    "shards would pay GSPMD partitioning overhead for "
                    "nothing); 0 = always shard")
config.define_float("ps_timeout", 300.0,
                    "async-PS request timeout seconds (generous default: "
                    "a shard's FIRST add/get of each bucket size jit-"
                    "compiles on the owner, which can take tens of seconds "
                    "per program on a cold TPU)")
config.define_float("ps_connect_timeout", 30.0,
                    "async-PS peer connect timeout seconds")
config.define_float("ps_reconnect_backoff", 5.0,
                    "seconds to fail fast against a rank that just died "
                    "before trying a fresh rendezvous lookup + reconnect "
                    "(lets a RESTARTED rank rejoin without every request "
                    "to a still-dead one stalling a connect timeout)")
config.define_bool("ps_coalesce", True,
                   "server-side request coalescing: Adds queued for the "
                   "same shard while an update is in flight are merged "
                   "(deltas summed) into ONE batched jitted update instead "
                   "of one serialized update per message — aggregate "
                   "throughput then rises with worker count instead of "
                   "collapsing on the shard lock (the reference server "
                   "applied strictly per-message, src/server.cpp:36-58). "
                   "Merged adds apply as if their deltas arrived in a "
                   "single message: exact for default/sgd updaters, within "
                   "the ASGD contract for the stateful ones")
config.define_bool("ps_native", True,
                   "serve and speak the async-PS wire through the native "
                   "C++ transport (native/mv_ps.cpp) when libmv_ps.so is "
                   "available: accepted connections are adopted by C++ "
                   "threads that serve hot row ops on host-backed linear "
                   "shards with zero Python in the loop (the reference's "
                   "C++ server hot path, src/server.cpp:36-58), and "
                   "clients send framed adds/gets straight from C. "
                   "Anything the native side cannot serve punts to the "
                   "Python handlers unchanged. Off = pure-Python plane")
config.define_float("ps_health_timeout", 5.0,
                    "MSG_HEALTH probe reply timeout seconds. Deliberately "
                    "watchdog-scale, NOT ps_timeout (300 s): a SIGSTOPPED "
                    "rank's kernel still completes the TCP handshake from "
                    "the listen backlog, and the probe must classify "
                    "'alive but wedged' in seconds — blocking a "
                    "supervisor's poll loop for 5 minutes against the "
                    "exact rank it is triaging would defeat the probe")
config.define_int("ps_probe_attempts", 1,
                  "one-shot probe (MSG_HEALTH/MSG_STATS) attempts per "
                  "pull, all within ONE ps_health_timeout budget "
                  "(deadline propagation, utils/retry.py): > 1 rides "
                  "out a transient connect refusal against a "
                  "restarting rank instead of classifying it down on "
                  "the first RST. Default 1 keeps the supervisor's "
                  "'unreachable IS the answer' fail-fast semantics")
config.define_float("ps_shutdown_grace", 60.0,
                    "seconds a rank keeps its shards served at shutdown "
                    "while waiting for peers to ALSO reach shutdown (the "
                    "reference's MV_ShutDown barrier, src/zoo.cpp:103 — "
                    "without it a fast rank's teardown kills peers still "
                    "pulling from its shard); observed-dead ranks are "
                    "skipped, timeout proceeds with a warning")


class PSError(RuntimeError):
    pass


class PSPeerError(PSError):
    """A specific peer is unreachable/dead; traffic to others is unaffected."""


def _sub_err(e: BaseException) -> Dict:
    """A super-frame sub-op's error as reply meta: the message plus a
    ``"peer"`` marker for peer-death errors, so the client-side fan-out
    can rethrow the TYPED PSPeerError (callers branch on it — a dead
    owner must not collapse into a generic request error just because
    the op rode a super-frame)."""
    out = {"error": f"{type(e).__name__}: {e}"}
    if isinstance(e, PSPeerError):
        out["peer"] = True
    return out


def await_reply(fut: cf.Future, timeout: float, what: str):
    """``fut.result`` with waiter timeouts surfaced as PSPeerError — a
    request that never got a reply is a peer-health event, not a generic
    concurrent.futures condition."""
    try:
        return fut.result(timeout=timeout)
    except cf.TimeoutError as e:
        raise PSPeerError(f"{what}: no reply within {timeout}s") from e


# ---------------------------------------------------------------------- #
# rendezvous backends
# ---------------------------------------------------------------------- #
class FileRendezvous:
    """Shared-directory rendezvous (the test/multi-process-on-one-host path;
    the reference's machine_file, include/multiverso/net/zmq_net.h:20-61)."""

    def __init__(self, directory: str):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)

    def publish(self, rank: int, addr: str) -> None:
        tmp = os.path.join(self._dir, f".{rank}.addr.tmp")
        with open(tmp, "w") as f:
            f.write(addr)
        os.replace(tmp, os.path.join(self._dir, f"{rank}.addr"))

    def lookup(self, rank: int, timeout: float) -> str:
        path = os.path.join(self._dir, f"{rank}.addr")
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                with open(path) as f:
                    addr = f.read().strip()
                if addr:
                    return addr
            except FileNotFoundError:
                pass
            time.sleep(0.02)
        raise PSPeerError(f"rank {rank} never published an address "
                          f"({path} missing after {timeout}s)")

    def mark(self, rank: int, tag: str, value: str = "1") -> None:
        """Publish a marker (shutdown quiesce handshake). ``value`` stamps
        the marker with this incarnation's identity (the published addr),
        so a REUSED rendezvous directory's stale markers from a previous
        run never satisfy the current run's barrier."""
        tmp = os.path.join(self._dir, f".{tag}.{rank}.tmp")
        with open(tmp, "w") as f:
            f.write(value)
        os.replace(tmp, os.path.join(self._dir, f"{tag}.{rank}"))

    def wait_mark(self, rank: int, tag: str, timeout: float,
                  expect: Optional[str] = None) -> bool:
        path = os.path.join(self._dir, f"{tag}.{rank}")
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                with open(path) as f:
                    got = f.read()
                if expect is None or got == expect:
                    return True
            except OSError:
                pass
            time.sleep(0.02)
        return False


class JaxRendezvous:
    """Rendezvous over the jax.distributed coordinator's KV store — the
    multi-host path; topology discovery the reference needed a Controller
    for (src/controller.cpp:38-80) comes from the TPU runtime."""

    def __init__(self, namespace: str = "mv_ps"):
        from jax._src import distributed  # jax's coordinator KV client
        client = distributed.global_state.client
        if client is None:
            raise PSError("jax.distributed is not initialized")
        self._client = client
        self._ns = namespace

    def publish(self, rank: int, addr: str) -> None:
        self._client.key_value_set(f"{self._ns}/{rank}", addr)

    def lookup(self, rank: int, timeout: float) -> str:
        try:
            return self._client.blocking_key_value_get(
                f"{self._ns}/{rank}", int(timeout * 1000))
        except Exception as e:
            raise PSPeerError(f"rank {rank} not in coordinator KV store: "
                              f"{e}") from e

    def mark(self, rank: int, tag: str, value: str = "1") -> None:
        self._client.key_value_set(f"{self._ns}/{tag}/{rank}", value)

    def wait_mark(self, rank: int, tag: str, timeout: float,
                  expect: Optional[str] = None) -> bool:
        # the coordinator KV store dies with the job, so stale cross-run
        # markers cannot exist here; ``expect`` is accepted for interface
        # parity but a present key is sufficient
        try:
            self._client.blocking_key_value_get(
                f"{self._ns}/{tag}/{rank}", int(max(timeout, 0.001) * 1000))
            return True
        except Exception:
            return False


# ---------------------------------------------------------------------- #
# client side: one persistent connection per remote rank
# ---------------------------------------------------------------------- #
_peer_gen = itertools.count()   # per-incarnation msg-id bases (below)


class _Peer:
    def __init__(self, rank: int, addr: str, connect_timeout: float,
                 io_timeout: float,
                 on_death: Optional[Callable[["_Peer", Exception],
                                             None]] = None,
                 src: int = -1):
        self.rank = rank
        self.src = src     # the LOCAL rank (fault-plane src identity;
        #                    -1 = unknown, plane falls back to its own)
        self.addr = addr   # the resolved incarnation address (native
                           # client conns to the same rank reuse it)
        self._on_death = on_death
        host, port = addr.rsplit(":", 1)
        # connect retries ride the shared capped-exponential policy
        # (utils/retry.py) with the connect timeout as the DEADLINE —
        # the flat 50 ms loop this replaces synchronized every client's
        # reconnect storm against a respawning rank
        deadline = _retry.deadline_in(connect_timeout)
        backoff = _retry.Backoff(base_s=0.05, cap_s=1.0)
        attempt = 0
        while True:
            try:
                self._sock = socket.create_connection(
                    (host, int(port)), timeout=connect_timeout)
                break
            except OSError as e:
                if not backoff.sleep(attempt, deadline):
                    raise PSPeerError(
                        f"cannot connect to rank {rank} at {addr}: {e}"
                    ) from e
                attempt += 1
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(io_timeout)
        self._send_lock = threading.Lock()
        # reorder-injection holdback (chaos plane, ps/faults.py): held
        # encoded frames, released after a LATER frame ships. Only ever
        # touched under _send_lock, and only when the plane is armed.
        self._held: List[bytes] = []
        self._pending: Dict[int, cf.Future] = {}
        self._pending_lock = threading.Lock()
        # msg ids start at a per-INCARNATION base (generation << 32):
        # the flight recorder keys in-flight ops by (rank, msg_id), and
        # a reconnected incarnation restarting at 0 would collide with
        # the dying one's unswept ids — its death sweep could then erase
        # the fresh incarnation's live entries (correlation is the outer
        # frame's job either way; the server just echoes the id)
        self._next_id = next(_peer_gen) << 32
        self._dead: Optional[Exception] = None
        self._recv_thread = threading.Thread(
            target=self._recv_loop, name=f"ps-peer-{rank}", daemon=True)
        self._recv_thread.start()

    def _recv_loop(self) -> None:
        try:
            while True:
                try:
                    msg_type, msg_id, meta, arrays = wire.recv(self._sock)
                except TimeoutError:
                    # idle socket, nothing in flight is harmed: the io
                    # timeout bounds BLOCKED REPLIES via each waiter's
                    # fut.result(timeout), not connection lifetime — a
                    # healthy-but-quiet peer must not be declared dead
                    continue
                if msg_type == MSG_REPLY_CHUNK:
                    # one sub-frame of a streamed reply: feed the
                    # requester's sink NOW (decode + scatter overlap the
                    # receive) — the entry stays pending until the
                    # stream's closing MSG_REPLY_OK. A sink failure is
                    # remembered and surfaces on the final frame (the
                    # caller must never consume a half-scattered buffer
                    # as complete).
                    with self._pending_lock:
                        fut = self._pending.get(msg_id)
                    if fut is not None:
                        sink = getattr(fut, "_mv_chunk_sink", None)
                        try:
                            if sink is None:
                                # chunks only arrive when the REQUEST
                                # asked for them, and every asking
                                # caller registers a sink — a sink-less
                                # chunk is a caller bug that must fail
                                # the op, not resolve it with a silently
                                # discarded payload
                                raise PSError(
                                    "chunked reply frame without a "
                                    "registered chunk sink")
                            sink(meta, arrays)
                        except Exception as e:  # noqa: BLE001
                            fut._mv_chunk_err = e
                    continue
                with self._pending_lock:
                    fut = self._pending.pop(msg_id, None)
                if fut is None:
                    continue
                _flight.end_op(self.rank, msg_id,
                               ok=msg_type != MSG_REPLY_ERR)
                if msg_type == MSG_REPLY_ERR:
                    fut.set_exception(PSError(
                        f"rank {self.rank}: {meta.get('error', '?')}"))
                else:
                    cerr = getattr(fut, "_mv_chunk_err", None)
                    if cerr is not None:
                        fut.set_exception(PSError(
                            f"rank {self.rank}: chunk sink failed: "
                            f"{type(cerr).__name__}: {cerr}"))
                    else:
                        fut.set_result((meta, arrays))
        except Exception as e:  # socket death: fail everything in flight
            err = PSPeerError(f"rank {self.rank} connection lost: {e}")
            self._dead = err
            with self._pending_lock:
                pending, self._pending = self._pending, {}
            # black box FIRST, while THIS incarnation's unacked ops are
            # still in the recorder's in-flight table: the dump is the
            # artifact that names this dead rank's oldest unacked msg
            # for postmortem. Only a death with unacked traffic is a
            # diagnostic event — a quiet conn dying at shutdown must not
            # write dumps. The sweep is scoped to OUR msg ids: a
            # reconnected fresh incarnation may already have live ops
            # under the same rank during the dump window.
            _flight.record(_flight.EV_PEER_DEAD, peer=self.rank,
                           note=str(e)[:120])
            if pending:
                _flight.dump_global(
                    f"peer rank {self.rank} connection lost with "
                    "requests in flight")
            _flight.RECORDER.fail_peer(self.rank, msg_ids=list(pending))
            for fut in pending.values():
                if not fut.done():
                    fut.set_exception(err)
            if self._on_death is not None:
                self._on_death(self, err)

    def request(self, msg_type: int, meta: Dict,
                arrays: Sequence[np.ndarray],
                chunk_sink: Optional[Callable] = None) -> cf.Future:
        fut: cf.Future = cf.Future()
        if chunk_sink is not None:
            # attached BEFORE the pending insert: the recv loop may see
            # the first chunk the instant the request hits the wire
            fut._mv_chunk_sink = chunk_sink
        if self._dead is not None:
            fut.set_exception(self._dead)
            return fut
        with self._send_lock:
            msg_id = self._next_id
            self._next_id += 1
            with self._pending_lock:
                self._pending[msg_id] = fut
            # probes are tracked in flight (stuck probes should age) but
            # keep their send/ack edges out of the ring — supervisor
            # polling must not wrap the tape (server-side rule mirrored)
            _flight.begin_op(self.rank, msg_id, msg_type,
                             sum(getattr(a, "nbytes", 0) for a in arrays),
                             record=msg_type not in (MSG_PING, MSG_STATS))
            try:
                if _faults.PLANE.armed:   # chaos plane (off: one load)
                    self._send_faulted(msg_type, msg_id, meta, arrays)
                else:
                    wire.send(self._sock, msg_type, msg_id, meta, arrays)
            except OSError as e:
                err = PSPeerError(f"rank {self.rank} send failed: {e}")
                self._dead = err
                _flight.end_op(self.rank, msg_id, ok=False)
                with self._pending_lock:
                    self._pending.pop(msg_id, None)
                fut.set_exception(err)
                if self._on_death is not None:
                    self._on_death(self, err)
                return fut
            except BaseException:
                # encode/packing failure (bad meta, exotic array): not a
                # peer-death signal — unwind THIS op's bookkeeping and
                # re-raise. Leaving the recorder entry would age into a
                # permanent spurious "stuck" verdict (fail_peer never
                # sweeps a live peer), and leaving the pending future
                # would hold its waiter to the full ps_timeout.
                _flight.end_op(self.rank, msg_id, ok=False)
                with self._pending_lock:
                    self._pending.pop(msg_id, None)
                raise
        # the recv loop may have died BETWEEN the entry _dead check and the
        # _pending insert (it fails only futures it saw in _pending when it
        # swept) — re-check so this future fails fast instead of dangling
        # until the 300s waiter timeout
        if self._dead is not None:
            with self._pending_lock:
                still = self._pending.pop(msg_id, None)
            # close the recorder's entry UNCONDITIONALLY (end_op is
            # idempotent): the recv loop's fail_peer sweep may have run
            # BEFORE begin_op registered this op — in that interleaving
            # the sweep also already took _pending[msg_id], so gating on
            # `still` would skip the close and the orphaned entry would
            # age forever: a permanent spurious "stuck" verdict
            _flight.end_op(self.rank, msg_id, ok=False)
            if still is not None and not fut.done():
                fut.set_exception(self._dead)
        return fut

    # chaos plane (ps/faults.py; reached ONLY when a scenario is armed
    # — the hot path's single `PLANE.armed` load guards it)
    _HELD_CAP = 8   # safety ceiling on the rule's reorder depth

    def _send_faulted(self, msg_type: int, msg_id: int, meta,
                      arrays) -> None:
        """One outbound frame through the armed fault plane. Runs under
        ``_send_lock`` (the caller holds it), so the holdback list and
        the socket are single-writer here. Injected partitions/resets
        raise :class:`faults.InjectedFault` (a ConnectionResetError) —
        the caller's OSError handling then takes the organic peer-death
        path, which is the point."""
        plan = _faults.PLANE.plan_send(self.rank, msg_type, msg_id,
                                       src=self.src)
        if plan is None:
            wire.send(self._sock, msg_type, msg_id, meta, arrays)
            self._release_held()
            return
        if plan.delay_s:
            # a slow wire backpressures senders to this peer exactly
            # like a real one: the sleep holds the send lock
            time.sleep(plan.delay_s)
        if plan.reset:
            # injected partition/reset: kill the conn FIRST so the recv
            # loop observes the death (fails in-flight futures, replay
            # re-arms), then fail this send like the kernel would
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            raise _faults.InjectedFault(
                f"injected {'/'.join(plan.kinds)} to rank {self.rank}")
        if plan.drop:
            return   # silently eaten; the caller's timeout is the signal
        buf = wire.encode(msg_type, msg_id, meta, arrays)
        if plan.reorder and len(self._held) < min(plan.depth,
                                                 self._HELD_CAP):
            self._held.append(buf)   # ships AFTER the next frame...
            timer = threading.Timer(plan.hold_s, self._release_held,
                                    kwargs={"locked": False})
            timer.daemon = True      # ...or after hold_s: a blocking
            timer.start()            # caller awaiting THIS frame's ack
            return                   # is its own only traffic source
        self._sock.sendall(buf)
        # a reorder-claimed frame never duplicates — even when the
        # holdback was full and it shipped immediately — so the
        # plane's injected counts/log match what hit the wire
        if plan.duplicate and not plan.reorder:
            self._sock.sendall(buf)
        self._release_held()

    def _release_held(self, locked: bool = True) -> None:
        """Flush reorder-held frames (oldest first) now that a later
        frame has shipped (``locked=True``: caller holds the send
        lock) or the hold timer fired (``locked=False``). Socket
        errors are swallowed on BOTH paths — a held frame dying with
        the conn is just an injected drop, the recv loop owns the
        death signal, and the CURRENT frame's future (its own send
        already succeeded) must not be failed by a sibling's
        corpse."""
        if not self._held:
            return
        if not locked:
            with self._send_lock:
                self._release_held()
            return
        held, self._held = self._held, []
        try:
            for buf in held:
                self._sock.sendall(buf)
        except OSError:
            pass

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


# ---------------------------------------------------------------------- #
# the service
# ---------------------------------------------------------------------- #
def _routable_ip() -> str:
    """Best-effort routable address of this host (the reference's
    GetLocalIPAddress, src/util/net_util.cpp — which was Windows-only;
    this one works everywhere): the UDP-connect trick picks the egress
    interface without sending a packet."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"
    finally:
        s.close()


def oneshot_probe(addr: str, msg_type: int, timeout: float,
                  connect_timeout: Optional[float] = None) -> Dict:
    """One telemetry pull (MSG_HEALTH / MSG_STATS / MSG_PING) over a
    fresh one-shot connection to ``addr``; returns the reply meta. The
    shared socket body of :meth:`PSService.health`/``stats_oneshot`` and
    the address-only consumers (``tools/mvtop.py`` probes straight from
    a rendezvous directory, no PSService constructed). The connect is
    budgeted like the reply: a partitioned host (SYN dropped, no RST)
    must not hold a triage loop for the data plane's 30 s connect
    timeout. Raises the raw socket/wire errors (callers wrap them in
    their own peer-health types); an ERR reply raises PSError with the
    server's message."""
    host, port = addr.rsplit(":", 1)
    ct = timeout if connect_timeout is None else min(timeout,
                                                     connect_timeout)
    with socket.create_connection((host, int(port)), timeout=ct) as s:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(timeout)
        wire.send(s, msg_type, 0, {})
        reply_type, _mid, meta, _ = wire.recv(s)
    if reply_type == MSG_REPLY_ERR:
        raise PSError(f"probe to {addr}: {meta.get('error', '?')}")
    return meta


class PSService:
    """Listener + shard registry + peer pool for one process."""

    def __init__(self, rank: int, world: int, rendezvous=None,
                 host: Optional[str] = None, port: Optional[int] = None,
                 defer_publish: bool = False):
        """``defer_publish=True`` holds the rendezvous publish until
        :meth:`publish_addr` — a RESTARTED shard must restore from its
        checkpoint before any survivor can discover the new address,
        or a replayed frame landing on the still-empty shard would
        commit its sequence, ack, and then be wiped by the restore
        (an acked op silently lost). See failover.rejoin."""
        self.rank, self.world = rank, world
        if host is None:
            host = config.get_flag("ps_host") or "127.0.0.1"
        self._rendezvous = rendezvous
        # process-colocation identity (ps/spmd.py): services sharing a
        # process AND a rendezvous may route to each other in-process
        # when ps_fanout is armed. The routing registry entry appears
        # with the rendezvous publish (deferred-publish services stay
        # invisible until their restore, same rule as the address).
        self._proc_key = _spmd.proc_key(rendezvous)
        self._routed_seen: set = set()
        self._routed_dead: set = set()
        self._handlers: Dict[str, Callable] = {}
        # table -> shard object for MSG_STATS (handlers alone are opaque
        # closures; the stats RPC needs the shard's stats() surface)
        self._shards: Dict[str, Any] = {}
        self._handlers_cv = threading.Condition()
        # telemetry: adopt the trace_ids flag under this service's rank
        # (the exporter starts at the END of __init__, once addr exists);
        # the always-on flight recorder pins the same rank and the
        # watchdog thread starts (flag-gated) to age its in-flight table
        _trace.configure(rank)
        _flight.configure(rank)
        _profiler.configure(rank)
        _devstats.configure(rank)
        # fault plane: adopt the rank; arms from faults_spec /
        # $MV_FAULTS_SPEC when set (chaos bench workers), else stays
        # the null object — zero injection codepaths reachable
        _faults.configure(rank)
        log.set_rank(rank)
        _watchdog.ensure_started()
        # memory sampler (flag memstats_interval_s; the byte LEDGER is
        # always on and pull-only — this only starts the RSS/device-
        # census cadence feeding the windowed leak verdicts)
        _memstats.ensure_started()
        self._peers: Dict[int, _Peer] = {}
        self._peers_lock = threading.Lock()
        self._peer_locks: Dict[int, threading.Lock] = {}
        # rank -> last observed death (monotonic ts); feeds the reconnect
        # backoff and the death hooks (elastic integration)
        self._dead_ranks: Dict[int, float] = {}
        self._death_hooks: List[Callable[[int], None]] = []
        self._conns: List[socket.socket] = []
        self._conns_lock = threading.Lock()
        self._closed = False
        # fire-and-forget local dispatch (ref: ops on the local shard still
        # hop through the Server actor thread, zoo.cpp SendTo)
        self._local_exec = cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ps-local")
        # native transport (flag ps_native + libmv_ps.so): accepted
        # connections are adopted by C++ serving threads; _native_cb must
        # stay referenced or ctypes frees the callback trampoline under
        # the C++ threads still holding it
        self._native = None        # cleared (under _native_lock) at close
        self._native_raw = None    # NEVER cleared: punt callbacks on C++
        #                            conn threads may run right up to the
        #                            join inside server_free, which close()
        #                            calls only after those threads exit
        self._native_cb = None
        self._native_lock = threading.Lock()
        self._nconns: Dict[int, Any] = {}
        # shard incarnation generation (flag ps_generation): 0 for a
        # first boot; the failover supervisor spawns each replacement
        # at gen+1 and MSG_HEALTH echoes it, so a restarted shard is
        # visible at a glance (mvtop's gen column). Assigned BEFORE
        # the listener exists: a health probe can land the instant the
        # accept loop starts, and health_payload reads this
        self.generation = int(config.get_flag("ps_generation"))
        if config.get_flag("ps_native"):
            from multiverso_tpu.ps import native as ps_native
            if ps_native.available():
                self._native, self._native_cb = ps_native.server_new(
                    self._punt, rank)
                self._native_raw = self._native
        self._listener = socket.create_server(
            (host, port if port is not None else config.get_flag("ps_port")))
        # published address must be ROUTABLE: a wildcard bind advertises the
        # machine's egress IP, not 0.0.0.0 (peers could never connect to it)
        publish_host = (_routable_ip() if host in ("", "0.0.0.0", "::")
                        else host)
        self.addr = "%s:%d" % (publish_host,
                               self._listener.getsockname()[1])
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ps-accept", daemon=True)
        self._accept_thread.start()
        self._published = False
        if rendezvous is not None and not defer_publish:
            self.publish_addr()
        # flag-gated metrics exporter with the rich (shard-aware)
        # payload; no-op unless metrics_dir is set
        _exporter.ensure_started(rank, self.stats_payload)
        # controller-side cluster observability (flag
        # stats_poll_interval_s): rank 0 polls every rank's MSG_STATS +
        # MSG_HEALTH over the one-shot probe path and keeps the merged
        # cluster time series
        if rank == 0:
            _aggregator.ensure_started(self)
        # flag-gated per-shard failover checkpointer (failover_dir +
        # failover_ckpt_interval_s): the durable half of exactly-once
        # replay — shards registered later are picked up per cycle
        _failover.ensure_checkpointer(self)
        log.debug("PSService rank %d/%d listening on %s", rank, world,
                  self.addr)

    # ----------------------------- server side ----------------------- #
    def publish_addr(self) -> None:
        """Publish (or re-publish) this incarnation's address through
        the rendezvous — the moment peers may discover it. Deferred-
        publish services (restarted shards) call this AFTER their
        checkpoint restore; idempotent. The in-process routing registry
        entry (ps/spmd.py) appears at the same moment and for the same
        reason: a survivor routing a replay onto the still-empty shard
        would commit, ack, and then be wiped by the restore."""
        if self._rendezvous is not None:
            self._rendezvous.publish(self.rank, self.addr)
            self._published = True
        _spmd.register_service(self)

    def register_handler(self, table: str, handler: Callable,
                         shard=None) -> None:
        """``handler(msg_type, meta, arrays) -> (meta, arrays)``, called on
        a connection thread; the shard serializes internally. When
        ``shard`` is a host-backed linear RowShard and the native server
        is live, the shard's buffer registers with C++ for zero-Python
        serving of the hot ops — and the Python handler (which then only
        sees punted messages: compressed wires, checkpoint state, sparse
        protocol) wraps itself in the native shard mutex so its buffer
        mutations serialize with C++ applies."""
        if self._native is not None and shard is not None:
            wrapped = self._try_register_native(table, handler, shard)
            if wrapped is not None:
                handler = wrapped
        with self._handlers_cv:
            self._handlers[table] = handler
            if shard is not None:
                self._shards[table] = shard
            self._handlers_cv.notify_all()
        if shard is not None:
            # mesh-stacked grouping (flag ps_spmd_stack, ps/spmd.py):
            # colocated same-table device shards pool into ONE
            # mesh-sharded stacked array with single-dispatch SPMD
            # apply/gather. No-op unless armed and the shard qualifies.
            _spmd.try_join(self, table, shard)

    def _try_register_native(self, table: str, handler: Callable,
                             shard) -> Optional[Callable]:
        from multiverso_tpu.ps import native as ps_native
        from multiverso_tpu.ps.shard import RowShard
        from multiverso_tpu.updaters import STATELESS_LINEAR
        # EXACT RowShard only: HashShard grows/remaps its buffer, which
        # would leave C++ writing through a stale pointer
        if type(shard) is not RowShard or not shard._np_mode:
            return None
        if config.get_flag("ps_fanout"):
            # process-coalesced routing (ps/spmd.py): a fanout world's
            # traffic arrives in-process, where a native registration
            # only costs — every routed op would cross the FFI to take
            # the C++ shard mutex around its whole python handler, and
            # the sampled 2-worker profile showed exactly that mutex
            # eating 60%+ of the wall. The C++ fast path exists for
            # SOCKET clients, which a fanout-armed world does not use.
            return None
        sign = STATELESS_LINEAR.get(type(shard.updater))
        if sign is None:
            return None
        nworkers = 0 if shard._dirty is None else shard._dirty.shape[0]
        with self._native_lock:
            if self._native is None:   # raced close(): python plane only
                return None
            pin = ps_native.register_shard(
                self._native, table, shard.lo, shard.n, shard.num_col,
                shard._data, sign, shard._dirty, nworkers)
        if pin is None:
            return None
        # the pin addresses THIS shard object — stable across same-name
        # re-registration and server close (review finding: a name lookup
        # at unlock time could unlock a DIFFERENT shard's mutex)
        shard.bind_native(pin)

        def locked_handler(msg_type, meta, arrays,
                           _inner=handler, _pin=pin):
            ps_native.shard_pin_lock(_pin)
            try:
                return _inner(msg_type, meta, arrays)
            finally:
                ps_native.shard_pin_unlock(_pin)

        return locked_handler

    def _punt(self, conn_id: int, frame: bytes) -> None:
        """Frames the native server can't serve, delivered synchronously
        on the C++ connection thread (per-connection FIFO preserved).
        Mirrors _serve_conn's dispatch; the reply goes back through the
        native conn's write lock."""
        from multiverso_tpu.ps import native as ps_native
        try:
            msg_type, msg_id, meta, arrays = wire.parse_frame(frame)
        except wire.WireError as e:
            # Header was sane (C++ validated magic/bounds) but the body
            # failed to parse. The python plane fails fast by killing the
            # connection; silently dropping here would instead park the
            # peer for the full ps_timeout. The header's msg_id is still
            # trustworthy, so send an ERR reply the peer can raise on.
            log.debug("ps native punt: malformed frame (%s)", e)
            try:
                reply = wire.encode(MSG_REPLY_ERR, wire.peek_msg_id(frame),
                                    {"error": f"WireError: {e}"})
                ps_native.send_raw(self._native_raw, conn_id, reply)
            except Exception:
                log.debug("ps native punt: ERR reply for malformed frame "
                          "failed; dropping")
            return
        # the serve beat AND the ring edges mark DATA-PLANE liveness:
        # health/stats/ping probes refresh neither — a wedged server
        # polled at 2 Hz must report a growing serve_age_s, and probe
        # noise must not wrap the ring past the pre-wedge evidence
        # before the operator reads the (refreshed-in-place) fault dump
        probe = msg_type in (MSG_PING, MSG_STATS, MSG_HEALTH)
        if not probe:
            _flight.beat("serve")
            _flight.record(_flight.EV_RECV, msg_type=msg_type,
                           msg_id=msg_id)
        try:
            if msg_type == MSG_PING:       # native serves PING; belt only
                reply = wire.encode(MSG_REPLY_OK, msg_id,
                                    {"rank": self.rank})
            elif msg_type == MSG_STATS:    # remote dashboard pull
                reply = wire.encode(MSG_REPLY_OK, msg_id,
                                    self.stats_payload())
            elif msg_type == MSG_HEALTH:   # liveness verdict pull
                reply = wire.encode(MSG_REPLY_OK, msg_id,
                                    self.health_payload())
            else:
                tr = (meta.get(wire.TRACE_META_KEY)
                      if _trace.enabled() else None)
                t0 = time.time() if tr is not None else 0.0
                if msg_type == MSG_MULTI:
                    # multi-owner super-frame punted by the native
                    # server (unknown type, like MSG_BATCH): dispatch
                    # across this process's colocated shards
                    with monitor("ps[multi].serve"):
                        rmeta, rarrays = self._handle_multi(meta, arrays)
                else:
                    handler = self._wait_handler(meta["table"])
                    with monitor(f"ps[{meta['table']}].serve"):
                        rmeta, rarrays = handler(msg_type, meta, arrays)
                if tr is not None:
                    _trace.add_span("ps.serve", t0, time.time(), trace=tr,
                                    args={"table": meta.get("table",
                                                            "multi"),
                                          "type": msg_type})
                if isinstance(rarrays, wire.ChunkedReply):
                    # streamed reply over the native conn: each chunk
                    # goes through send_raw (the conn's C++ write lock
                    # orders them); the closing OK is the `reply` below
                    for cmeta, carrays in rarrays.chunks:
                        ps_native.send_raw(
                            self._native_raw, conn_id,
                            wire.encode(MSG_REPLY_CHUNK, msg_id, cmeta,
                                        carrays))
                        _flight.record(_flight.EV_GET_CHUNK,
                                       msg_type=msg_type, msg_id=msg_id)
                    rmeta, rarrays = rarrays.meta, ()
                reply = wire.encode(MSG_REPLY_OK, msg_id, rmeta, rarrays)
        except Exception as e:
            log.debug("ps handler error: %s", e)
            if isinstance(e, MemoryError):
                # OOM forensics (same rule as the python serve loop)
                _memstats.oom_dump("MemoryError serving a punted request")
            reply = wire.encode(MSG_REPLY_ERR, msg_id,
                                {"error": f"{type(e).__name__}: {e}"})
        # _native_raw, not _native: close() clears the latter while punts
        # may still be in flight; the raw handle stays valid until
        # server_free (which runs after this conn thread is joined)
        if not probe:
            _flight.record(_flight.EV_REPLY, msg_type=msg_type,
                           msg_id=msg_id, nbytes=len(reply))
        ps_native.send_raw(self._native_raw, conn_id, reply)

    # ----------------------------- telemetry -------------------------- #
    def stats_payload(self) -> Dict:
        """This rank's full telemetry snapshot (the MSG_STATS reply meta
        and the exporter record share this one shape): Dashboard monitor
        histograms, free-form notes, and per-shard server stats. Pure
        JSON-safe data — consumers on other ranks can never mutate live
        state through it."""
        shards = {}
        with self._handlers_cv:
            items = list(self._shards.items())
        for table, shard in items:
            try:
                stats = shard.stats()
            except Exception as e:  # noqa: BLE001 — one bad shard must
                stats = {"error": f"{type(e).__name__}: {e}"}  # not hide
            shards[table] = stats                              # the rest
        # ONE record shape: the monitors/notes assembly is the
        # exporter's (default_stats_fn), overlaid with this service's
        # identity and shard registry — MSG_STATS replies and exporter
        # records must never diverge
        payload = _exporter.default_stats_fn()
        payload.update(rank=self.rank, world=self.world, addr=self.addr,
                       shards=shards)
        # serving plane: this process's read replicas (lag, versions,
        # cache hit rate, shed counters) — the block mvtop's serving
        # panel and the cluster aggregator merge. Process-global like
        # the monitors (same (host, pid) dedupe rule applies there).
        try:
            serving = _serving_replica.stats_snapshot()
            if serving:
                payload["serving"] = serving
        except Exception:   # noqa: BLE001 — telemetry never breaks stats
            pass
        # step-profiler block (flag step_profile): per-process stall
        # fraction / recompile summary — mvtop's stall%/recompiles
        # columns and the aggregator pass it through like serving.
        # Process-global (same (host, pid) collapse as the monitors).
        try:
            profile = _profiler.stats_snapshot()
            if profile:
                payload["profile"] = profile
        except Exception:   # noqa: BLE001
            pass
        # memory plane (telemetry/memstats.py): the byte ledger + RSS +
        # recent leak verdicts. Process-global like the monitors (same
        # (host, pid) dedupe in the aggregator); always present — the
        # ledger is always on, like the flight recorder.
        try:
            payload["memory"] = _memstats.stats_snapshot()
        except Exception:   # noqa: BLE001 — telemetry never breaks stats
            pass
        # device plane (telemetry/devstats.py): transfer/collective/
        # compile counters + the per-device live-buffer rollup. OMITTED
        # when nothing ran on the device plane (and by older peers in a
        # mixed-version cluster) — every consumer renders its absence
        # as "-", never a KeyError.
        try:
            devices = _devstats.stats_snapshot()
            if devices:
                payload["devices"] = devices
        except Exception:   # noqa: BLE001
            pass
        # tenant attribution plane (telemetry/tenants.py): per-tenant
        # serve ledger + budgets + the noisy-neighbor verdict sweep
        # (the pull drives one sweep interval). Process-global like
        # serving ((host, pid) dedupe in the aggregator); OMITTED when
        # no tenant traffic was ever accounted — consumers render its
        # absence as "-", never a KeyError.
        try:
            tenants = _tenants.stats_snapshot()
            if tenants:
                payload["tenants"] = tenants
        except Exception:   # noqa: BLE001
            pass
        # SLO sentinel (telemetry/slo.py): per-objective burn rates,
        # firing state, episode counts, and the named straggler.
        # Process-global (rank 0's sentinel judges the cluster);
        # OMITTED while disarmed — the payload stays additive.
        try:
            slo_block = _slo.stats_snapshot()
            if slo_block:
                payload["slo"] = slo_block
        except Exception:   # noqa: BLE001
            pass
        return payload

    def stats(self, rank: int, timeout: Optional[float] = None) -> Dict:
        """Pull ``rank``'s telemetry snapshot over MSG_STATS (the remote
        dashboard; local rank short-circuits). Raises PSPeerError for a
        dead/unreachable rank like any other request."""
        if rank == self.rank:
            return self.stats_payload()
        fut = self._peer(rank).request(MSG_STATS, {}, ())
        meta, _ = await_reply(
            fut, timeout or config.get_flag("ps_timeout"),
            f"stats from rank {rank}")
        return meta

    def health_payload(self) -> Dict:
        """This rank's compact liveness verdict (the MSG_HEALTH reply
        meta): serve-loop heartbeat age, summed shard apply-queue depth,
        oldest in-flight op age, and the last watchdog verdict. Counter
        reads ONLY — no shard lock, no native crossing: a health probe
        must answer even when the data plane is wedged."""
        with self._handlers_cv:
            shards = list(self._shards.values())
        queue_depth = 0
        for s in shards:
            depth = getattr(s, "queue_depth", None)   # RowShard's lock-
            if callable(depth):                       # free accessor;
                queue_depth += depth()                # KV shards: none
        # ONE in-flight snapshot serves both fields (oldest + count):
        # this path contends the hot-path ring lock and is polled, so it
        # must not copy the table twice per probe
        snap = _flight.RECORDER.inflight_snapshot()
        oldest = (max(snap, key=lambda e: e[2]) if snap else None)
        wd = _watchdog.last_verdict()
        serve_age = _flight.RECORDER.beat_age("serve")
        apply_age = _flight.RECORDER.beat_age("apply")
        return {
            "rank": self.rank, "addr": self.addr,
            # incarnation generation: a respawned shard reports its
            # predecessor's + 1, so operators (mvtop) and the cluster
            # aggregator can tell a restarted rank from a healthy one
            # even after its beacon/tombstone state settles
            "gen": self.generation,
            "ts": round(time.time(), 3),
            # beat ages: PYTHON-plane liveness only. None = that loop
            # never ran (no python-plane traffic yet), a growing number
            # = how long it has been quiet. Probe traffic (PING/STATS/
            # HEALTH) does not refresh them, and neither do natively-
            # served ops (zero-Python path, same rule as tracing) — the
            # "native" flag below tells consumers to discount quiet
            # beats on a native-serving rank rather than read them as a
            # wedge (the in-flight/watchdog fields are plane-agnostic).
            "native": self._native_raw is not None,
            "serve_age_s": (None if serve_age is None
                            else round(serve_age, 3)),
            "apply_age_s": (None if apply_age is None
                            else round(apply_age, 3)),
            "queue_depth": queue_depth,
            "inflight": len(snap),
            "oldest_inflight_s": (round(oldest[2], 3) if oldest else 0.0),
            "oldest_inflight": ({"peer": oldest[0], "msg_id": oldest[1],
                                 "type": oldest[3]} if oldest else None),
            "watchdog": wd,
            # headline verdict: the watchdog's view when it has run, else
            # "ok" (an unwatched plane that answered this RPC is serving)
            "status": wd["status"] if wd.get("checked") else "ok",
        }

    def health(self, rank: int, timeout: Optional[float] = None) -> Dict:
        """Pull ``rank``'s liveness verdict over MSG_HEALTH (local rank
        short-circuits). The probe rides its OWN one-shot connection,
        never the shared data conn: per-conn FIFO would queue it behind
        the very data op that is wedged (and behind this caller's own
        outstanding traffic), turning "alive but stuck" into a 300 s
        timeout — the opposite of a liveness probe. A fresh conn gets a
        fresh handler thread on the Python server (and a fresh C++
        serving thread on the native one), so the answer only requires
        the accept loop to be alive — and the reply wait defaults to
        ps_health_timeout (seconds), not ps_timeout: a fully frozen
        rank accepts the handshake in-kernel and then never answers,
        and the probe must return in triage time, not 5 minutes. Raises
        PSPeerError for a dead/unresponsive rank — which IS the 'not
        serving' answer, typed."""
        return self._oneshot_pull(rank, MSG_HEALTH, timeout)

    def stats_oneshot(self, rank: int,
                      timeout: Optional[float] = None) -> Dict:
        """MSG_STATS over the probe path (own one-shot connection,
        triage-scale timeout) — the cluster aggregator's poll primitive.
        :meth:`stats` rides the shared data conn and is the right call
        for a worker consulting a healthy peer; a periodic cluster poll
        must instead survive exactly the degraded states it exists to
        observe, so it gets the same isolation as MSG_HEALTH: a wedged
        data plane (or this rank's own outstanding traffic) can never
        stall it, and an unanswering rank costs ps_health_timeout, not
        ps_timeout."""
        return self._oneshot_pull(rank, MSG_STATS, timeout)

    def _probe_addr(self, rank: int, timeout: float) -> str:
        """Resolve ``rank``'s address for a one-shot probe, WITHOUT the
        data-plane peer registry's liveness gate: _peer() fails fast
        inside the reconnect-backoff window, which would report a rank
        "dead" during exactly the transient the probe exists to
        classify — and a probe-only caller must not construct a full
        persistent peer (socket + recv thread) just to learn an
        address. A healthy cached peer donates its addr; otherwise the
        rendezvous re-resolves (so a restarted incarnation's fresh
        address is honored)."""
        with self._peers_lock:
            peer = self._peers.get(rank)
        if peer is not None and peer._dead is None:
            return peer.addr
        if self._rendezvous is not None:
            try:
                return self._rendezvous.lookup(
                    rank, min(config.get_flag("ps_connect_timeout"),
                              timeout))
            except PSError:
                if peer is None:
                    raise
                return peer.addr   # dead peer's last known address
        if peer is not None:
            return peer.addr
        raise PSError("no rendezvous configured for remote ranks")

    def _oneshot_pull(self, rank: int, msg_type: int,
                      timeout: Optional[float] = None) -> Dict:
        if rank == self.rank:
            return (self.health_payload() if msg_type == MSG_HEALTH
                    else self.stats_payload())
        timeout = timeout or config.get_flag("ps_health_timeout")
        addr = self._probe_addr(rank, timeout)
        # probe retries ride the shared policy (utils/retry.py) inside
        # ONE overall timeout — deadline propagation: each attempt's
        # socket budget is the REMAINING triage time, so attempts > 1
        # rides out a restarting rank's transient RST without ever
        # holding a supervisor poll past ps_health_timeout
        deadline = _retry.deadline_in(timeout)
        try:
            return _retry.call_with_retries(
                lambda: oneshot_probe(
                    addr, msg_type,
                    max(_retry.remaining_s(deadline, timeout), 0.05),
                    config.get_flag("ps_connect_timeout")),
                attempts=config.get_flag("ps_probe_attempts"),
                deadline=deadline,
                retry_on=(OSError, wire.WireError, TimeoutError),
                backoff=_retry.Backoff(base_s=0.05, cap_s=0.5))
        except (OSError, wire.WireError, TimeoutError) as e:
            raise PSPeerError(
                f"probe (type 0x{msg_type:X}) to rank {rank} at {addr} "
                f"failed: {e}") from e

    # ------------------------- multi-owner super-frames --------------- #
    def _owner_service(self, owner: int) -> "PSService":
        """Resolve a super-frame sub-op's owning service: this rank, or
        a colocated sibling through the process registry (ps/spmd.py).
        A previously-routed owner observed gone raises the typed peer
        error AND fires the death hooks — a super-framed sub-op must
        signal a dead shard exactly like a dying socket would (the
        send-window replay plane re-arms off that hook). An owner that
        was NEVER colocated is a routing error."""
        if owner == self.rank:
            return self
        svc = _spmd.colocated_service(self._proc_key, owner)
        if svc is not None:
            self._routed_seen.add(owner)
            if owner in self._routed_dead:
                # fresh incarnation registered (respawn): clear the
                # tombstone — same rule as _route, or a SECOND death of
                # this rank would never re-fire the hooks
                self._routed_dead.discard(owner)
                with self._peers_lock:
                    self._dead_ranks.pop(owner, None)
            return svc
        if owner in self._routed_seen:
            if owner not in self._routed_dead:
                self._routed_dead.add(owner)
                self._note_death(owner)
            raise PSPeerError(
                f"rank {owner} (in-process route) is down")
        raise PSError(
            f"super-frame sub-op for rank {owner}, which is not "
            f"colocated with rank {self.rank}")

    def multi_local(self, subs: Sequence[Tuple[int, Dict, Sequence]]
                    ) -> List[cf.Future]:
        """In-process super-frame dispatch from PYTHON objects: one
        task on this client's serial executor runs every sub-op across
        the colocated shards (grouped SPMD/np fast paths included) and
        resolves one future per sub — the routed fan-out's hot path,
        with ZERO wire encode/parse on either side (the socket-framed
        MSG_MULTI pays that only when a super-frame actually crosses a
        wire). Ordering: same executor queue as every other routed op,
        so per-(client, owner) FIFO holds."""
        futs: List[cf.Future] = [cf.Future() for _ in subs]
        # INLINE on the caller thread (like every routed dispatch when
        # the fan-out plane is armed): program order IS per-owner FIFO,
        # and an executor hop would cost two thread wakeups per op — on
        # an oversubscribed host the scheduler latency of that
        # ping-pong dominated the op itself (measured: 2 workers at 2
        # shards ran 2x SLOWER than one until dispatch went inline)
        try:
            results = self._handle_multi_obj(subs)
        except Exception as e:   # noqa: BLE001 — transport-level
            for f in futs:
                f.set_exception(e)
            return futs
        for f, (ok, rm, ra) in zip(futs, results):
            if ok:
                f.set_result((rm, ra))
            elif rm.get("peer"):
                # rethrow TYPED: callers branch on PSPeerError (dead
                # owner → retry/failover) vs PSError (fail fast), and a
                # sub-op riding a super-frame must not lose that
                f.set_exception(PSPeerError(rm.get("error", "?")))
            else:
                f.set_exception(PSError(rm.get("error", "?")))
        return futs

    def _handle_multi(self, meta: Dict, arrays: Sequence[np.ndarray]
                      ) -> Tuple[Dict, List[np.ndarray]]:
        """Wire entry for a MSG_MULTI super-frame (socket / native
        punt): unpack the inner frames, run the shared sub-op engine,
        and pack the inner replies (OK or ERR per sub, in order) the
        same way."""
        subs = wire.unpack_batch(arrays)
        results = self._handle_multi_obj(subs)
        blobs = [wire.encode(MSG_REPLY_OK if ok else MSG_REPLY_ERR,
                             i, rm, ra)
                 for i, (ok, rm, ra) in enumerate(results)]
        return {"n": len(subs)}, wire.pack_batch(blobs)

    def _handle_multi_obj(self, subs: Sequence[Tuple[int, Dict,
                                                     Sequence]]
                          ) -> List[Tuple[bool, Dict, Any]]:
        """The super-frame sub-op engine: dispatch every ``(msg_type,
        meta, arrays)`` sub-op to its owning colocated shard and return
        ``(ok, reply_meta, reply_arrays)`` per sub. Plain (unstamped)
        row adds and gets whose target shards share an ACTIVE
        mesh-stacked plane collapse into ONE SPMD dispatch per kind
        (MeshStack.apply_grouped / gather_grouped); host-numpy shards
        python alone serves take a direct lock+apply/gather fast path
        (no coalescing-queue event round trip — this executor thread is
        the one server for the client's routed ops); everything else —
        batch frames, replay-stamped frames, state ops, natively-
        registered shards — dispatches through the shard's ordinary
        handler in frame order. Per-sub failures come back as per-sub
        errors (per-owner independence: sub K failing must not fail sub
        K+1); grouping requires each owner to appear at most once, else
        the whole frame falls back to in-order per-sub dispatch."""
        n = len(subs)
        results: List[Optional[Tuple[bool, Dict, Any]]] = [None] * n
        owners = [int(m.get(wire.OWNER_META_KEY, self.rank))
                  for _mt, m, _a in subs]
        groupable = len(set(owners)) == n
        add_group: List[Tuple[int, Any, Dict, Sequence]] = []
        get_group: List[Tuple[int, Any, Dict, Sequence]] = []
        direct: List[int] = []
        from multiverso_tpu.ps.shard import RowShard as _RowShard
        for i, (mt, m, arrs) in enumerate(subs):
            shard = None
            if groupable and mt in (MSG_ADD_ROWS, MSG_GET_ROWS):
                try:
                    shard = self._owner_service(
                        owners[i])._shards.get(m.get("table"))
                except PSError as e:
                    results[i] = (False, _sub_err(e), [])
                    continue
            plane = getattr(shard, "_plane", None)
            # the np fast path mirrors the plane grouping for
            # host-numpy shards python alone serves: direct lock+apply
            # and pinned gather, skipping the per-request machinery a
            # socket frame needs. Natively-registered shards keep the
            # ordinary handler (its wrapper holds the C++ shard mutex).
            np_fast = (type(shard) is _RowShard and shard._np_mode
                       and shard._native_ref is None)
            if (mt == MSG_ADD_ROWS
                    and wire.REPLAY_CLIENT_KEY not in m
                    and ((plane is not None and plane.active)
                         or np_fast)):
                add_group.append((i, shard, m, arrs))
            elif (mt == MSG_GET_ROWS and not m.get("sparse")
                    and not m.get("chunk")
                    and ((plane is not None and plane.active)
                         or np_fast)):
                get_group.append((i, shard, m, arrs))
            else:
                direct.append(i)
        # one SPMD dispatch for all grouped adds (per-sub validation
        # errors stay per-sub; a dispatch-level failure fails exactly
        # the subs that were in it)
        if add_group:
            entries = []
            for i, shard, m, arrs in add_group:
                try:
                    local, vals, opt = shard._prep_add(m, arrs)
                    entries.append((i, shard, local, vals, opt))
                except Exception as e:  # noqa: BLE001 — per sub
                    results[i] = (False,
                                  _sub_err(e),
                                  [])
            planes: Dict[int, List] = {}
            np_done: List[Tuple[Any, float, int]] = []
            from multiverso_tpu.updaters import \
                STATELESS_LINEAR as _LINEAR
            for ent in entries:
                p = ent[1]._plane
                if p is not None and p.active:
                    planes.setdefault(id(p), []).append(ent)
                    continue
                # np fast path: apply under the shard lock directly —
                # the coalescing queue exists to merge CONCURRENT
                # senders' adds, and the routed plane's callers apply
                # in program order. The lock hold is the MUTATION
                # alone: the telemetry sinks (shared apply monitor,
                # global flightrec ring) have their own locks, and
                # nesting them inside n shard locks per super-frame
                # chained lock convoys across every concurrent worker.
                i, s, l, v, o = ent
                try:
                    sign = _LINEAR[type(s.updater)]
                    t0 = time.perf_counter()
                    with s._lock:
                        data = s._writable_data()
                        if sign > 0:
                            data[l] += v
                        else:
                            data[l] -= v
                        if s._dirty is not None:
                            s._dirty[:, l] = True
                        s._version += 1
                        s._record_wave(1)
                        s._stat_adds += 1
                        s._stat_applies += 1
                    np_done.append((s, (time.perf_counter() - t0) * 1e3,
                                    int(v.nbytes)))
                    results[i] = (True, {}, [])
                except Exception as e:  # noqa: BLE001 — per sub
                    results[i] = (False,
                                  _sub_err(e),
                                  [])
            if np_done:
                # off-lock telemetry: per-shard apply histogram samples
                # plus ONE flight edge + beat for the frame's np waves
                for s, ms, _nb in np_done:
                    s._mon_apply.observe_ms(ms)
                _flight.beat("apply")
                _flight.record(_flight.EV_APPLY,
                               nbytes=sum(nb for _s, _m, nb in np_done),
                               note=f"multi np ops={len(np_done)}")
            for group in planes.values():
                plane = group[0][1]._plane
                try:
                    plane.apply_grouped(
                        [(s, l, v, o) for _i, s, l, v, o in group])
                    for _i, s, _l, _v, _o in group:
                        with s._lock:
                            s._record_wave(1)
                            s._stat_adds += 1
                            s._stat_applies += 1
                    for i, *_rest in group:
                        results[i] = (True, {}, [])
                except Exception as e:  # noqa: BLE001
                    err = _sub_err(e)
                    for i, *_rest in group:
                        results[i] = (False, dict(err), [])
        # grouped gets: ONE SPMD dispatch per stacked plane; np shards
        # serve off the shared pinned-epoch body directly
        if get_group:
            pairs = []
            np_srv_bytes = 0
            np_srv = 0
            for i, shard, m, arrs in get_group:
                p = shard._plane
                if p is None or not p.active:
                    try:
                        if (shard._host_serve
                                and m.get("wire", "none") == "none"):
                            # np fast path: ONE lock hold around the
                            # small gather — the pin/release round trip
                            # costs TWO contended lock handoffs per sub
                            # (sampled: a quarter of the 2-worker wall
                            # sat in _pin_data), and a fan-out part's
                            # gather is tiny
                            s = shard
                            local = s._localize_raw(arrs[0])
                            s._note_rows(local)
                            with s._lock:
                                rows = np.asarray(s._data)[local]
                            s._stat_gets += 1
                            s._stat_get_bytes += int(rows.nbytes)
                            np_srv += 1
                            np_srv_bytes += int(rows.nbytes)
                            results[i] = (True, {}, [rows])
                        else:
                            results[i] = (
                                True, *shard._serve_get_rows(m, arrs))
                    except Exception as e:  # noqa: BLE001 — per sub
                        results[i] = (
                            False,
                            _sub_err(e), [])
                    continue
                try:
                    local = shard._localize_raw(arrs[0])
                    shard._note_rows(local)
                    pairs.append((i, shard, m, local))
                except Exception as e:  # noqa: BLE001 — per sub
                    results[i] = (False,
                                  _sub_err(e),
                                  [])
            if np_srv:
                # ONE flight edge for the frame's np-served gathers
                _flight.record(_flight.EV_GET_SERVE,
                               nbytes=np_srv_bytes,
                               note=f"multi np ops={np_srv}")
            if pairs:
                planes = {}
                for ent in pairs:
                    planes.setdefault(id(ent[1]._plane), []).append(ent)
                for group in planes.values():
                    plane = group[0][1]._plane
                    try:
                        blocks = plane.gather_grouped(
                            [(s, l) for _i, s, _m, l in group])
                        for (i, s, m, l), rows in zip(group, blocks):
                            w = m.get("wire", "none")
                            payload = wire.encode_payload(rows, w)
                            s._stat_gets += 1
                            s._stat_get_bytes += sum(
                                int(a.nbytes) for a in payload)
                            _flight.record(
                                _flight.EV_GET_SERVE,
                                nbytes=l.size * s.num_col
                                * s.dtype.itemsize)
                            results[i] = (True, {}, payload)
                    except Exception as e:  # noqa: BLE001
                        err = _sub_err(e)
                        for i, *_rest in group:
                            results[i] = (False, dict(err), [])
        # everything else: in-order per-sub dispatch through the owning
        # shard's ordinary handler (stamp gates, batch waves, native
        # mutex wrappers all apply exactly as for a direct frame)
        for i in direct:
            mt, m, arrs = subs[i]
            try:
                svc2 = self._owner_service(owners[i])
                handler = svc2._wait_handler(m["table"])
                with monitor(f"ps[{m['table']}].serve"):
                    rmeta, rarrays = handler(mt, m, arrs)
                if isinstance(rarrays, wire.ChunkedReply):
                    raise PSError(
                        "chunk-streamed replies cannot ride a "
                        "super-frame")
                results[i] = (True, rmeta, rarrays)
            except Exception as e:  # noqa: BLE001 — per sub
                results[i] = (False,
                              _sub_err(e), [])
        return results

    def _wait_handler(self, table: str, timeout: float = 20.0) -> Callable:
        # a worker can race ahead of a peer still constructing its tables
        # (the reference serialized this through MV_CreateTable's barrier;
        # the async plane just waits at the server)
        with self._handlers_cv:
            deadline = time.monotonic() + timeout
            while table not in self._handlers:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._handlers_cv.wait(remaining):
                    raise PSError(f"no such table {table!r} on rank "
                                  f"{self.rank} (after {timeout}s)")
            return self._handlers[table]

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # single read: close()'s leak branch clears self._native while
            # this thread may be between the check and the call — a second
            # read here would hand serve_fd a null server
            native = self._native
            if native is not None:
                from multiverso_tpu.ps import native as ps_native
                # hand the fd to a C++ serving thread (detach: the C++
                # side owns it now; close() reaches it via the native
                # server, not self._conns)
                ps_native.serve_fd(native, conn.detach())
                continue
            with self._conns_lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="ps-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()
        try:
            while not self._closed:
                msg_type, msg_id, meta, arrays = wire.recv(conn)
                # serve-loop heartbeat + request edge for the black box
                # (natively-served ops bypass Python and stay unrecorded,
                # same rule as tracing). Probes neither beat nor hit the
                # ring: see _punt.
                if msg_type not in (MSG_PING, MSG_STATS, MSG_HEALTH):
                    _flight.beat("serve")
                    _flight.record(_flight.EV_RECV, msg_type=msg_type,
                                   msg_id=msg_id)
                if msg_type == MSG_PING:
                    with send_lock:
                        wire.send(conn, MSG_REPLY_OK, msg_id,
                                  {"rank": self.rank})
                    continue
                if msg_type in (MSG_STATS, MSG_HEALTH):  # telemetry pulls
                    try:
                        payload = (self.stats_payload()
                                   if msg_type == MSG_STATS
                                   else self.health_payload())
                    except Exception as e:  # noqa: BLE001
                        with send_lock:
                            wire.send(conn, MSG_REPLY_ERR, msg_id,
                                      {"error": f"{type(e).__name__}: {e}"})
                        continue
                    with send_lock:
                        wire.send(conn, MSG_REPLY_OK, msg_id, payload)
                    continue
                try:
                    # chaos plane (ps/faults.py): slow-serve sleeps
                    # before the handler (a slow RANK, not a slow
                    # wire); drop_reply serves the request but never
                    # answers — an ack lost after the apply, which the
                    # client's replay plane must dedupe on retry
                    drop_reply = False
                    if _faults.PLANE.armed:
                        _slow_s, drop_reply = _faults.PLANE.plan_serve(
                            msg_type, msg_id, rank=self.rank)
                        if _slow_s:
                            time.sleep(_slow_s)
                    tr = (meta.get(wire.TRACE_META_KEY)
                          if _trace.enabled() else None)
                    t0 = time.time() if tr is not None else 0.0
                    # server-side Dashboard visibility (ref MONITOR_BEGIN
                    # around Server::ProcessAdd/Get, src/server.cpp:37-45)
                    if msg_type == MSG_MULTI:
                        # multi-owner super-frame over a real socket:
                        # dispatch across this process's colocated
                        # shards (sub-ops carry their owning rank)
                        with monitor("ps[multi].serve"):
                            rmeta, rarrays = self._handle_multi(
                                meta, arrays)
                    else:
                        handler = self._wait_handler(meta["table"])
                        with monitor(f"ps[{meta['table']}].serve"):
                            rmeta, rarrays = handler(msg_type, meta,
                                                     arrays)
                    if tr is not None:
                        _trace.add_span("ps.serve", t0, time.time(),
                                        trace=tr,
                                        args={"table": meta.get(
                                            "table", "multi"),
                                              "type": msg_type})
                    if isinstance(rarrays, wire.ChunkedReply):
                        # streamed get reply: one MSG_REPLY_CHUNK per
                        # sub-frame as the generator yields (encode of
                        # chunk k+1 overlaps chunk k draining into the
                        # socket), closed by the ordinary OK
                        for cmeta, carrays in rarrays.chunks:
                            if drop_reply:
                                continue   # drain the generator, send
                            with send_lock:  # nothing (injected loss)
                                wire.send(conn, MSG_REPLY_CHUNK, msg_id,
                                          cmeta, carrays)
                            _flight.record(_flight.EV_GET_CHUNK,
                                           msg_type=msg_type,
                                           msg_id=msg_id)
                        rmeta, rarrays = rarrays.meta, ()
                    if not drop_reply:
                        with send_lock:
                            wire.send(conn, MSG_REPLY_OK, msg_id, rmeta,
                                      rarrays)
                        _flight.record(_flight.EV_REPLY,
                                       msg_type=msg_type, msg_id=msg_id)
                except Exception as e:  # reply errors, don't kill the conn
                    log.debug("ps handler error: %s", e)
                    if isinstance(e, MemoryError):
                        # OOM forensics: dump the ledger + device census
                        # through the flight recorder's fault path WHILE
                        # the hoards are still reachable — the one
                        # moment the byte ledger answers "what ate it"
                        _memstats.oom_dump(
                            "MemoryError serving a request")
                    with send_lock:
                        wire.send(conn, MSG_REPLY_ERR, msg_id,
                                  {"error": f"{type(e).__name__}: {e}"})
                    # the ERR reply is a reply edge too (the punt path
                    # records both): without it a handler error reads as
                    # "received, never answered" — a wedged-server
                    # signature — in postmortem timelines
                    _flight.record(_flight.EV_REPLY, msg_type=msg_type,
                                   msg_id=msg_id, note="err")
        except (wire.WireError, OSError):
            pass  # client went away; its shard traffic simply stops
        finally:
            conn.close()
            # drop the registry entry too: one-shot health probes open a
            # conn per poll, and an append-only list would leak a dead
            # socket object per probe for process lifetime (close() only
            # clears the list at teardown)
            with self._conns_lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass   # already cleared by close()

    # ----------------------------- client side ----------------------- #
    def add_death_hook(self, fn: Callable[[int], None]) -> None:
        """``fn(rank)`` runs when a peer connection is observed dead —
        the PS plane's failure signal, consumable by elastic heartbeats
        (elastic.bind_ps) or any supervisor."""
        self._death_hooks.append(fn)

    def dead_ranks(self) -> List[int]:
        """Ranks whose connection died and has not been re-established."""
        with self._peers_lock:
            return sorted(self._dead_ranks)

    def _note_death(self, rank: int, hooks: bool = True,
                    peer: Optional[_Peer] = None) -> None:
        """``hooks=False`` records the failure for reconnect backoff only:
        a rendezvous-lookup/connect timeout may just mean the rank has not
        STARTED yet — only an established socket dying is a death signal
        worth tombstoning (a supervisor keying restarts off elastic.failed
        must not kill a rank that was never up). ``peer`` identifies the
        reporting incarnation: a LATE callback from a superseded peer
        (e.g. its recv loop dying only when the reconnect path closes the
        stale socket) must not re-tombstone a rank whose fresh connection
        is already healthy — that would make dead_ranks()/quiesce skip a
        live rank forever."""
        with self._peers_lock:
            cur = self._peers.get(rank)
            if (peer is not None and cur is not None and cur is not peer
                    and cur._dead is None):
                return   # stale incarnation reporting after replacement
            self._dead_ranks[rank] = time.monotonic()
        if not hooks:
            return
        for fn in self._death_hooks:
            try:
                fn(rank)
            except Exception as e:   # a hook must never break the plane
                log.error("ps death hook failed for rank %d: %s", rank, e)

    def _peer(self, rank: int) -> _Peer:
        # two-phase: the global lock only guards the dict; the (slow)
        # rendezvous lookup + connect runs under a PER-RANK lock, so a dead
        # rank's connect_timeout cannot stall requests to healthy ranks
        with self._peers_lock:
            peer = self._peers.get(rank)
            if peer is not None and peer._dead is None:
                # belt to the incarnation check in _note_death: a healthy
                # peer proves the rank is alive, so any lingering
                # tombstone is stale
                self._dead_ranks.pop(rank, None)
                return peer
            # known-dead rank (cached dead peer OR a recent failed
            # lookup/connect with nothing cached): fail fast inside the
            # backoff window, else re-resolve below — a RESTARTED rank
            # republished its address, so a fresh rendezvous lookup finds
            # the new incarnation (recovery path)
            last = self._dead_ranks.get(rank)
            if (last is not None and time.monotonic() - last
                    < config.get_flag("ps_reconnect_backoff")):
                raise (peer._dead if peer is not None else PSPeerError(
                    f"rank {rank} unreachable (in reconnect backoff)"))
            if peer is not None:
                del self._peers[rank]
                peer.close()   # release the dead socket fd now, not at GC
            lock = self._peer_locks.setdefault(rank, threading.Lock())
        with lock:
            with self._peers_lock:
                peer = self._peers.get(rank)
                if peer is not None and peer._dead is None:
                    return peer
            if self._rendezvous is None:
                raise PSError("no rendezvous configured for remote ranks")
            try:
                addr = self._rendezvous.lookup(
                    rank, config.get_flag("ps_connect_timeout"))
                peer = _Peer(rank, addr,
                             config.get_flag("ps_connect_timeout"),
                             config.get_flag("ps_timeout"),
                             on_death=lambda p, e, r=rank:
                                 self._note_death(r, peer=p),
                             src=self.rank)
            except PSError:
                # lookup/connect failure: backoff yes, death hooks no —
                # the rank may simply not be up yet
                self._note_death(rank, hooks=False)
                raise
            with self._peers_lock:
                stale = self._peers.get(rank)
                self._peers[rank] = peer
                self._dead_ranks.pop(rank, None)   # fresh incarnation
            if stale is not None:
                stale.close()
            return peer

    # ------------------------- native client side --------------------- #
    def native_enabled(self) -> bool:
        """True when this process can open native client connections (the
        remote end may still be pure-Python — the wire is identical)."""
        if not config.get_flag("ps_native"):
            return False
        from multiverso_tpu.ps import native as ps_native
        return ps_native.available()

    def native_conn(self, rank: int):
        """Native client connection to ``rank`` (NativeConn), creating it
        lazily. Liveness, rendezvous, and reconnect-backoff bookkeeping
        stay with the python :meth:`_peer` (which this piggybacks for the
        address); a native conn observed dead is simply dropped — the next
        op re-resolves through _peer, so a restarted rank's fresh address
        is honored. Raises PSPeerError like _peer."""
        from multiverso_tpu.ps import native as ps_native
        with self._peers_lock:
            c = self._nconns.get(rank)
        if c is not None and not c.dead():
            return c
        addr = self.addr if rank == self.rank else self._peer(rank).addr
        try:
            c2 = ps_native.NativeConn(addr,
                                      config.get_flag("ps_connect_timeout"),
                                      config.get_flag("ps_timeout"))
        except ps_native.NativeConnError as e:
            raise PSPeerError(f"rank {rank}: {e}") from e
        with self._peers_lock:
            old = self._nconns.get(rank)
            if old is not None and not old.dead():
                # lost the race to another thread: use theirs
                c2.close()
                return old
            self._nconns[rank] = c2
        if old is not None:
            old.close()
        return c2

    def native_conn_or_none(self, rank: int):
        """:meth:`native_conn` with unreachable ranks mapped to None (the
        fanout paths turn those into failed futures per owner)."""
        try:
            return self.native_conn(rank)
        except PSError:
            return None

    def drop_native_conn(self, rank: int, conn) -> None:
        """Forget a native conn observed dead (kept: death bookkeeping —
        tombstones, hooks — belongs to the python peer plane, which will
        observe the same failure on its own socket)."""
        with self._peers_lock:
            if self._nconns.get(rank) is conn:
                del self._nconns[rank]
        conn.close()

    def native_conns(self):
        with self._peers_lock:
            return list(self._nconns.values())

    def request(self, rank: int, msg_type: int, meta: Dict,
                arrays: Sequence[np.ndarray] = (),
                meta_b: Optional[bytes] = None,
                chunk_sink: Optional[Callable] = None) -> cf.Future:
        """Uncoordinated request to ``rank``; local rank short-circuits the
        socket but keeps async dispatch order via the local executor.
        ``meta_b`` (wire.pack_meta) lets a fan-out op serialize its meta
        once instead of once per remote peer; the local path always uses
        the dict. ``chunk_sink(meta, arrays)`` consumes the sub-frames of
        a chunk-streamed reply as they land on the peer's recv thread
        (the final OK then carries no payload). NEVER raises: a
        dead/unreachable rank yields a future carrying PSPeerError, so
        fire-and-forget callers stay fire-and-forget and multi-owner ops
        keep their live-shard futures."""
        if rank == self.rank:
            return self._dispatch_inproc(self, msg_type, meta, arrays,
                                         chunk_sink)
        # process-coalesced routing (ps/spmd.py; flag ps_fanout): a
        # COLOCATED rank's request skips the localhost socket and
        # dispatches on this client's serial local executor straight
        # into the owning service's handler — per-(client, owner) FIFO
        # (and with it read-your-writes and every window fence) holds
        # because all of one client's routed ops ride ONE queue. A
        # routed rank observed gone (service closed / not yet
        # respawned) fails fast like a dead peer AND fires the death
        # hooks, so the send-window replay plane re-arms exactly as it
        # would off a dying socket.
        rsvc, rerr = self._route(rank)
        if rerr is not None:
            fut: cf.Future = cf.Future()
            fut.set_exception(rerr)
            return fut
        if rsvc is not None:
            return self._dispatch_inproc(rsvc, msg_type, meta, arrays,
                                         chunk_sink)
        try:
            return self._peer(rank).request(
                msg_type, meta if meta_b is None else meta_b, arrays,
                chunk_sink=chunk_sink)
        except PSError as e:
            fut = cf.Future()
            fut.set_exception(e if isinstance(e, PSPeerError)
                              else PSPeerError(str(e)))
            return fut

    def _route(self, rank: int):
        """Resolve ``rank`` to a live colocated service (or a typed
        fast-fail once a previously-routed rank is observed gone).
        ``(None, None)`` = not routed, use the socket path."""
        if self._proc_key is None or not config.get_flag("ps_fanout"):
            return None, None
        svc = _spmd.colocated_service(self._proc_key, rank)
        if svc is not None:
            self._routed_seen.add(rank)
            if rank in self._routed_dead:
                # fresh incarnation registered (respawn): clear the
                # tombstone so backoff-free routing resumes
                self._routed_dead.discard(rank)
                with self._peers_lock:
                    self._dead_ranks.pop(rank, None)
            return svc, None
        if rank in self._routed_seen:
            err = PSPeerError(
                f"rank {rank} (in-process route) is down")
            if rank not in self._routed_dead:
                self._routed_dead.add(rank)
                self._note_death(rank)
            return None, err
        return None, None

    def _dispatch_inproc(self, svc: "PSService", msg_type: int,
                         meta: Dict, arrays,
                         chunk_sink: Optional[Callable]) -> cf.Future:
        """The local short-circuit, generalized to any colocated
        service. With the fan-out plane armed (flag ``ps_fanout``), the
        dispatch runs INLINE on the caller thread: the caller's program
        order IS per-owner FIFO (stronger than the executor queue), and
        skipping the two thread wakeups per op removes the scheduler
        ping-pong that dominated routed round trips on oversubscribed
        hosts. With the plane off (the classic local-rank path), the
        serial executor keeps the established fire-and-forget timing.
        Multi-owner super-frames dispatch through the target's
        :meth:`_handle_multi`."""
        fut: cf.Future = cf.Future()

        def _run():
            try:
                if msg_type == MSG_MULTI:
                    rmeta, rarrays = svc._handle_multi(meta, arrays)
                else:
                    handler = svc._wait_handler(meta["table"])
                    rmeta, rarrays = handler(msg_type, meta, arrays)
                if isinstance(rarrays, wire.ChunkedReply):
                    # in-process dispatch: drive the sink inline (no
                    # socket to overlap, but the caller's scatter
                    # contract holds); clients normally skip the
                    # chunk request for in-process ranks entirely
                    if chunk_sink is None:
                        raise PSError(
                            "chunked reply without a chunk sink on "
                            "the local path")
                    for cmeta, carrays in rarrays.chunks:
                        chunk_sink(cmeta, carrays)
                    rmeta, rarrays = rarrays.meta, []
                fut.set_result((rmeta, rarrays))
            except Exception as e:
                fut.set_exception(e)

        if config.get_flag("ps_fanout"):
            _run()
        else:
            self._local_exec.submit(_run)
        return fut

    def ping(self, rank: int, timeout: Optional[float] = None) -> bool:
        if rank == self.rank:
            return True
        try:
            self._peer(rank).request(MSG_PING, {}, ()).result(
                timeout or config.get_flag("ps_timeout"))
            return True
        except (PSError, cf.TimeoutError):
            return False

    def close(self) -> None:
        # the cluster aggregator polls THROUGH this service: stop it
        # (final short-timeout poll included) while the probe path is
        # still alive — afterwards a poll would just record every rank
        # unreachable. The shard checkpointer stops with a FINAL save
        # while the shards are intact: a cleanly-closing rank's tail of
        # applies must stay durable for whoever inherits its rows.
        _aggregator.stop_if_bound(self)
        _failover.stop_if_bound(self)
        self._closed = True
        # mesh data plane (ps/spmd.py): leave the routing registry (so
        # colocated clients observe this rank's death like a dead
        # socket) and evict this service's shards from their stacked
        # groups — they keep working standalone for the failover
        # checkpointer's final save below
        _spmd.release_service(self)
        # shutdown, not just close: close() does NOT wake a thread blocked
        # in accept() on Linux — shutdown() makes accept return EINVAL
        # immediately (close alone left the join below eating its timeout
        # on every service teardown)
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        # the accept thread must be DONE before the native server is
        # freed: it could otherwise adopt a last-instant connection into
        # freed memory
        if self._accept_thread.is_alive():
            self._accept_thread.join(timeout=10.0)
        if self._accept_thread.is_alive():
            # A wedged accept thread could still call serve_fd into the
            # native server; freeing it now would be a use-after-free.
            # Leak the native server instead (process is tearing down or
            # the test harness will kill it) and log loudly.
            log.error("ps service close: accept thread did not exit in "
                      "10s; leaking native server instead of freeing it")
            with self._native_lock:
                self._native = None
            # NOT clearing _native_cb: the leaked server's C++ threads
            # still hold the ctypes trampoline — freeing it under them
            # (by dropping the last reference) would be the same
            # use-after-free this branch exists to avoid.
        # drop accepted connections too, so an in-process "killed" service
        # actually goes silent (a killed OS process gets this for free)
        with self._conns_lock:
            for conn in self._conns:
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                conn.close()
            self._conns.clear()
        with self._native_lock:
            native, self._native = self._native, None
        if native is not None:
            from multiverso_tpu.ps import native as ps_native
            # joins the C++ serving threads (any in-flight punt callback
            # finishes first — ctypes released the GIL for this call)
            ps_native.server_free(native)
            self._native_cb = None
        with self._peers_lock:
            nconns, self._nconns = list(self._nconns.values()), {}
            for peer in self._peers.values():
                peer.close()
            self._peers.clear()
        for c in nconns:
            c.close()
        self._local_exec.shutdown(wait=True)


# ---------------------------------------------------------------------- #
# default per-process context
# ---------------------------------------------------------------------- #
class PSContext:
    """Bundle of (rank, world, service) used by the async tables. Built
    from the JAX multi-controller topology by default; tests construct
    standalone contexts to simulate N ranks in-process."""

    def __init__(self, rank: int, world: int, service: PSService):
        self.rank, self.world, self.service = rank, world, service

    def quiesce(self) -> None:
        """Shutdown handshake (the reference's MV_ShutDown barrier,
        src/zoo.cpp:103-115): mark this rank done through the rendezvous
        and keep serving until every live peer is done too — a fast rank's
        teardown must not kill peers still pulling from its shard.
        Observed-dead ranks are skipped; timing out proceeds with a
        warning (an unobserved crash must not wedge shutdown forever)."""
        rdv = self.service._rendezvous
        if self.world <= 1 or rdv is None or not hasattr(rdv, "mark"):
            return
        # reserved tag (must not collide with user/harness markers in the
        # same rendezvous dir — utils/filesync.file_barrier writes
        # "<tag>.<rank>" files there too); the marker VALUE is this
        # incarnation's published address, so a reused rendezvous dir's
        # stale markers never satisfy the current run's barrier
        rdv.mark(self.rank, "ps_quiesce", self.service.addr)
        deadline = time.monotonic() + config.get_flag("ps_shutdown_grace")
        for r in range(self.world):
            if r == self.rank or r in self.service.dead_ranks():
                continue
            remaining = deadline - time.monotonic()
            try:
                expect = rdv.lookup(r, min(max(remaining, 0.001), 5.0))
            except PSError:
                continue   # never published: the rank never came up
            if remaining <= 0 or not rdv.wait_mark(
                    r, "ps_quiesce", remaining, expect=expect):
                # keep waiting on the REMAINING ranks — one laggard (or a
                # transient KV error reading its marker) must not collapse
                # the barrier for everyone after it
                log.error("ps shutdown: rank %d did not reach shutdown "
                          "within ps_shutdown_grace; not waiting for it", r)

    def close(self, quiesce: bool = False) -> None:
        if quiesce:
            try:
                self.quiesce()
            except Exception as e:
                # the handshake is best-effort: a vanished rendezvous dir
                # or dead coordinator must not abort shutdown and leak the
                # service's sockets/threads
                log.error("ps shutdown quiesce failed (%s: %s); closing "
                          "anyway", type(e).__name__, e)
        # final telemetry flush BEFORE the service dies: the last metrics
        # record and any buffered trace spans must survive a short run.
        # export_global, NOT stop_global: a process may hold several
        # contexts (test fixtures, bench workers) and one closing must
        # not kill the exporter for the rest — the global exporter stops
        # at Zoo.stop (app teardown) or with the process.
        try:
            _exporter.export_global()
            d = config.get_flag("metrics_dir")
            if d:
                _trace.dump_to(d)
                _profiler.dump_to(d)
        except Exception as e:  # noqa: BLE001 — telemetry never blocks
            log.error("telemetry flush at close failed: %s", e)  # shutdown
        self.service.close()


_default_ctx: Optional[PSContext] = None
_default_lock = threading.Lock()


def default_context() -> PSContext:
    global _default_ctx
    with _default_lock:
        if _default_ctx is None:
            world = config.get_flag("ps_world")
            rank = config.get_flag("ps_rank")
            if world <= 0:
                import jax
                rank, world = jax.process_index(), jax.process_count()
            elif rank < 0:
                raise PSError("ps_world set but ps_rank is not")
            rdv = None
            if world > 1:
                rdv_dir = config.get_flag("ps_rendezvous")
                rdv = (FileRendezvous(rdv_dir) if rdv_dir
                       else JaxRendezvous())
            _default_ctx = PSContext(
                rank, world, PSService(rank, world, rdv))
        return _default_ctx


def reset_default_context() -> None:
    global _default_ctx
    with _default_lock:
        if _default_ctx is not None:
            # the default (app-flow) context quiesces: every rank got here
            # via mv.shutdown, so the handshake converges quickly; test
            # fixtures closing explicit contexts sequentially skip it
            _default_ctx.close(quiesce=True)
            _default_ctx = None
