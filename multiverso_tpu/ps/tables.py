"""Async tables: the uncoordinated cross-process Add/Get client plane.

TPU-native equivalent of the reference WorkerTable family in *async* mode
(ref: src/worker.cpp:30-76 — Partition a request into per-server messages,
track expected replies; src/table/matrix_table.cpp:266-313 — route row ids
by ``row_id / rows_per_server``; include/multiverso/table_interface.h:24-46
— Get/Add/GetAsync/AddAsync/Wait). Every process owns a contiguous row
block of each table (its :class:`~multiverso_tpu.ps.shard.RowShard`, on its
local device); a client partitions each op by owner rank and sends
uncoordinated requests — workers at different rates, with different row
sets, never waiting on each other. This is the plane the sync tables
(lockstep XLA collectives) cannot provide; see multiverso_tpu/ps/__init__.

msg-id bookkeeping matches the sync tables: every async op returns a msg
id; ``wait(id)`` blocks on the underlying request futures (the reference's
Waiter, src/table.cpp:27-97).
"""

from __future__ import annotations

import collections
import concurrent.futures as cf
import contextlib
import itertools
import os
import threading
import time
import weakref
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from multiverso_tpu import updaters as updaters_lib
from multiverso_tpu.ps import service as svc
from multiverso_tpu.ps import wire as wire_mod
from multiverso_tpu.ps.shard import KVShard, RowShard
from multiverso_tpu.serving import hotcache as _hotcache
from multiverso_tpu.telemetry import flightrec as _flight
from multiverso_tpu.telemetry import memstats as _memstats
from multiverso_tpu.telemetry import profiler as _profiler
from multiverso_tpu.telemetry import tenants as _tenants
from multiverso_tpu.telemetry import trace as ttrace
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils import config, log
from multiverso_tpu.utils import retry as _retry
from multiverso_tpu.utils.dashboard import Dashboard, monitor


# ---------------------------------------------------------------------- #
# native-transport futures: Future-shaped handles over the C++ client
# (ps/native.py). They plug into the same _track/wait/flush bookkeeping as
# the python _Peer futures — done()/exception()/result(timeout) is all the
# plane consumes.
# ---------------------------------------------------------------------- #
def _failed_future(exc: Exception) -> cf.Future:
    f: cf.Future = cf.Future()
    f.set_exception(exc if isinstance(exc, svc.PSPeerError)
                    else svc.PSPeerError(str(exc)))
    return f


class _NativeAddFuture:
    """Counted fire-and-forget add: complete when the conn's ack counter
    reaches this op's sequence number — no Python wakeup per reply. A
    server ERR reply binds to this op alone (by msg id), matching the
    python plane's per-future errors."""

    __slots__ = ("_conn", "_seq", "_mid", "_exc")

    def __init__(self, conn, seq: int, mid: int):
        self._conn, self._seq, self._mid = conn, seq, mid
        self._exc: Optional[Exception] = None

    def done(self) -> bool:
        if self._conn.dead():
            return True
        done = self._conn.adds_done()
        return done < 0 or done >= self._seq

    def result(self, timeout=None):
        from multiverso_tpu.ps.native import NativeConnError
        if self._exc is not None:
            raise self._exc
        try:
            self._conn.wait_adds(self._seq,
                                 3600.0 if timeout is None else timeout)
        except TimeoutError as e:
            raise cf.TimeoutError(str(e)) from None
        except NativeConnError as e:
            self._exc = svc.PSPeerError(str(e))
            raise self._exc from None
        err = self._conn.take_add_error(self._mid)
        if err is not None:
            self._exc = svc.PSError(err)
            raise self._exc
        return ({}, [])

    def exception(self):
        if not self.done():
            return None
        try:
            self.result(timeout=1.0)
        except Exception as e:   # noqa: BLE001 — the sweep logs it
            return e
        return None


class _NativeGetFuture:
    """Buffer-filling get: the C++ recv thread copies the reply payload
    straight into ``out``; result() blocks on the native wait."""

    __slots__ = ("_conn", "_mid", "_out", "_state", "_exc")

    def __init__(self, conn, mid: int, out: np.ndarray):
        self._conn, self._mid, self._out = conn, mid, out
        self._state = "pending"
        self._exc: Optional[Exception] = None

    def done(self) -> bool:
        return self._state != "pending"

    def __del__(self):
        # abandoned while pending (a sibling owner's failure aborted the
        # whole op): cancel so the C++ recv thread can never scatter into
        # the (about to be GC'd) out buffer
        try:
            if self._state == "pending":
                self._conn.get_cancel(self._mid)
        except Exception:   # noqa: BLE001 — interpreter teardown
            pass

    def result(self, timeout=None):
        from multiverso_tpu.ps.native import NativeConnError
        if self._state == "error":
            raise self._exc
        if self._state == "pending":
            try:
                self._conn.get_wait(self._mid,
                                    3600.0 if timeout is None else timeout)
            except TimeoutError as e:
                # the native side dropped the pending entry: this future
                # can never complete now — pin the failure
                self._exc = svc.PSPeerError(f"native get: {e}")
                self._state = "error"
                raise cf.TimeoutError(str(e)) from None
            except NativeConnError as e:
                self._exc = svc.PSPeerError(str(e))
                self._state = "error"
                raise self._exc from None
            self._state = "done"
        return ({}, [self._out])

    def exception(self):
        return self._exc


def _native_add(service, rank: int, msg_type: int, meta_b: bytes,
                ids: Optional[np.ndarray], vals: np.ndarray):
    """One counted add on the native conn to ``rank``; failures come back
    as failed futures so multi-owner fan-outs keep their live shards
    (mirrors service.request's never-raise contract)."""
    conn = None
    try:
        conn = service.native_conn(rank)
        seq, mid = conn.add(msg_type, meta_b, ids, vals)
        return _NativeAddFuture(conn, seq, mid)
    except svc.PSError as e:
        return _failed_future(e)
    except Exception as e:   # NativeConnError mid-send: conn is toast
        if conn is not None:
            service.drop_native_conn(rank, conn)
        return _failed_future(e)


def _native_get(service, rank: int, msg_type: int, meta_b: bytes,
                ids: Optional[np.ndarray], out: np.ndarray):
    conn = None
    try:
        conn = service.native_conn(rank)
        mid = conn.get_send(msg_type, meta_b, ids, out)
        return _NativeGetFuture(conn, mid, out)
    except svc.PSError as e:
        return _failed_future(e)
    except Exception as e:
        if conn is not None:
            service.drop_native_conn(rank, conn)
        return _failed_future(e)


def _fanout_futures(parts, make):
    """Shared shaping of add_fanout/get_fanout results into futures: an
    unreachable owner becomes a failed future (live shards unaffected),
    everything else goes through ``make(conn, seq, mid)``."""
    return [(_failed_future(svc.PSPeerError(f"rank {r} unreachable over "
                                            "native transport"))
             if conn is None else make(conn, seq, mid))
            for r, conn, seq, mid in parts]


def _resolve_updater(updater, num_workers: int, dtype):
    if updater is None:
        updater = config.get_flag("updater_type")
    if isinstance(updater, str):
        updater = updaters_lib.get_updater(updater, num_workers=num_workers,
                                           dtype=dtype)
    return updater


def _dedupe_batch(row_ids, num_col: int, dtype,
                  bound: Optional[int], values=None):
    """Validate + dedupe a row/key batch, accumulating duplicate values in
    float64 (one implementation for range-sharded rows and hash keys).
    Returns (unique_ids, vals | None, inverse) where ``inverse=None``
    means the ids were already unique and kept in caller order — the
    overwhelmingly common case (one minibatch touches each row once),
    which skips the sort-ordering, the float64 accumulate, and the
    caller's ``out[inv]`` re-expansion copy (measured ~1 ms of client CPU
    per 1024x128 add on the old always-dedupe path — the single biggest
    per-op cost on the async plane)."""
    raw = np.asarray(row_ids)
    if raw.size == 0:
        raise ValueError("empty row_ids")
    if not np.issubdtype(raw.dtype, np.integer):
        raise TypeError(f"row_ids must be integers, got {raw.dtype}")
    ids = np.asarray(raw, np.int64).reshape(-1)   # no copy if already i64
    if ids.min() < 0:
        raise IndexError("row ids/keys must be non-negative")
    if bound is not None and ids.max() >= bound:
        raise IndexError(f"row id out of range [0, {bound})")
    # the sort only exists to detect duplicates — skip it for the 1-row
    # small-add hot path
    if ids.size == 1:
        has_dups = False
    else:
        s = np.sort(ids)
        has_dups = bool(np.any(s[1:] == s[:-1]))
    if not has_dups:
        vals = (None if values is None
                else np.asarray(values, dtype).reshape(ids.size, num_col))
        # own the ids: np.asarray above is zero-copy for int64 input, but
        # async gets re-read these AFTER the reply lands (finalize
        # closures) — a caller refilling a reused id buffer between
        # dispatch and wait() must not corrupt them. (vals need no copy:
        # every consumer slices per-owner with a boolean mask, which
        # always copies.)
        return (ids.copy() if ids.base is not None or ids is raw
                else ids), vals, None
    uids, inv = np.unique(ids, return_inverse=True)
    if values is None:
        return uids, None, inv
    vals = np.asarray(values, dtype).reshape(ids.size, num_col)
    acc = np.zeros((uids.size, num_col), np.float64)
    np.add.at(acc, inv, vals.astype(np.float64))
    return uids, acc.astype(dtype), inv


def _window_loop(ref: "weakref.ref") -> None:
    """Flusher thread body. Holds the window only through a WEAKREF,
    re-resolved each cycle: when the table (and its window) are
    garbage-collected the thread simply exits at its next bounded
    wakeup — a windowed table must not be pinned in memory (with its
    conns and monitors) for process lifetime by its own daemon thread."""
    while True:
        win = ref()
        if win is None:
            return
        step = win._step
        del win
        step()
        # drop the bound method too — it strongly references the window,
        # and anything still held here across the next wait would keep
        # ref() alive forever
        del step


def _complete_window_futures(batch_fut: cf.Future,
                             group_futs: List[List[cf.Future]],
                             owner: int = -1) -> None:
    """Fan a window frame's single ack out to the per-entry placeholder
    futures the callers are tracking (runs on the peer's recv thread).
    ``group_futs`` is aligned with the frame's sub-ops: a partially
    applied batch reports per-sub-op failures in the reply meta
    ("failed" indices), and only THOSE futures carry the error — a
    delta that was durably applied must never be reported lost, or a
    caller honoring the lost-delta contract would re-issue it and
    double-apply."""
    exc: Optional[BaseException] = None
    meta: Dict = {}
    try:
        exc = batch_fut.exception()
        if exc is None:
            res = batch_fut.result()
            if isinstance(res, tuple) and isinstance(res[0], dict):
                meta = res[0]
    except (cf.CancelledError, Exception) as e:   # defensive
        exc = e
    # black box: the window ack edge (runs on the peer's recv thread)
    _flight.record(_flight.EV_WIN_ACK, peer=owner,
                   note=None if exc is None else str(exc)[:120])
    failed = set(meta.get("failed", ()))
    ferr = (svc.PSError("batched add failed at the shard: "
                        f"{meta.get('error', '?')}") if failed else None)
    for i, futs in enumerate(group_futs):
        for f in futs:
            if f.done():
                continue
            if exc is not None:
                f.set_exception(exc)
            elif i in failed:
                f.set_exception(ferr)
            else:
                f.set_result(({}, []))


def _attach_reply_span(futs: List, name: str, t0: float, tid: int,
                       table: str) -> None:
    """Record a client send->reply span when the LAST per-owner future
    completes (runs on a peer recv thread). Only cf.Futures support
    callbacks — native-transport handles never reach here (the native
    fast path is untraced by design)."""
    remaining = [len([f for f in futs if isinstance(f, cf.Future)])]
    lock = threading.Lock()
    if not remaining[0]:
        return

    def _done(_f):
        with lock:
            remaining[0] -= 1
            last = remaining[0] == 0
        if last:
            ttrace.add_span(name, t0, time.time(), trace=tid,
                            args={"table": table})

    for f in futs:
        if isinstance(f, cf.Future):
            f.add_done_callback(_done)


def _attach_profile_end(futs: List, span) -> bool:
    """Close a step-profiler async span when the LAST per-owner future
    completes (runs on a peer recv thread) — the exact round-trip end
    the overlap-credit math needs. Returns False when ANY future lacks
    callback support (native-transport handles — including a MIXED
    list, where a dead owner's already-failed cf placeholder would
    otherwise fire the close at dispatch while the live native
    round-trips are still in flight): the caller then leaves the span
    open and the wait()/sweep fallback closes it conservatively."""
    if any(not isinstance(f, cf.Future) for f in futs):
        return False
    remaining = [len(futs)]
    if not remaining[0]:
        return False
    lock = threading.Lock()

    def _done(_f):
        with lock:
            remaining[0] -= 1
            last = remaining[0] == 0
        if last:
            span.end()

    for f in futs:
        if isinstance(f, cf.Future):
            f.add_done_callback(_done)
    return True


class _RetainedFrame:
    """One replay-retained window frame: everything needed to put the
    EXACT frame back on the wire (same sequence stamp, same meta, same
    blobs) plus the waiter futures its eventual ack fans out to."""

    __slots__ = ("owner", "seq", "msg_type", "meta", "arrays", "gfuts",
                 "acked", "needs_send", "created", "attempts",
                 "retry_since", "episode_attempts")

    def __init__(self, owner: int, seq: int, msg_type: int, meta: Dict,
                 arrays, gfuts):
        self.owner, self.seq = owner, seq
        self.msg_type, self.meta, self.arrays = msg_type, meta, arrays
        self.gfuts = gfuts
        self.acked = False
        self.needs_send = False
        self.created = time.monotonic()
        self.attempts = 0
        # when this frame ENTERED its current replay episode (first
        # failed attempt / owner-death re-arm); None = not replaying.
        # ps_replay_timeout bounds time spent RETRYING, measured from
        # here — a frame acked long ago and re-armed by a late owner
        # death must get the full retry budget, not zero of it
        self.retry_since: Optional[float] = None
        # failed attempts within the CURRENT episode: the exponent of
        # the shared capped-exponential backoff (utils/retry.py) —
        # lifetime `attempts` would punish a frame whose earlier
        # episode resolved cleanly
        self.episode_attempts = 0


def _replay_backoff() -> "_retry.Backoff":
    """The replay plane's instance of the shared retry policy: base =
    ``ps_replay_backoff``, capped at ``ps_replay_backoff_cap`` — early
    retries against a briefly-unreachable owner stay quick, a long
    respawn decays to a bounded poll instead of a flat hammer, and the
    jitter de-synchronizes a fleet of clients re-arming off the same
    death event. Built per scheduling decision (off the hot path; flag
    reads stay test-overridable)."""
    base = config.get_flag("ps_replay_backoff")
    return _retry.Backoff(
        base_s=base,
        cap_s=max(config.get_flag("ps_replay_backoff_cap"), base),
        jitter=0.25)


class _ReplayBuffer:
    """Client half of exactly-once send-window replay (flag
    ``ps_replay``; docs/FAILOVER.md): per-owner monotonic frame
    sequences, the retained-frame log, and the replay schedule.

    Every windowed frame is stamped with (client id, per-owner seq) and
    RETAINED — past its ack — until the owning shard reports it durable
    (the reply's ``dseq`` floor, advanced by the failover checkpointer).
    On a peer death the whole retained tail for that owner re-arms and
    the flusher re-flushes it, oldest first, to whatever incarnation the
    rendezvous resolves next: the restored shard's sequence channels ack
    the already-checkpointed prefix as duplicates and apply the rest —
    no acked op lost, no frame applied twice."""

    # per-process window nonce: a re-created same-named table must get
    # a FRESH sequence channel on the shard — reusing (rank, pid) alone
    # would restart next_seq at 0 under the old channel's floor and the
    # shard would dedupe every fresh frame as already-applied
    _nonce = itertools.count()

    def __init__(self, table):
        self.client_id = (f"r{table.ctx.rank}.{os.getpid()}"
                          f".{next(self._nonce)}")
        self.lock = threading.Lock()
        self.next_seq: Dict[int, int] = {}
        # owner -> seq -> frame, insertion (= seq) order
        self.retained: Dict[int, "collections.OrderedDict[int, _RetainedFrame]"] = {}
        # owner -> count of frames awaiting (re-)send; > 0 blocks direct
        # dispatch of NEW frames so the wire order stays the seq order
        self.pending_send: Dict[int, int] = {}
        # owner -> monotonic deadline of the next replay attempt
        self.next_due: Dict[int, float] = {}
        base = f"table[{table.name}].replay"
        self.mon_replayed = Dashboard.get(base + ".frames")
        self.mon_dups = Dashboard.get(base + ".dups")
        self.mon_dropped = Dashboard.get(base + ".dropped")

    def soonest_due(self) -> Optional[float]:
        with self.lock:
            return min(self.next_due.values()) if self.next_due else None


class _SendWindow:
    """Client-side cross-call add coalescer (the PS *send window*), one
    per windowed table: ``add_rows_async`` enqueues per-owner entries and
    returns immediately; a time/byte/op-bounded flusher ships each
    owner's pending adds as ONE frame — a plain MSG_ADD_ROWS when the
    whole window merged into one logical op, a MSG_BATCH multi-op frame
    otherwise — so a window costs one round-trip and one batched shard
    apply instead of one per call (the classic PS client-side batching
    lever, Li et al. OSDI'14; BytePS's fused small-tensor transfers).

    Exactness: queued entries merge into a single sub-op ONLY when the
    merge is bit-transparent — same effective AddOption, pairwise-
    disjoint row sets, an elementwise wire ("none"/"bf16"), a row-local-
    state updater (``updaters.ROW_LOCAL_STATE``; adam's global step
    counter advances once per apply, so adam never merges); everything
    else stays its own sub-op (its own meta + codec payload) and the
    shard applies the sub-ops in order as conflict-free waves
    (``shard._apply_batch_adds``). Windowed results are therefore
    BIT-IDENTICAL to window-off — the fuzz tests assert it.

    Ordering: each owner's frames leave in enqueue order on the owner's
    ordinary python conn — senders serialize on a per-owner SEND lock
    (taken before popping the queue, so a later sender always ships a
    later batch), while the window lock itself is never held across a
    socket send: an ``add_rows_async`` enqueue can never block behind an
    in-progress flush. A caller that fences (:meth:`flush_pending`) and
    then issues a get on the same conn reads its own writes — per-conn
    FIFO at the server does the rest; the fence does NOT wait for acks.

    Replay (flag ``ps_replay``; docs/FAILOVER.md): frames are stamped
    with (client, per-owner seq), RETAINED past their ack until the
    owning shard reports them checkpoint-durable, and re-flushed in seq
    order when the owner dies — the shard's sequence channels dedupe,
    so an acked op is never lost and no frame applies twice. While an
    owner's retained tail awaits replay, fresh frames to it queue
    behind (seq order IS wire order) and their futures stay pending
    until the restored incarnation acks them; the fence then means
    "queued or retained", and read-your-writes on that owner degrades
    to eventual until the replay drains."""

    def __init__(self, table, window_ms: float, max_bytes: int,
                 max_ops: int):
        # weak: the table owns the window, not vice versa — a strong
        # backref would make table lifetime depend on cyclic GC racing
        # the flusher thread's per-step strong ref (the thread exits by
        # observing ITS weakref die, see _window_loop)
        self._table_ref = weakref.ref(table)
        self._table_name = table.name
        self.window_s = float(window_ms) / 1e3
        self.max_bytes = int(max_bytes)
        self.max_ops = int(max_ops)
        self._cv = threading.Condition()
        # owner -> [(ids, vals, opt, placeholder future, trace id,
        # tenant id)], enqueue order
        self._pending: Dict[int, List[Tuple]] = {}
        self._nbytes: Dict[int, int] = {}
        # per-tenant add budgets (flag tenant_add_qps): tenant -> bucket
        self._tenant_buckets: Dict[str, Any] = {}
        self._send_locks: Dict[int, threading.Lock] = {}
        self._deadline: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        base = f"table[{table.name}].add_rows"
        self._mon_windowed = Dashboard.get(base + ".windowed")
        self._mon_flushes = Dashboard.get(base + ".flushes")
        self._mon_merged = Dashboard.get(base + ".merged_rows")
        # exactly-once replay (flag ps_replay; docs/FAILOVER.md):
        # stamped, retained, re-flushed frames. The peer-death hook is
        # weakref-bound — the service's hook list outlives any one
        # table and must not pin it (same rule as the flusher thread)
        # memory ledger (telemetry/memstats.py): pending window payloads
        # + the replay retention tail — the PR-7 hoard that grows
        # silently when no failover checkpointer advances the durable
        # floor. Registration only; gauges are pull-time.
        _memstats.register(f"window[{table.name}]", self)
        self._replay: Optional[_ReplayBuffer] = None
        if config.get_flag("ps_replay"):
            self._replay = _ReplayBuffer(table)
            wref = weakref.ref(self)

            def _death(rank: int, _w=wref) -> None:
                w = _w()
                if w is not None:
                    w._on_owner_death(rank)

            table.ctx.service.add_death_hook(_death)

    # ------------------------------------------------------------------ #
    def submit(self, parts: List[Tuple[int, np.ndarray, np.ndarray]],
               opt: AddOption,
               trace: Optional[int] = None,
               tenant: Optional[str] = None) -> List[cf.Future]:
        """Queue ONE logical add's per-owner pieces; returns one
        placeholder future per owner (completed by the window ack).
        ``trace`` is the logical op's trace ID (telemetry/trace.py) —
        it rides every per-owner entry into the frame meta, as does the
        resolved ``tenant`` (wire.TENANT_META_KEY; None = default)."""
        self._mon_windowed.incr()
        if tenant is not None:
            self._note_tenant_budget(tenant)
        return [self._enqueue(r, ids, vals, opt, trace, tenant)
                for r, ids, vals in parts]

    def _note_tenant_budget(self, tn: str) -> None:
        """Per-(table, tenant) add budget (flag ``tenant_add_qps``):
        train writes are NEVER dropped — an over-budget windowed add is
        COUNTED as deferred in the tenant ledger (the noisy-neighbor
        sweep's write-plane degradation evidence) and still ships."""
        qps = config.get_flag("tenant_add_qps")
        if qps <= 0:
            return
        b = self._tenant_buckets.get(tn)
        if b is None or b.rate != qps:
            from multiverso_tpu.serving.admission import TokenBucket
            b = self._tenant_buckets[tn] = TokenBucket(qps)
        if not b.try_acquire(1.0):
            _tenants.LEDGER.note_deferred(self._table_name, tn)

    def _enqueue(self, owner: int, ids: np.ndarray, vals: np.ndarray,
                 opt: AddOption, trace: Optional[int] = None,
                 tn: Optional[str] = None) -> cf.Future:
        fut: cf.Future = cf.Future()
        ship = False
        # black box: the enqueue edge (flightrec is always on; one ring
        # write ~1 us against the ~30-60 us windowed-add budget)
        _flight.record(_flight.EV_WIN_ENQ, peer=owner,
                       nbytes=ids.nbytes + vals.nbytes)
        with self._cv:
            q = self._pending.setdefault(owner, [])
            q.append((ids, vals, opt, fut, trace, tn))
            self._nbytes[owner] = (self._nbytes.get(owner, 0)
                                   + ids.nbytes + vals.nbytes)
            if (len(q) >= self.max_ops
                    or self._nbytes[owner] >= self.max_bytes):
                ship = True   # bound hit: ship now, on this thread
            elif self._deadline is None:
                # arm the window and wake the flusher ONLY then — a
                # notify per enqueue would cost a thread wakeup (~70 us)
                # on every small add for nothing: the flusher's existing
                # wait already covers an armed deadline
                self._deadline = time.monotonic() + self.window_s
                self._ensure_flusher_locked()
                self._cv.notify()
        if ship:
            self._flush_owner(owner)
        return fut

    def flush_pending(self) -> None:
        """Send every queued add NOW — the ordering fence gets / flush /
        overwrites run before dispatching their own frames. On return,
        every entry queued BEFORE the call is on its conn. The sweep
        covers every owner ever sent to, not just those currently
        pending: a concurrent flusher may have POPPED an owner's queue
        but not yet reached the socket, and the fence must wait that
        send out (acquiring the owner's send lock does exactly that) —
        skipping absent owners would let the caller's next frame
        overtake the popped batch. Uncontended, a spare owner costs one
        lock acquire (~100 ns)."""
        with self._cv:
            owners = set(self._pending) | set(self._send_locks)
            self._deadline = None
        self._flush_owners(owners)

    def memory_stats(self) -> Dict[str, Any]:
        """Byte-ledger gauges (telemetry/memstats.py, pull-only): queued
        window payloads awaiting flush, and the replay plane's retained
        frames — per owner and total, with how many are ARMED for
        re-send (armed > 0 means the owner is dead/being failed over,
        which the retention-leak verdict treats as failover working,
        not hoarding). Bytes are the frames' actual wire blobs."""
        with self._cv:
            pending_ops = sum(len(q) for q in self._pending.values())
            pending_bytes = sum(self._nbytes.values())
        out: Dict[str, Any] = {
            "pending_ops": int(pending_ops),
            "pending_bytes": int(pending_bytes),
            "retained_frames": 0, "retained_bytes": 0,
            "armed_frames": 0,
        }
        rp = self._replay
        if rp is None:
            return out
        def _nb(a) -> int:
            # lazy fallback: frame blobs are ndarrays (nbytes); a raw
            # bytes blob falls back to len only when nbytes is absent
            nb = getattr(a, "nbytes", None)
            return int(nb) if nb is not None else len(a)

        owners: Dict[str, Dict[str, int]] = {}
        with rp.lock:
            for owner, q in rp.retained.items():
                fb = sum(sum(_nb(a) for a in fr.arrays)
                         for fr in q.values())
                # armed PER OWNER: the retention-leak verdict judges
                # each owner separately — one dead owner's re-armed
                # tail (failover working) must not mask another LIVE
                # owner's unpruned hoard
                owners[str(owner)] = {
                    "retained_frames": len(q),
                    "retained_bytes": int(fb),
                    "armed_frames": max(
                        int(rp.pending_send.get(owner, 0)), 0)}
                out["retained_frames"] += len(q)
                out["retained_bytes"] += int(fb)
            out["armed_frames"] = sum(max(int(n), 0)
                                      for n in rp.pending_send.values())
        if owners:
            out["owners"] = owners
        return out

    # idle condvar waits are bounded so the flusher can notice its window
    # died (see _window_loop's weakref) instead of pinning it forever
    _IDLE_WAIT_S = 5.0

    def _ensure_flusher_locked(self) -> None:
        """Start (or restart) the flusher thread; caller holds
        ``self._cv``. Shared by the enqueue path and the replay plane —
        a replay-armed window with no fresh enqueues still needs the
        thread alive to drive retries."""
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=_window_loop, args=(weakref.ref(self),),
                daemon=True, name=f"ps-window-{self._table_name}")
            self._thread.start()

    def _step(self) -> bool:
        """One flusher cycle: wait out the open window (or idle,
        bounded), then ship everything pending; with replay armed, the
        wait also bounds to the soonest replay deadline and every cycle
        drives due replays. Returns False only on a spurious/idle
        wakeup with nothing to do."""
        owners: List[int] = []
        rp = self._replay
        with self._cv:
            bound = self._IDLE_WAIT_S
            now = time.monotonic()
            nd = rp.soonest_due() if rp is not None else None
            if nd is not None:
                bound = min(bound, max(nd - now, 0.005))
            if self._deadline is not None:
                delay = self._deadline - now
                if delay <= 0:
                    self._deadline = None
                    owners = list(self._pending)
                else:
                    bound = min(bound, delay)
            if not owners and not (nd is not None and nd <= now):
                self._cv.wait(bound)
        # _replay_step runs OUTSIDE the cv hold: it takes owner send
        # locks (and its sends can block on a dead owner's sockets),
        # while senders holding those locks block on the cv to queue
        # armed frames — calling it under the cv would be an ABBA
        # deadlock of the table during exactly the failover it serves
        self._flush_owners(owners)
        return self._replay_step() or bool(owners)

    # ------------------------------------------------------------------ #
    def _send_lock(self, owner: int) -> threading.Lock:
        with self._cv:
            lock = self._send_locks.get(owner)
            if lock is None:
                lock = self._send_locks[owner] = threading.Lock()
            return lock

    # shared flush pool for concurrent multi-owner sweeps (class-level,
    # like the drain-handoff pool: windows are many, the pool is one;
    # per-owner flushes never block on anything but the owner's send
    # lock and its socket, so owners never deadlock across threads)
    _flush_pool: Optional[Any] = None
    _flush_pool_lock = threading.Lock()

    @classmethod
    def _flush_executor(cls):
        with cls._flush_pool_lock:
            if cls._flush_pool is None:
                cls._flush_pool = cf.ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="ps-flush")
            return cls._flush_pool

    def _flush_owners(self, owners) -> None:
        """One multi-owner flush sweep. Colocated owners' frames (flag
        ps_fanout, replay off) coalesce into ONE multi-owner super-frame
        — one dispatch per destination process instead of one per shard
        — and the remaining owners flush CONCURRENTLY on the shared
        pool instead of serializing their socket sends; the sweep still
        returns only when every owner's batch is on its conn (the fence
        contract)."""
        owners = sorted(owners)
        if not owners:
            return
        t = self._table_ref()
        routed: List[int] = []
        if (t is not None and self._replay is None
                and getattr(t, "_fanout", False) and len(owners) > 1):
            routed = [o for o in owners
                      if o == t.ctx.rank
                      or o in getattr(t, "_routed_set", ())]
            if len(routed) < 2:
                routed = []
        rest = [o for o in owners if o not in routed]
        if routed:
            self._flush_coalesced(t, routed)
        if len(rest) > 1:
            pool = self._flush_executor()
            futs = [pool.submit(self._flush_owner, o) for o in rest]
            cf.wait(futs)
            # propagate the first failure AFTER every owner flushed —
            # the serial loop surfaced flush exceptions (encode/packing
            # re-raises), and swallowing one here would stall the
            # popped entries' waiters to the full timeout instead
            for f in futs:
                f.result()
        elif rest:
            self._flush_owner(rest[0])

    def _flush_coalesced(self, t, owners: List[int]) -> None:
        """Pop + merge every routed owner's queue under ALL their send
        locks (sorted — deterministic, so concurrent sweeps cannot
        deadlock) and ship the collected frames as ONE multi-owner
        super-frame; the packed inner replies fan back out to each
        frame's window futures. Locks are held until the super-frame is
        dispatched, so a later frame to any of these owners cannot
        overtake the batch (the same ordering the per-owner send lock
        buys the classic path)."""
        collected: List[Tuple] = []
        with contextlib.ExitStack() as st:
            for o in owners:
                st.enter_context(self._send_lock(o))
            for o in owners:
                with self._cv:
                    entries = self._pending.pop(o, None)
                    self._nbytes.pop(o, None)
                if entries:
                    self._send(o, entries, collect=collected.append)
            if not collected:
                return
            subs = []
            frames = []
            for owner, msg_type, meta, arrays, gfuts in collected:
                meta = dict(meta)
                meta[wire_mod.OWNER_META_KEY] = owner
                subs.append((msg_type, meta, arrays))
                frames.append((owner, gfuts))
            pfuts = t.ctx.service.multi_local(subs)
        for (owner, gfuts), pf in zip(frames, pfuts):
            pf.add_done_callback(
                lambda bf, gf=gfuts, o=owner:
                    _complete_window_futures(bf, gf, owner=o))

    def _flush_owner(self, owner: int) -> None:
        """Merge + ship one owner's queue as one frame. The send lock is
        taken BEFORE popping: concurrent senders to the same owner
        serialize pop-and-send as a unit, so frames leave in enqueue
        order and a fence returning means the batch is on the conn. The
        window lock is only pinched for the pop — enqueues stay
        wait-free while the socket send runs."""
        with self._send_lock(owner):
            with self._cv:
                entries = self._pending.pop(owner, None)
                self._nbytes.pop(owner, None)
            if entries:
                self._send(owner, entries)

    def _send(self, owner: int, entries: List[Tuple],
              collect=None) -> None:
        """``collect`` (coalesced multi-owner sweep): instead of
        dispatching each wire frame, hand ``(owner, msg_type, meta,
        arrays, gfuts)`` to the collector — the sweep ships every
        owner's frames as ONE super-frame and fans the inner replies
        back to ``gfuts``. Never used with replay armed (stamped frames
        keep their per-owner retained dispatch)."""
        t = self._table_ref()
        if t is None:
            # table died with queued adds (caller dropped it without a
            # flush): nobody can await these futures, but fail them
            # anyway so any stray holder sees a typed error, not a hang
            err = svc.PSError(
                f"table[{self._table_name}] was garbage-collected with "
                "windowed adds still queued")
            for _, _, _, fut, _, _ in entries:
                if not fut.done():
                    fut.set_exception(err)
            return
        traced = ttrace.enabled()
        t_flush0 = time.time() if traced else 0.0
        # flush edge: per-flush (not per-add), so the f-string note is
        # off the hot path
        _flight.record(_flight.EV_WIN_FLUSH, peer=owner,
                       nbytes=sum(e[0].nbytes + e[1].nbytes
                                  for e in entries),
                       note=f"ops={len(entries)}")
        w = t._wire_for(owner)
        # merging conditions, ALL required for bit-transparency: an
        # elementwise wire ("none"/"bf16" — 1bit/topk mix values across
        # block/top-k structure, so each op keeps its own codec payload),
        # disjoint row sets, a row-local-state updater (adam's global
        # step counter advances once per APPLY — a merge would miscount),
        # and matching AddOptions (unless the updater never reads them)
        exact = (w in ("none", "bf16")
                 and type(t.updater) in updaters_lib.ROW_LOCAL_STATE)
        merge_all = type(t.updater) in updaters_lib.OPT_INSENSITIVE
        groups: List[List] = []   # [ids[], vals[], opt, futs[], idset,
        merged_rows = 0           #  traces[], tenant]
        for ids, vals, opt, fut, tid, tn in entries:
            g = groups[-1] if groups else None
            # tenants never blur: a merged sub-op is one attribution
            # record at the shard, so only same-tenant entries merge
            if (g is not None and exact
                    and (merge_all or opt == g[2])
                    and tn == g[6]
                    and not g[4].intersection(ids.tolist())):
                g[0].append(ids)
                g[1].append(vals)
                g[3].append(fut)
                g[4].update(ids.tolist())
                if tid is not None:
                    g[5].append(tid)
                merged_rows += int(ids.size)
            else:
                groups.append([[ids], [vals], opt, [fut],
                               set(ids.tolist()),
                               [] if tid is None else [tid], tn])
        try:
            packed = [(np.concatenate(g[0]) if len(g[0]) > 1 else g[0][0],
                       np.concatenate(g[1]) if len(g[1]) > 1 else g[1][0],
                       g[2], g[5], g[6]) for g in groups]
        except Exception as e:   # merge failure must not orphan waiters
            # close the flush edge too: an unmatched win.flush in a dump
            # is the wedged-window signature, and this window failed
            # FAST, not wedged
            _flight.record(_flight.EV_WIN_FLUSH_END, peer=owner,
                           note=f"merge failed: {e}"[:120])
            for g in groups:
                for f in g[3]:
                    if not f.done():
                        f.set_exception(e)
            return

        def sub_meta(opt, tids, tn):
            """Per-sub-op meta: the cached packed bytes normally; a dict
            carrying the trace ID (wire.TRACE_META_KEY) and/or tenant
            (wire.TENANT_META_KEY) when stamped — a merged group's
            FIRST ID names the sub-op, the full set rides the client
            flush/ack spans."""
            if not tids and tn is None:
                return t._add_meta_b(opt, w)
            meta = {"table": t.name, "opt": opt._asdict()}
            if w != "none":
                meta["wire"] = w
            if tids:
                meta[wire_mod.TRACE_META_KEY] = tids[0]
            if tn is not None:
                meta[wire_mod.TENANT_META_KEY] = tn
            return meta

        all_tids = [tid for g in groups for tid in g[5]]
        # a window can outgrow one frame (knob raced/misconfigured past
        # the wire bound): ship in MAX_BATCH_OPS chunks, in order on the
        # same conn — never fail the whole window over frame capacity
        for i0 in range(0, len(packed), wire_mod.MAX_BATCH_OPS):
            chunk = packed[i0:i0 + wire_mod.MAX_BATCH_OPS]
            gfuts = [g[3] for g in groups[i0:i0 + wire_mod.MAX_BATCH_OPS]]
            futs = [f for fs in gfuts for f in fs]
            try:
                if len(chunk) == 1:
                    ids, vals, opt, tids, tn = chunk[0]
                    meta = {"table": t.name, "opt": opt._asdict()}
                    if w != "none":
                        meta["wire"] = w
                    if tids:
                        meta[wire_mod.TRACE_META_KEY] = tids[0]
                    if tn is not None:
                        meta[wire_mod.TENANT_META_KEY] = tn
                    msg_type = svc.MSG_ADD_ROWS
                    frame_arrays = [ids] + wire_mod.encode_payload(vals, w)
                    meta_b = (None if tids or tn is not None
                              or self._replay is not None
                              else t._add_meta_b(opt, w))
                else:
                    blobs = [wire_mod.encode(
                        svc.MSG_ADD_ROWS, i, sub_meta(opt, tids, tn),
                        [ids] + wire_mod.encode_payload(vals, w))
                        for i, (ids, vals, opt, tids, tn) in
                        enumerate(chunk)]
                    msg_type = svc.MSG_BATCH
                    meta = {"table": t.name, "n": len(chunk)}
                    frame_arrays = wire_mod.pack_batch(blobs)
                    meta_b = None
            except Exception as e:   # encode failure must not orphan waiters
                for f in futs:
                    if not f.done():
                        f.set_exception(e)
                continue
            self._mon_flushes.incr()
            if self._replay is not None:
                # stamped + retained dispatch: the ack callback,
                # retention pruning, and peer-death replay all live in
                # _frame_done (trace ack spans stay off this path — a
                # replayed frame's span would stitch to a long-dead
                # request)
                self._dispatch_retained(t, owner, msg_type, meta,
                                        frame_arrays, gfuts)
                continue
            if collect is not None:
                # coalesced sweep: the caller ships this frame inside
                # one multi-owner super-frame (trace ack spans stay off
                # this path like the replay one — the fan-out future is
                # not the wire request)
                collect((owner, msg_type, meta, frame_arrays, gfuts))
                continue
            req = t.ctx.service.request(owner, msg_type, meta,
                                        frame_arrays, meta_b=meta_b)
            if traced and all_tids:
                # ack span: frame on the wire -> window ack fanned out
                # (runs on the peer's recv thread)
                t_send = time.time()
                chunk_tids = [tid for (_, _, _, tids, _) in chunk
                              for tid in tids]

                def _done(bf, gf=gfuts, ts=t_send, ct=chunk_tids):
                    _complete_window_futures(bf, gf, owner=owner)
                    ttrace.add_span(
                        "window.ack", ts, time.time(),
                        trace=ct[0] if ct else None,
                        args={"owner": owner, "traces": ct})

                req.add_done_callback(_done)
            else:
                req.add_done_callback(
                    lambda bf, gf=gfuts:
                        _complete_window_futures(bf, gf, owner=owner))
        _flight.record(_flight.EV_WIN_FLUSH_END, peer=owner,
                       note=f"frames={-(-len(packed) // wire_mod.MAX_BATCH_OPS)}")
        if merged_rows:
            self._mon_merged.incr(merged_rows)
        if traced and all_tids:
            nframes = -(-len(packed) // wire_mod.MAX_BATCH_OPS)  # ceil:
            ttrace.add_span(                 # wire frames, not sub-ops
                "window.flush", t_flush0, time.time(),
                trace=all_tids[0],
                args={"owner": owner, "ops": len(entries),
                      "frames": nframes, "traces": all_tids})

    # ------------------------------------------------------------------ #
    # exactly-once replay plane (flag ps_replay; docs/FAILOVER.md)
    # ------------------------------------------------------------------ #
    def _dispatch_retained(self, t, owner: int, msg_type: int,
                           meta: Dict, arrays, gfuts) -> None:
        """Stamp one window frame with (client, per-owner seq), retain
        it, and put it on the wire — unless earlier frames to this
        owner are awaiting replay, in which case it queues behind them
        (seq order IS wire order; a new frame overtaking a replayed one
        could commit a later sequence first and the shard would then
        treat the late arrival as the duplicate)."""
        rp = self._replay
        with rp.lock:
            seq = rp.next_seq.get(owner, 0)
            rp.next_seq[owner] = seq + 1
            meta = dict(meta)
            meta[wire_mod.REPLAY_CLIENT_KEY] = rp.client_id
            meta[wire_mod.REPLAY_SEQ_KEY] = seq
            fr = _RetainedFrame(owner, seq, msg_type, meta, arrays, gfuts)
            q = rp.retained.setdefault(owner, collections.OrderedDict())
            q[seq] = fr
            blocked = rp.pending_send.get(owner, 0) > 0
            if blocked:
                fr.needs_send = True
                rp.pending_send[owner] += 1
        if blocked:
            with self._cv:
                self._ensure_flusher_locked()
                self._cv.notify()
            return
        self._send_frame(t, fr)

    def _send_frame(self, t, fr: _RetainedFrame) -> None:
        fr.attempts += 1
        try:
            req = t.ctx.service.request(fr.owner, fr.msg_type, fr.meta,
                                        fr.arrays)
        except Exception as e:   # defensive: request() never raises
            req = _failed_future(e)
        req.add_done_callback(lambda bf, fr=fr: self._frame_done(bf, fr))

    def _frame_done(self, bf: cf.Future, fr: _RetainedFrame) -> None:
        """Outcome of one retained frame's latest wire attempt (peer
        recv thread, or inline for a failed-fast dispatch). A peer-
        unreachable failure inside the replay window does NOT fail the
        waiters — the frame re-arms and they complete when it finally
        lands on a (possibly restored) incarnation; anything else — a
        shard-side error, or the replay window exhausted — completes
        them with the error exactly like the unreplayed path."""
        rp = self._replay
        exc: Optional[BaseException] = None
        meta: Dict = {}
        try:
            exc = bf.exception()
            if exc is None:
                res = bf.result()
                if isinstance(res, tuple) and isinstance(res[0], dict):
                    meta = res[0]
        except (cf.CancelledError, Exception) as e:   # defensive
            exc = e
        if isinstance(exc, svc.PSPeerError):
            now = time.monotonic()
            if fr.retry_since is None:
                fr.retry_since = now
                fr.episode_attempts = 0
            if (now - fr.retry_since
                    <= config.get_flag("ps_replay_timeout")):
                with rp.lock:
                    if not fr.needs_send:
                        fr.needs_send = True
                        rp.pending_send[fr.owner] = (
                            rp.pending_send.get(fr.owner, 0) + 1)
                    # shared capped-exponential policy with deadline
                    # propagation: the delay never schedules past the
                    # episode's ps_replay_timeout budget
                    due = now + _replay_backoff().delay_s(
                        fr.episode_attempts,
                        deadline=fr.retry_since
                        + config.get_flag("ps_replay_timeout"))
                    fr.episode_attempts += 1
                    cur = rp.next_due.get(fr.owner)
                    if cur is None or due < cur:
                        rp.next_due[fr.owner] = due
                with self._cv:
                    self._ensure_flusher_locked()
                    self._cv.notify()
                return
        if meta.get(wire_mod.REPLAY_DUP_KEY):
            rp.mon_dups.incr()
        with rp.lock:
            q = rp.retained.get(fr.owner)
            if exc is None:
                fr.acked = True
                fr.retry_since = None
                fr.episode_attempts = 0
                if q is not None:
                    self._prune_owner_locked(
                        fr.owner,
                        int(meta.get(wire_mod.REPLAY_DURABLE_KEY, -1)))
            elif q is not None:
                # permanently failed (shard error / replay window
                # exhausted): nothing left to replay — drop the frame,
                # keeping the armed-frame invariant (pending_send ==
                # count of needs_send frames; a stale positive count
                # would block every later frame to this owner forever)
                if fr.needs_send:
                    fr.needs_send = False
                    rp.pending_send[fr.owner] = max(
                        rp.pending_send.get(fr.owner, 0) - 1, 0)
                dropped_acked = all(f.done()
                                    for fs in fr.gfuts for f in fs)
                q.pop(fr.seq, None)
                if dropped_acked:
                    # the waiters already saw success: this IS a lost
                    # acked op — the one outcome replay exists to
                    # prevent — and it must be loud, not silent
                    log.error(
                        "table[%s]: replay of frame seq %d to owner %d "
                        "exhausted its window (%s); an ACKED op may be "
                        "lost", self._table_name, fr.seq, fr.owner, exc)
        _complete_window_futures(bf, fr.gfuts, owner=fr.owner)

    def _prune_owner_locked(self, owner: int, durable: int) -> None:
        """Drop retained frames the shard has made durable (caller
        holds ``rp.lock``), then enforce the retention cap: past it the
        oldest ACKED frames drop with a warning — durability degrades
        to ack-time instead of memory growing without bound when no
        checkpointer is advancing the durable floor."""
        rp = self._replay

        def _remove(seq: int) -> None:
            # keep the armed-frame invariant (pending_send == count of
            # needs_send frames) on EVERY removal path: a frame can be
            # re-armed by an owner death while its (old-incarnation)
            # success ack is in flight, and pruning it without the
            # decrement would leave the owner "blocked" forever
            fr = q.pop(seq, None)
            if fr is not None and fr.needs_send:
                fr.needs_send = False
                rp.pending_send[owner] = max(
                    rp.pending_send.get(owner, 0) - 1, 0)

        q = rp.retained.get(owner)
        if not q:
            return
        for seq in [s for s, f in q.items()
                    if f.acked and s <= durable]:
            _remove(seq)
        cap = config.get_flag("ps_replay_max_frames")
        if len(q) > cap:
            drop = [s for s, f in q.items() if f.acked][: len(q) - cap]
            if drop:
                rp.mon_dropped.incr(len(drop))
                log.error(
                    "table[%s]: replay retention cap (%d) dropped %d "
                    "acked frames for owner %d — they are durable only "
                    "to ack-time (is the failover checkpointer "
                    "running?)", self._table_name, cap, len(drop), owner)
                for s in drop:
                    _remove(s)

    def _on_owner_death(self, rank: int) -> None:
        """Peer-death hook: the owner may come back restored from a
        checkpoint missing the tail of what it acked — re-arm EVERY
        retained frame (acked ones too) for re-flush in seq order; the
        restored incarnation's sequence channels ack the prefix its
        checkpoint already holds as duplicates and apply only the
        genuinely lost tail."""
        rp = self._replay
        if rp is None:
            return
        now = time.monotonic()
        with rp.lock:
            q = rp.retained.get(rank)
            if not q:
                return
            armed = 0
            for fr in q.values():
                fr.acked = False
                if fr.retry_since is None:
                    fr.retry_since = now
                    fr.episode_attempts = 0
                if not fr.needs_send:
                    fr.needs_send = True
                    armed += 1
            if armed:
                rp.pending_send[rank] = (rp.pending_send.get(rank, 0)
                                         + armed)
            # episode start: the FIRST re-flush is quick (attempt 0 of
            # the shared policy); subsequent failures grow the delay
            # per frame in _frame_done
            rp.next_due[rank] = (time.monotonic()
                                 + _replay_backoff().delay_s(0))
            n = len(q)
        _flight.record(_flight.EV_FAILOVER_REPLAY, peer=rank,
                       note=f"owner died: {n} frames re-armed")
        with self._cv:
            self._ensure_flusher_locked()
            self._cv.notify()

    def _replay_step(self) -> bool:
        """Flusher-cycle half of the replay plane: re-flush every owner
        whose retry deadline passed."""
        rp = self._replay
        if rp is None:
            return False
        now = time.monotonic()
        with rp.lock:
            due = [o for o, t0 in rp.next_due.items() if now >= t0]
        did = False
        for owner in due:
            did = self._replay_owner(owner) or did
        return did

    def _replay_owner(self, owner: int) -> bool:
        """Re-flush one owner's armed frames, oldest first, under the
        owner's SEND lock (fresh flushes queue behind, so the conn sees
        strict seq order). Frames that fail again re-arm themselves via
        their _frame_done; frames landing on a restored incarnation
        dedupe server-side."""
        rp = self._replay
        t = self._table_ref()
        with self._send_lock(owner):
            with rp.lock:
                rp.next_due.pop(owner, None)
                q = rp.retained.get(owner)
                frames = ([f for f in q.values() if f.needs_send]
                          if q else [])
                for f in frames:
                    f.needs_send = False
                if frames:
                    rp.pending_send[owner] = max(
                        rp.pending_send.get(owner, 0) - len(frames), 0)
            if not frames:
                return False
            if t is None:
                err = svc.PSError(
                    f"table[{self._table_name}] was garbage-collected "
                    "with frames awaiting replay")
                with rp.lock:
                    for f in frames:
                        if q is not None:
                            q.pop(f.seq, None)
                for f in frames:
                    for fut in (x for fs in f.gfuts for x in fs):
                        if not fut.done():
                            fut.set_exception(err)
                return True
            rp.mon_replayed.incr(len(frames))
            _flight.record(_flight.EV_FAILOVER_REPLAY, peer=owner,
                           note=f"re-flush {len(frames)} frames")
            for fr in frames:
                self._send_frame(t, fr)
        return True


def _chunk_scatter(buf: np.ndarray, idx: Optional[np.ndarray],
                   ncol: int, dtype):
    """Sink for a chunk-streamed get reply (service.request chunk_sink):
    decode each sub-frame as it lands on the peer's recv thread and
    scatter it straight into ``buf`` — at ``idx[row0:row0+rows]``
    positions when the part is a row subset, contiguously at
    ``[row0:row0+rows]`` when it is a whole range. This is the overlap
    the chunking exists for: chunk k decodes + scatters while chunk
    k+1's bytes are still in flight."""
    def sink(cmeta, arrays):
        a, k = int(cmeta["row0"]), int(cmeta["rows"])
        rows = wire_mod.decode_payload(arrays, cmeta.get("wire", "none"),
                                       (k, ncol), dtype)
        if idx is None:
            buf[a:a + k] = rows
        else:
            buf[idx[a:a + k]] = rows
    return sink


class _GetWindow:
    """Client-side get coalescer (the read-path mirror of
    :class:`_SendWindow`), one per windowed table: concurrent
    ``get_rows_async`` calls dedupe overlapping row ids per owner into
    single-flight batched fetches.

    Shape: a get to an owner with NO outstanding fetch dispatches
    IMMEDIATELY — serial gets pay nothing for the window. Gets arriving
    while that owner's fetch is on the wire queue here; their ids dedupe
    into ONE follow-up frame dispatched the moment the outstanding reply
    lands, or when the oldest queued entry ages past ``get_window_ms``
    (the starvation bound: a 1-row get must not wait out a long chunked
    fetch). Each waiter's future resolves to ITS OWN row block sliced
    from the batch reply, so N concurrent pullers cost one frame, one
    shard serve, and one reply instead of N.

    Read-your-writes: every caller fences its SEND window before
    reaching :meth:`fetch`, and a batch's frame reaches the conn only
    AFTER the join — per-owner conn FIFO then orders the fetch behind
    the caller's adds. Joining an already-dispatched fetch is impossible
    by construction (dispatch atomically consumes the queue)."""

    _IDLE_WAIT_S = 5.0

    def __init__(self, table, window_ms: float):
        self._table_ref = weakref.ref(table)
        self._table_name = table.name
        self.window_s = float(window_ms) / 1e3
        self._cv = threading.Condition()
        # owner -> [(unique ids, waiter future)], join order
        self._queued: Dict[int, List[Tuple[np.ndarray, cf.Future]]] = {}
        self._q_t0: Dict[int, float] = {}
        self._inflight: Dict[int, int] = {}
        # batches due NOW (a completed fetch released them): dispatched
        # by the flusher thread, never on the peer's recv thread — a
        # send from the recv callback could head-of-line-block (or, with
        # both TCP buffers full, deadlock) the very reply plane that
        # completes fetches
        self._ready: List[Tuple[int, List[Tuple]]] = []
        self._thread: Optional[threading.Thread] = None
        base = f"table[{table.name}].get_rows"
        self._mon_windowed = Dashboard.get(base + ".windowed")
        self._mon_fetches = Dashboard.get(base + ".fetches")
        self._mon_merged = Dashboard.get(base + ".merged_rows")

    def fetch(self, owner: int, ids: np.ndarray) -> cf.Future:
        """One caller's rows from ``owner`` (``ids`` unique, caller
        order — the ``_prep`` contract); resolves to the
        (len(ids), num_col) host block in that order."""
        fut: cf.Future = cf.Future()
        self._mon_windowed.incr()
        with self._cv:
            if self._inflight.get(owner, 0) > 0:
                q = self._queued.setdefault(owner, [])
                if not q:
                    self._q_t0[owner] = time.monotonic()
                q.append((ids, fut))
                self._ensure_thread_locked()
                self._cv.notify()
                return fut
            self._inflight[owner] = self._inflight.get(owner, 0) + 1
        self._dispatch(owner, [(ids, fut)])
        return fut

    def _ensure_thread_locked(self) -> None:
        """Start the flusher thread (caller holds ``self._cv``) — the
        shared :func:`_window_loop` body over a weakref, here both aging
        queued batches and dispatching released ones."""
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=_window_loop, args=(weakref.ref(self),),
                daemon=True, name=f"ps-getwin-{self._table_name}")
            self._thread.start()

    def _step(self) -> bool:
        """One flusher cycle (the :func:`_window_loop` body): dispatch
        batches released by a completed fetch, plus queued batches whose
        oldest entry aged past the window."""
        with self._cv:
            batches, self._ready = self._ready, []
            if not batches and not self._q_t0:
                self._cv.wait(self._IDLE_WAIT_S)
                return False
            now = time.monotonic()
            due = [o for o, t0 in self._q_t0.items()
                   if now - t0 >= self.window_s]
            if not due and not batches:
                soonest = min(self._q_t0.values()) + self.window_s - now
                self._cv.wait(min(max(soonest, 0.001),
                                  self._IDLE_WAIT_S))
                return False
            for o in due:
                q = self._queued.pop(o, None)
                self._q_t0.pop(o, None)
                if q:
                    self._inflight[o] = self._inflight.get(o, 0) + 1
                    batches.append((o, q))
        for o, q in batches:
            self._dispatch(o, q)
        return True

    def _release(self, owner: int) -> None:
        """A fetch completed: drop its flight and hand whatever queued
        behind it to the FLUSHER as the next single-flight batch. Never
        dispatches here: _release runs on the peer's recv thread (the
        reply callback), and a socket send from there could block the
        reply plane behind its own follow-up frame."""
        with self._cv:
            self._inflight[owner] = max(
                self._inflight.get(owner, 1) - 1, 0)
            if self._inflight[owner] == 0:
                q = self._queued.pop(owner, None)
                self._q_t0.pop(owner, None)
                if q:
                    self._inflight[owner] = 1
                    self._ready.append((owner, q))
                    self._ensure_thread_locked()
                    self._cv.notify()

    def _dispatch(self, owner: int, entries: List[Tuple]) -> None:
        try:
            self._dispatch_inner(owner, entries)
        except Exception as e:   # noqa: BLE001 — waiters must never hang
            for _, fut in entries:
                if not fut.done():
                    fut.set_exception(e)
            self._release(owner)

    def _dispatch_inner(self, owner: int, entries: List[Tuple]) -> None:
        t = self._table_ref()
        if t is None:
            raise svc.PSError(
                f"table[{self._table_name}] was garbage-collected with "
                "coalesced gets still queued")
        if len(entries) == 1:
            # single-flight of one: ship the caller's ids as-is (caller
            # order — _prep's no-dup path does NOT sort) and hand the
            # reply block straight back
            uids = entries[0][0]
        else:
            # merged batch: a SORTED unique union, so each waiter's
            # (arbitrary-order) ids resolve by searchsorted below
            cat = np.concatenate([ids for ids, _ in entries])
            uids = np.unique(cat)
            self._mon_merged.incr(int(cat.size - uids.size))
        gw = t._get_wire_for(owner)
        chunk = int(config.get_flag("get_chunk_rows"))
        buf = np.empty((uids.size, t.num_col), t.dtype)
        meta: Dict = {"table": t.name}
        if gw != "none":
            meta["wire"] = gw
        sink = None
        if chunk > 0 and uids.size > chunk and owner != t.ctx.rank:
            meta["chunk"] = chunk
            sink = _chunk_scatter(buf, None, t.num_col, t.dtype)
        _flight.record(_flight.EV_GET_WIN, peer=owner,
                       note=f"ops={len(entries)}")
        self._mon_fetches.incr()
        req = t.ctx.service.request(owner, svc.MSG_GET_ROWS, meta,
                                    [uids], chunk_sink=sink)
        chunked = sink is not None

        def _done(bf, entries=entries, uids=uids, buf=buf, gw=gw,
                  owner=owner, chunked=chunked, ncol=t.num_col,
                  dt=t.dtype):
            exc: Optional[BaseException] = None
            try:
                exc = bf.exception()
                if exc is None:
                    rmeta, arrays = bf.result()
                    if not (chunked and rmeta.get("chunks")):
                        buf[:] = wire_mod.decode_payload(
                            arrays, gw, (uids.size, ncol), dt)
            except (cf.CancelledError, Exception) as e:   # defensive
                exc = e
            try:
                for ids, fut in entries:
                    if fut.done():
                        continue
                    if exc is not None:
                        fut.set_exception(exc)
                    elif len(entries) == 1:
                        fut.set_result(buf)   # reply IS this block
                    else:
                        # uids is sorted-unique here; fancy-index copy
                        # gives each waiter its block in ITS id order
                        fut.set_result(buf[np.searchsorted(uids, ids)])
            finally:
                # ALWAYS drop the flight: a slicing bug above must fail
                # this batch, not wedge every later get behind a flight
                # count that never returns to zero
                self._release(owner)

        req.add_done_callback(_done)


def _part_len(ix) -> int:
    """Row count of an ``_owner_slices`` indexer (slice or positions)."""
    return ix.stop - ix.start if isinstance(ix, slice) else ix.size


def _part_index(ix) -> np.ndarray:
    """An ``_owner_slices`` indexer as explicit positions (the chunk
    sinks scatter by position array)."""
    return (np.arange(ix.start, ix.stop) if isinstance(ix, slice)
            else ix)


def _owned_part(arr: np.ndarray, ix) -> np.ndarray:
    """``arr[ix]`` as OWNED bytes (deferred in-process dispatch reads
    the part later): fancy indexing already copies, a slice view gets
    an explicit copy."""
    part = arr[ix]
    return part.copy() if isinstance(ix, slice) else part


def _maybe_register_in_zoo(table) -> Optional[int]:
    """Async tables join the Zoo registry (checkpoint walk, C ABI) when the
    runtime is up; standalone PSContext tests run without a Zoo."""
    from multiverso_tpu.zoo import Zoo
    zoo = Zoo.get()
    if zoo.started:
        return zoo.register_table(table)
    return None


class _AsyncBase:
    """msg-id -> futures bookkeeping shared by the async tables."""

    # store() is plain RPC to the owners, not a collective: checkpoint.save
    # runs it on rank 0 only (sync tables' sharded-state fetch is collective,
    # so THEY must run store() on every rank)
    collective_store = False

    def __init__(self, ctx: Optional[svc.PSContext], name: str):
        self.ctx = ctx if ctx is not None else svc.default_context()
        self.name = name
        self._pending: Dict[int, Tuple[List[cf.Future], Any]] = {}
        self._next_msg_id = 0
        self._lock = threading.Lock()
        self._meta_cache: Dict[Any, bytes] = {}
        # client send window (flag batch_window_ms / per-table override);
        # None = every add ships immediately (the default)
        self._window: Optional[_SendWindow] = None
        # failures of already-swept fire-and-forget ops, kept so flush()
        # can surface them deterministically (sweep timing must not decide
        # whether a lost delta is seen)
        self._swept_failures: List[Exception] = []
        # step-profiler async spans per tracked msg_id (flag
        # step_profile; empty dict and one attribute read otherwise)
        self._prof_spans: Dict[int, Any] = {}

    def _wire_for(self, rank: int) -> str:
        """Wire codec per destination rank (overridden by tables with a
        compressed wire; hash/KV tables always send raw)."""
        return "none"

    def _add_meta_b(self, opt: AddOption, wire: str = "none") -> bytes:
        """Packed add meta, cached per (AddOption, wire) (one
        serialization per distinct opt instead of one per op)."""
        key = (opt, wire)
        b = self._meta_cache.get(key)
        if b is None:
            meta = {"table": self.name, "opt": opt._asdict()}
            if wire != "none":
                meta["wire"] = wire
            b = wire_mod.pack_meta(meta)
            if len(self._meta_cache) < 64:
                self._meta_cache[key] = b
        return b

    def _make_window(self, send_window_ms: Optional[float]) -> None:
        """Install the send window when enabled (per-table override wins
        over the batch_window_ms flag; <= 0 stays off)."""
        wm = (config.get_flag("batch_window_ms") if send_window_ms is None
              else float(send_window_ms))
        if wm > 0:
            self._window = _SendWindow(
                self, wm, config.get_flag("batch_window_bytes"),
                # the wire refuses frames over MAX_BATCH_OPS sub-ops; a
                # knob set past it must not make windows unsendable
                min(config.get_flag("batch_window_ops"),
                    wire_mod.MAX_BATCH_OPS))

    def _flush_window(self) -> None:
        """Ordering fence: ship any queued windowed adds before the
        caller dispatches an op that must observe them (no-op when the
        window is off or empty)."""
        if self._window is not None:
            self._window.flush_pending()

    # sweep trigger: scanning every outstanding future on every _track is
    # O(in-flight) per op (quadratic across a burst of small adds); under
    # this many pending ops the scan is deferred — memory stays bounded,
    # and flush() still surfaces every failure deterministically
    _SWEEP_THRESHOLD = 32

    def _track(self, futures: List[cf.Future], finalize=None,
               op: Optional[str] = None) -> int:
        with self._lock:
            # sweep fire-and-forget adds whose futures are all done; their
            # failures are LOGGED, not raised — raising here would poison
            # every later op on the table with a dead peer's stale error,
            # breaking the "live-shard traffic unaffected" contract (a
            # caller who cares about an add's outcome calls wait())
            done = ([mid for mid, (futs, fin) in self._pending.items()
                     if fin is None and all(f.done() for f in futs)]
                    if len(self._pending) >= self._SWEEP_THRESHOLD else ())
            for mid in done:
                futs, _ = self._pending.pop(mid)
                sp = self._prof_spans.pop(mid, None)
                if sp is not None:   # native-handle span: close at the
                    sp.end()         # sweep (its futures are all done)
                for f in futs:
                    exc = f.exception()
                    if exc is not None:
                        log.error("table[%s]: fire-and-forget op %d "
                                  "failed: %s", self.name, mid, exc)
                        if len(self._swept_failures) < 100:
                            self._swept_failures.append(exc)
            msg_id = self._next_msg_id
            self._next_msg_id += 1
            self._pending[msg_id] = (futures, finalize)
        # step-profiler async span (one attribute read when off): the
        # op's dispatch->reply interval is what the overlap-credit math
        # intersects with compute phases. Reply callbacks close it at
        # the true round-trip end; native handles (no callbacks) fall
        # back to closing at wait()/step-finalize.
        if op is not None and _profiler.PROFILER.enabled:
            span = _profiler.async_begin(op, attach="thread")
            if span is not None:
                if not _attach_profile_end(futures, span):
                    with self._lock:
                        # re-check under the lock: a concurrent _track's
                        # sweep may have already popped this msg_id (all
                        # native acks landed) — storing now would leak an
                        # open span under a dead id forever
                        if msg_id in self._pending:
                            self._prof_spans[msg_id] = span
                        else:
                            span.end()
        return msg_id

    def wait(self, msg_id: int) -> Any:
        """Block until the op behind ``msg_id`` completes (ref Wait). For
        gets, returns the assembled host array; for adds, None. Raises
        :class:`~multiverso_tpu.ps.service.PSPeerError` if an owning rank
        died — other tables/ops remain usable."""
        # a waited op may still be queued in the send window — ship it
        # (its placeholder futures complete on the window ack)
        self._flush_window()
        return self._wait_tracked(msg_id)

    def _wait_tracked(self, msg_id: int) -> Any:
        """:meth:`wait` minus the window fence — for callers that already
        fenced (flush waits many ops behind ONE fence instead of paying
        a per-owner send-lock sweep per op)."""
        with self._lock:
            entry = self._pending.pop(msg_id, None)
            span = self._prof_spans.pop(msg_id, None)
        if entry is None:
            if span is not None:
                span.end()
            return None
        futures, finalize = entry
        timeout = config.get_flag("ps_timeout")
        try:
            results = [svc.await_reply(f, timeout,
                                       f"table[{self.name}] op {msg_id}")
                       for f in futures]
        finally:
            if span is not None:   # native-handle span: the reply is in
                span.end()         # by the time await_reply returned
        return finalize(results) if finalize is not None else None

    def flush(self) -> None:
        """Wait for every outstanding op on this table (this worker only —
        NOT a barrier; peers are unaffected). Raises the first failure of
        any fire-and-forget op issued since the last flush, whether it is
        still pending or was already swept — a lost delta is reported
        deterministically, not only when sweep timing happens to expose
        it."""
        self._flush_window()
        with self._lock:
            ids = list(self._pending)
        for mid in ids:
            self._wait_tracked(mid)
        with self._lock:
            failures, self._swept_failures = self._swept_failures, []
        if failures:
            raise failures[0]

    def _zoo_dirty(self) -> None:
        """Mutating ops register with the Zoo's dirty set so a
        single-process ``mv.barrier()`` fences this table's local shard
        (raw()) like every other table's."""
        if getattr(self, "table_id", None) is not None:
            from multiverso_tpu.zoo import Zoo
            Zoo.get().mark_dirty(self.table_id)

    def server_stats(self, rank: Optional[int] = None) -> Dict:
        """Remote dashboard (MSG_STATS): pull ``rank``'s full telemetry
        snapshot — Dashboard monitor histograms, notes, and first-class
        per-shard server stats for EVERY table served there (keyed by
        table name under ``"shards"``; this table's own shard is
        ``server_stats(r)["shards"][self.name]``). ``rank=None`` reads
        the local rank without touching the socket. Raises
        :class:`~multiverso_tpu.ps.service.PSPeerError` for a dead rank,
        like any other request."""
        return self.ctx.service.stats(
            self.ctx.rank if rank is None else int(rank))

    def server_health(self, rank: Optional[int] = None) -> Dict:
        """Liveness probe (MSG_HEALTH): pull ``rank``'s compact verdict
        — serve-loop heartbeat age, shard queue depth, oldest in-flight
        op age, last watchdog verdict — distinguishing 'alive but
        stuck' from 'dead' (the latter raises the usual typed
        :class:`~multiverso_tpu.ps.service.PSPeerError`). ``rank=None``
        reads the local rank without touching the socket. See
        docs/OBSERVABILITY.md 'Postmortem debugging'."""
        return self.ctx.service.health(
            self.ctx.rank if rank is None else int(rank))


class AsyncMatrixTable(_AsyncBase):
    """Row-partitioned 2-D async table (ref MatrixTable in async mode)."""

    def __init__(self, num_row: int, num_col: int, dtype=np.float32,
                 updater: Union[str, updaters_lib.Updater, None] = None,
                 name: str = "async_matrix",
                 init: Optional[np.ndarray] = None,
                 seed: Optional[int] = None, init_scale: float = 0.0,
                 shard_workers: int = 0, wire: str = "none",
                 send_window_ms: Optional[float] = None,
                 get_window_ms: Optional[float] = None,
                 ctx: Optional[svc.PSContext] = None):
        """``shard_workers > 0`` enables per-worker dirty-bit tracking on
        the owned shard (the sparse stale-row protocol; set by
        AsyncSparseMatrixTable). ``wire="bf16"`` sends payloads over TCP
        as bfloat16 — half the bytes on the DCN-analogue wire, the role
        the reference's SparseFilter played on its MPI wire
        (quantization_util.h); values are cast back at the endpoint.
        ``wire="1bit"`` sends whole-table add deltas as sign bits +
        per-block scales (~29x fewer bytes; 1-bit SGD) with per-owner
        error feedback, row-batch adds as stateless 1-bit payloads (row
        sets change between batches, so a positional residual has no
        stable meaning there), and get replies as bf16 (parameter VALUES
        are not deltas; sign-quantizing them would be destructive —
        same rule as the sync table's 1bit mode). ``wire="topk"`` is the
        same shape with the ~3% largest-|x| entries exact (QSGD-style)
        instead of sign bits. All encodes go through
        ``ps/wire.encode_payload``: the frame blobs ARE the codec
        output, decoded exactly once at the receiving shard.

        ``send_window_ms`` overrides the ``batch_window_ms`` flag for
        this table: > 0 buffers ``add_rows_async`` client-side and ships
        each owner's queue as one (multi-op) frame — see _SendWindow.
        Gets/flush/waits fence the window, so results are bit-identical
        to window-off; only the moment an add reaches the wire changes.

        ``get_window_ms`` overrides the ``get_window_ms`` flag: > 0
        installs the client get coalescer (single-flight per-owner
        fetches deduping concurrent pullers' row ids into one frame —
        see _GetWindow). Values are unchanged; only how many frames a
        burst of concurrent gets costs."""
        super().__init__(ctx, name)
        if wire not in ("none", "bf16", "1bit", "topk"):
            raise ValueError(f"unknown wire {wire!r}")
        self._wire = wire
        # per-owner error-feedback residuals for 1bit whole-table adds
        # (each rank's delta slice has a fixed shape, so the residual's
        # positions are stable across payloads). The lock serializes the
        # encode: filter_in reads AND writes the residual, so two
        # threaded add()s racing it would compensate the same error
        # twice and bias the stream (the sync Table guards its residual
        # with the dispatch lock for the same reason)
        self._add_filters: Dict[int, Any] = {}
        self._add_filter_lock = threading.Lock()
        self.num_row, self.num_col = int(num_row), int(num_col)
        self.shape = (self.num_row, self.num_col)
        self.dtype = np.dtype(dtype)
        world = self.ctx.world
        self._rows_per = -(-self.num_row // world)   # ceil
        self.updater = _resolve_updater(updater, world, self.dtype)
        lo = min(self.ctx.rank * self._rows_per, self.num_row)
        hi = min(lo + self._rows_per, self.num_row)
        self.lo, self.hi = lo, hi
        if hi > lo:
            shard_init = (np.asarray(init, self.dtype)[lo:hi]
                          if init is not None else None)
            self._shard = RowShard(lo, hi, self.num_col, self.dtype,
                                   self.updater, name, init=shard_init,
                                   seed=seed, init_scale=init_scale,
                                   num_workers=shard_workers)
            self.ctx.service.register_handler(name, self._shard.handle,
                                              shard=self._shard)
        else:
            self._shard = None
        # client-side native transport eligibility: plain wire, no sparse
        # stale-row protocol (its dirty-bit ordering relies on the python
        # conn's FIFO), a dtype the C++ side frames. The server side needs
        # no agreement — a python peer speaks the same wire.
        self._native_ok = (wire == "none" and shard_workers == 0
                           and self.dtype.str in ("<f4", "<f8")
                           and self.ctx.service.native_enabled())
        self._plain_meta_b = wire_mod.pack_meta({"table": self.name})
        # identical on every rank: (rank, lo, hi) of each non-empty shard
        self._ranges = [(r, min(r * self._rows_per, self.num_row),
                         min((r + 1) * self._rows_per, self.num_row))
                        for r in range(world)]
        self._ranges = [(r, a, b) for r, a, b in self._ranges if b > a]
        # process-coalesced fan-out (ps/spmd.py, flag ps_fanout):
        # owners whose PSService shares this process AND this world
        # route in-process — their wire is raw like the local rank's
        # (no socket = compression buys nothing), multi-owner fan-outs
        # coalesce into ONE MSG_MULTI super-frame, and the native fast
        # path stays off (routing pins ordering to the one local
        # executor queue, the same rule as the send window). Captured
        # at construct: tables are built after the world's services,
        # and a routed rank dying/respawning changes liveness, not
        # membership.
        self._routed_set: frozenset = frozenset()
        # with the plane armed, EVERY in-process dispatch (local rank
        # included) runs INLINE on the caller thread — sub arrays are
        # consumed before the call returns, so the deferred-read
        # defensive copies below are skipped
        self._inline = bool(config.get_flag("ps_fanout"))
        if self._inline:
            from multiverso_tpu.ps import spmd as _spmd
            key = getattr(self.ctx.service, "_proc_key", None)
            self._routed_set = frozenset(
                r for r in _spmd.colocated_ranks(key)
                if r < world and r != self.ctx.rank)
        self._fanout = bool(self._routed_set)
        self._make_window(send_window_ms)
        # client get coalescer (flag get_window_ms / per-table override):
        # None = every get is its own frame (the default)
        self._get_window: Optional[_GetWindow] = None
        gm = (config.get_flag("get_window_ms") if get_window_ms is None
              else float(get_window_ms))
        if gm > 0:
            self._get_window = _GetWindow(self, gm)
        if self._window is not None or self._get_window is not None:
            # windowed adds/coalesced gets ride the python conns; every
            # other op must share that per-conn FIFO for the fences to
            # mean read-your-writes, so the native fast path (its own
            # socket = no cross-plane ordering) stays off for this table
            self._native_ok = False
        if self._fanout:
            # routed ops ride the client's local executor queue; a
            # native add racing them on its own socket would break the
            # per-owner ordering the routing plane guarantees
            self._native_ok = False
        # hot-row TRAINING cache (flag train_cache_rows; ISSUE 11): cached
        # rows serve gets locally, only cold rows cross the wire. Write-
        # through is bit-exact only when the local push delta IS what the
        # shard applies: plain-add updater, lossless wire, no sparse
        # dirty-bit protocol, and NO send window (a window may merge two
        # queued deltas into one summed add — one f32 add at the shard vs
        # two in the cache is a bit divergence)
        # (the get coalescer disqualifies write-through like the send
        # window does: _GetWindow.fetch may QUEUE a cold fetch behind an
        # in-flight one, so dispatch order is no longer conn-FIFO order
        # and a push landing in between would be replayed onto a reply
        # that already contains it)
        self._train_cache = _hotcache.make_train_cache(
            name, self.num_col, self.dtype,
            writethrough_ok=(wire == "none" and shard_workers == 0
                             and self._window is None
                             and self._get_window is None
                             and getattr(self.updater, "name", "")
                             == "default"))
        # cache/dispatch ordering lock: the cache's push-log seq must
        # order pushes vs get dispatch EXACTLY as the conn FIFO does —
        # a push logged after a get's token but entering the FIFO before
        # its cold fetch would be replayed onto a reply that already
        # contains it (double-apply), and the inverse interleave would
        # skip a replay the reply needs. Held across {on_push + add
        # dispatch} and {token + local serve + cold-get dispatch}, in
        # BOTH modes: invalidate needs it too — a push logged (seq
        # bumped, rows dropped) whose frames have NOT yet entered the
        # FIFO lets a concurrent get capture a current token, have its
        # cold fetch served pre-push rows, and fill_since admit them
        # with nothing ever invalidating them again. The shipped
        # single-writer WE pipeline never contends on it.
        self._tc_order = (threading.Lock()
                          if self._train_cache is not None else None)
        self.table_id = _maybe_register_in_zoo(self)

    # ------------------------------------------------------------------ #
    # hot-row training cache (serving/hotcache.TrainRowCache)
    # ------------------------------------------------------------------ #
    def train_cache_stats(self) -> Optional[Dict]:
        """Hit/miss/occupancy of the training cache (None when off)."""
        tc = self._train_cache
        return None if tc is None else tc.stats()

    def _tc_ordered(self):
        """The cache/dispatch ordering lock as a context (no-op when the
        cache is off)."""
        return (self._tc_order if self._tc_order is not None
                else contextlib.nullcontext())

    def train_cache_device_block(self, row_ids, bucket: int):
        """Serve ``row_ids`` as a zero-padded ``(bucket, num_col)``
        DEVICE block straight from the training cache's device mirror —
        one fused gather/pad program (ops/row_assemble), nothing crosses
        the host boundary. None unless the cache is on and EVERY id is
        cached; the caller then falls back to the normal get path (which
        does the hit/cold split and the counting itself)."""
        tc = self._train_cache
        if tc is None:
            return None
        return tc.device_block_counted(row_ids, bucket)

    # ------------------------------------------------------------------ #
    def raw(self):
        """Local shard's device array (diagnostics / Zoo barrier fencing)."""
        return self._shard._data if self._shard is not None else None

    def _prep(self, row_ids, values: Optional[np.ndarray] = None):
        return _dedupe_batch(row_ids, self.num_col, self.dtype,
                             self.num_row, values)

    def _owner_slices(self, uids: np.ndarray) -> List[Tuple[int, Any]]:
        """Partition an id batch into per-owner ``(rank, indexer)``
        parts. Sorted batches (every ``_prep`` dedupe output) get ONE
        boundary ``searchsorted`` pass and contiguous ``slice``
        indexers (zero-copy views); caller-ordered batches (``_prep``'s
        no-duplicate fast path — the PR-5 searchsorted-on-unsorted
        lesson) get vectorized per-owner position arrays, so each
        part's consumption is O(part), never an O(n) mask scan per use.
        ``arr[indexer]`` works for both shapes;
        :func:`_part_len`/:func:`_part_index` give size/positions. This
        is the ONE partition implementation — ``_by_owner`` and the
        native ``_owner_conns`` derive from it. Measured vs the
        per-owner mask generator: 100k sorted ids over 8 owners
        592 -> 108 us (5.5x), the 256-row strided train shape
        31.8 -> 15.6 us (2x), single-owner 9.9 -> 0.8 us (13x)."""
        n = uids.size
        if n == 0:
            return []
        rp = self._rows_per
        first = int(uids[0]) // rp
        last = int(uids[-1]) // rp
        if (first <= last
                and (n == 1 or bool(np.all(uids[1:] >= uids[:-1])))):
            # sorted batch (every np.unique dedupe output — the common
            # shape): O(owners · log n) boundary searchsorted, no
            # per-id division, no masks. Single-owner batches (the
            # small-add hot path) cost the monotonicity check alone.
            if first == last:
                return [(first, slice(0, n))]
            bounds = np.searchsorted(
                uids,
                np.arange(first + 1, last + 1, dtype=np.int64) * rp)
            starts = [0] + [int(b) for b in bounds] + [n]
            return [(r, slice(starts[i], starts[i + 1]))
                    for i, r in enumerate(range(first, last + 1))
                    if starts[i + 1] > starts[i]]
        # caller-ordered batch (_prep's no-duplicate fast path): one
        # vectorized division + per-owner position extraction — the
        # owner count is small (<= world), so this stays O(owners · n)
        # vectorized compares, never a python per-uid loop
        owners = uids // rp
        r0 = int(owners[0])
        if not np.any(owners != r0):
            return [(r0, slice(0, n))]
        return [(int(r), np.flatnonzero(owners == r))
                for r in np.unique(owners)]

    def _by_owner(self, uids: np.ndarray):
        """Mask-shaped compatibility wrapper over :meth:`_owner_slices`
        for callers that still want boolean masks."""
        n = uids.size
        for r, ix in self._owner_slices(uids):
            m = np.zeros(n, bool)
            m[ix] = True
            yield r, m

    def _wire_for(self, rank: int) -> str:
        """Wire codec per destination: the local rank — and any
        in-process ROUTED rank (ps_fanout) — short-circuits the socket,
        so compressing its payload would cost two casts (and bf16
        precision) for zero transport savings."""
        return ("none" if rank == self.ctx.rank
                or rank in self._routed_set else self._wire)

    def _reply_wire(self) -> str:
        """Reply wire for gets, rank-independent: 1bit/topk apply to
        DELTAS (add traffic); parameter values ride bf16 instead —
        sparsifying a pulled VALUE block would zero ~97% of the weights
        (sync-table rule). THE one place that rule lives."""
        return "bf16" if self._wire in ("1bit", "topk") else self._wire

    def _get_wire_for(self, rank: int) -> str:
        """Reply wire per source rank (local short-circuit and routed
        in-process ranks stay raw)."""
        return ("none" if rank == self.ctx.rank
                or rank in self._routed_set else self._reply_wire())

    def _owner_conns(self, uids: np.ndarray):
        """Native conns for the C-side fanout, indexed by rank. ONLY the
        ranks that own rows of THIS batch are resolved (a down rank that
        owns nothing must not cost unrelated ops its connect timeout, and
        a single-owner batch must not open world-many sockets); the rest
        stay None, which the fanout reads as no-rows/unreachable."""
        svc_ = self.ctx.service
        conns = [None] * self.ctx.world
        # owner set from the shared one-searchsorted partition pass —
        # no O(n) division/unique sweep over the id batch
        for r, _sl in self._owner_slices(uids):
            conns[r] = svc_.native_conn_or_none(r)
        return conns

    def _native_flush(self) -> None:
        """Order fence before python-conn ops that must observe earlier
        native adds (set_rows/checkpoint): wait for every add issued on
        this service's native conns. Failures are swallowed here — they
        surface deterministically through the ops' own futures."""
        if not getattr(self, "_native_ok", False):
            return
        timeout = config.get_flag("ps_timeout")
        for c in self.ctx.service.native_conns():
            if c.dead():
                continue
            seq = c.adds_issued()   # read under the C issue lock: cannot
            if seq:                 # lag a completed add on any thread
                try:
                    c.wait_adds(seq, timeout)
                except Exception:   # noqa: BLE001
                    pass

    # ------------------------------------------------------------------ #
    # row ops (ref matrix_table.h:26-75)
    # ------------------------------------------------------------------ #
    def add_rows_async(self, row_ids, values,
                       opt: Optional[AddOption] = None) -> int:
        opt = opt or AddOption(worker_id=self.ctx.rank)
        self._zoo_dirty()
        with monitor(f"table[{self.name}].add_rows"), self._tc_ordered():
            uids, vals, _ = self._prep(row_ids, values)
            if self._train_cache is not None:
                # AT DISPATCH, before any transport: the cache must see
                # this push at the same point in program order the conn
                # FIFO will (write-through applies the exact deduped
                # delta the shard will add; invalidate drops the rows)
                self._train_cache.on_push(uids, vals)
            # per-request trace ID (telemetry/trace.py): rides the frame
            # meta so client spans and the owning shard's serve/wave
            # spans stitch by ID; None (the default) costs one attribute
            # read. The native fan-out stays untraced by design (zero-
            # Python C++ path).
            tid = ttrace.new_id() if ttrace.enabled() else None
            # effective tenant (telemetry/tenants.py): None for the
            # default tenant, so default traffic keeps the cached
            # meta_b bytes and the native fast path; a named tenant
            # stamps TENANT_META_KEY on every frame (punts the native
            # server to Python like any modern meta key).
            tn = _tenants.current()
            if self._window is not None:
                # send window: enqueue per-owner pieces and return — the
                # flusher (or the next fencing op) ships each owner's
                # queue as ONE (multi-op) frame. Single-owner batches (the
                # 1-row small-add hot path) skip the mask partitioning.
                t_enq0 = time.time() if tid is not None else 0.0
                oparts = self._owner_slices(uids)
                if len(oparts) == 1:
                    # the queue reads vals LATER (flusher thread), so it
                    # must own the bytes: _prep's no-dup path can return
                    # a zero-copy view of the caller's buffer, and a
                    # reused gradient scratch would corrupt queued deltas
                    # (multi-owner slicing below always copies)
                    if vals is values or vals.base is not None:
                        vals = vals.copy()
                    parts = [(oparts[0][0], uids, vals)]
                else:
                    parts = [(r, _owned_part(uids, ix),
                              _owned_part(vals, ix))
                             for r, ix in oparts]
                mid = self._track(
                    self._window.submit(parts, opt, tid, tenant=tn),
                    op="ps.add")
                if tid is not None:
                    ttrace.add_span("client.enqueue", t_enq0, time.time(),
                                    trace=tid,
                                    args={"table": self.name,
                                          "rows": int(uids.size)})
                return mid
            if tn is None:
                meta_b = self._add_meta_b(opt)
            else:
                # named tenant: stamped meta per call (the cache is
                # keyed on (opt, wire) only; a stamped frame punts the
                # native server to Python, where _prep_add attributes it)
                meta_b = wire_mod.pack_meta(wire_mod.with_tenant(
                    {"table": self.name, "opt": opt._asdict()}, tn))
            if self._native_ok and vals.dtype == self.dtype:
                from multiverso_tpu.ps import native as ps_native
                parts = ps_native.add_fanout(
                    self._owner_conns(uids), self.ctx.world, False,
                    self._rows_per, meta_b, uids,
                    np.ascontiguousarray(vals))
                return self._track(
                    _fanout_futures(
                        parts, lambda c, s, m: _NativeAddFuture(c, s, m)),
                    op="ps.add")
            t_send0 = time.time() if tid is not None else 0.0
            futs = []
            parts = self._owner_slices(uids)
            rest = parts
            if self._fanout and len(parts) > 1:
                # multi-owner fan-out to COLOCATED owners coalesces
                # into ONE super-frame per destination process (the
                # client's local-executor hop) — one dispatch instead
                # of one frame per shard; non-colocated owners keep
                # their classic per-owner frames below
                grp = [i for i, (r, _ix) in enumerate(parts)
                       if r == self.ctx.rank or r in self._routed_set]
                if len(grp) > 1:
                    gset = set(grp)
                    rest = [p for i, p in enumerate(parts)
                            if i not in gset]
                    subs = []
                    for i in grp:
                        r, ix = parts[i]
                        meta = wire_mod.with_tenant(wire_mod.with_trace(
                            {"table": self.name, "opt": opt._asdict(),
                             wire_mod.OWNER_META_KEY: r}, tid), tn)
                        # object sub-ops, no wire framing, consumed
                        # INLINE by multi_local — views are safe
                        subs.append((svc.MSG_ADD_ROWS, meta,
                                     [uids[ix], vals[ix]]))
                    futs.extend(self.ctx.service.multi_local(subs))
            for r, ix in rest:
                w = self._wire_for(r)
                # meta and blobs per destination wire: the local short-
                # circuit stays uncompressed, remote peers get the codec
                # frame (decoded exactly once in the shard's _prep_add)
                meta = wire_mod.with_tenant(wire_mod.with_trace(
                    {"table": self.name, "opt": opt._asdict()}, tid), tn)
                if (tid is not None or tn is not None) and w != "none":
                    meta["wire"] = w
                # deferred in-process dispatch (the legacy local-rank
                # executor path, plane off) reads the arrays LATER:
                # own the bytes. With the plane armed the dispatch is
                # inline — views are safe.
                deferred = (not self._inline
                            and (r == self.ctx.rank
                                 or r in self._routed_set))
                ids_part = (_owned_part(uids, ix) if deferred
                            else uids[ix])
                vals_part = (_owned_part(vals, ix) if deferred
                             else vals[ix])
                futs.append(self.ctx.service.request(
                    r, svc.MSG_ADD_ROWS, meta,
                    [ids_part] + wire_mod.encode_payload(vals_part, w),
                    meta_b=(None if tid is not None or tn is not None
                            else self._add_meta_b(opt, w))))
            if tid is not None:
                _attach_reply_span(futs, "client.add_rows", t_send0, tid,
                                   self.name)
        return self._track(futs, op="ps.add")

    def add_rows(self, row_ids, values,
                 opt: Optional[AddOption] = None) -> None:
        self.wait(self.add_rows_async(row_ids, values, opt))

    def _can_take_reply(self, out: Optional[np.ndarray],
                        rows: int) -> bool:
        """True when the caller's buffer can take reply rows directly
        (right shape/dtype, C-contiguous) — the one predicate behind
        both the scatter-target choice and the chunked commit."""
        return (out is not None and isinstance(out, np.ndarray)
                and out.dtype == self.dtype
                and out.shape == (rows, self.num_col)
                and out.flags.c_contiguous)

    def _reply_buffer(self, out: Optional[np.ndarray], rows: int
                      ) -> np.ndarray:
        """Scatter target for a get's per-owner replies: the CALLER's
        buffer when it can take them directly, else a fresh array.
        Avoids the extra (rows x cols) allocation + copy per get on the
        steady-state training loop."""
        if self._can_take_reply(out, rows):
            return out
        return np.empty((rows, self.num_col), self.dtype)

    def get_rows_async(self, row_ids,
                       out: Optional[np.ndarray] = None) -> int:
        tc = self._train_cache
        if tc is not None:
            return self._train_cache_get(row_ids, out)
        return self._track(*self._get_rows_futs(row_ids, out),
                           op="ps.get")

    def _train_cache_get(self, row_ids,
                         out: Optional[np.ndarray] = None) -> int:
        """Cache-aware get: cached rows fill locally (host copy under
        the cache lock, captured AT DISPATCH — the same point in program
        order the wire snapshot would be taken, which is what makes
        write-through bit-identical to the uncached path); only the
        residual cold rows ride the wire, and the reply warms the cache
        for the next block."""
        tc = self._train_cache
        tc.on_get()
        uids, _, inv = self._prep(row_ids)
        # PRIVATE scatter target: cached rows land in it at DISPATCH, so
        # it must not alias the caller's out= — a cold residual failing
        # at wait() would leave out torn (the chunked plane's untouched-
        # on-failure rule); _expand commits into out only at finalize
        buf = np.empty((uids.size, self.num_col), self.dtype)
        with self._tc_ordered():
            # serve_into is ONE lock hold: token + membership + gather —
            # a concurrent fill/drop can't skew positions between them,
            # and under the cache/dispatch ordering lock the token
            # orders against pushes exactly as the conn FIFO will order
            # the cold fetch dispatched below
            token, hit = tc.serve_into(uids, buf)
            nhit = int(np.count_nonzero(hit))
            tc.count(nhit, uids.size - nhit)

            def _expand(res: np.ndarray) -> np.ndarray:
                if inv is None:
                    if res is not out and self._can_take_reply(
                            out, res.shape[0]):
                        np.copyto(out, res)
                        return out
                    return res
                dest = self._reply_buffer(out, inv.size)
                np.take(res, inv, axis=0, out=dest)
                return dest

            if nhit == uids.size:
                # full local serve, zero wire ops. Read-your-writes holds
                # without the window fence: write-through already applied
                # any queued pushes to the cache, and invalidate dropped
                # their rows (so they cannot full-hit). Still a
                # table-level get: count it in the get_rows monitor
                # (mvtop's get counters must not flatline on a warm
                # cache) — incr only, no wire latency to record
                Dashboard.get(f"table[{self.name}].get_rows").incr()
                return self._track([], lambda _res: _expand(buf),
                                   op="ps.get")
            full_miss = nhit == 0
            cold_sel = np.flatnonzero(~hit)
            cold_uids = uids[cold_sel]
            cold_buf = (buf if full_miss else
                        np.empty((cold_uids.size, self.num_col),
                                 self.dtype))
            futs, inner_fin = self._get_rows_futs(
                cold_uids, out=cold_buf, prepped=True)

        def _fin(results):
            rows_cold = inner_fin(results)
            if not full_miss:
                buf[cold_sel] = rows_cold
            elif rows_cold is not buf:
                np.copyto(buf, rows_cold)
            # warm the cache, reconciled against pushes dispatched since
            # the token (write-through replay / exclusion — fill_since)
            tc.fill_since(cold_uids, rows_cold, token)
            return _expand(buf)

        return self._track(futs, _fin, op="ps.get")

    def _get_rows_futs(self, row_ids,
                       out: Optional[np.ndarray] = None,
                       prepped: bool = False):
        """The wire get: returns ``(futures, finalize)`` for
        :meth:`_track` (split out so the training cache can fetch just
        its cold residual through the same three transports).
        ``prepped=True`` marks ``row_ids`` as already validated sorted-
        unique int64 (the cache's cold residual) — the _prep dedupe sort
        is the biggest per-op host cost and must not run twice."""
        # ordering fence: a get must observe every windowed add this
        # caller already issued (read-your-writes over per-conn FIFO)
        self._flush_window()
        with monitor(f"table[{self.name}].get_rows"):
            if prepped:
                uids, inv = np.asarray(row_ids, np.int64), None
            else:
                uids, _, inv = self._prep(row_ids)
            # effective tenant (telemetry/tenants.py): None = default,
            # frames stay unstamped and every cached-meta/coalescing
            # fast path below is untouched
            tn = _tenants.current()
            if self._native_ok:
                from multiverso_tpu.ps import native as ps_native
                # no duplicate ids: the C++ recv threads scatter replies
                # straight into the caller's buffer
                buf = self._reply_buffer(out if inv is None else None,
                                         uids.size)
                # a stamped get punts the native server to Python (punt
                # pattern, ps/wire.py) — the reply frame is unchanged,
                # so the C++ recv scatter still applies
                gmeta_b = (self._plain_meta_b if tn is None
                           else wire_mod.pack_meta(wire_mod.with_tenant(
                               {"table": self.name}, tn)))
                fparts = ps_native.get_fanout(
                    self._owner_conns(uids), self.ctx.world, False,
                    self._rows_per, gmeta_b, uids, buf)
                futs = _fanout_futures(
                    fparts, lambda c, s, m: _NativeGetFuture(c, m, buf))

                def _assemble_native(results):
                    # replies scattered into ``buf`` in the C++ recv
                    # threads; results only carry completion
                    return buf if inv is None else buf[inv]

                return futs, _assemble_native
            parts = self._owner_slices(uids)
            if self._get_window is not None and tn is None:
                # coalesced single-flight fetches: each part resolves to
                # its own row block (possibly served by a batch shared
                # with concurrent callers). Named tenants BYPASS the
                # coalescer: a batch merged across tenants would blur
                # per-tenant byte attribution at the shard, and minority
                # traffic loses little from skipping the share
                futs = [self._get_window.fetch(r, _owned_part(uids, ix))
                        for r, ix in parts]

                def _assemble_win(results):
                    buf = self._reply_buffer(out if inv is None else None,
                                             uids.size)
                    for (r, ix), rows in zip(parts, results):
                        buf[ix] = rows
                    if inv is None:
                        return buf
                    dest = self._reply_buffer(out, inv.size)
                    np.take(buf, inv, axis=0, out=dest)
                    return dest

                return futs, _assemble_win
            # remote peers share one packed meta (with the table's reply
            # wire); the local short-circuit keeps its uncompressed dict
            gw = self._reply_wire()
            chunk = int(config.get_flag("get_chunk_rows"))
            tid = ttrace.new_id() if ttrace.enabled() else None
            t_send0 = time.time() if tid is not None else 0.0
            meta_b = wire_mod.pack_meta(wire_mod.with_tenant(
                wire_mod.with_trace(
                    {"table": self.name, "wire": gw}, tid), tn))
            # in-process destinations (local rank / routed colocated
            # ranks) never chunk-stream: there is no network receive to
            # overlap, and routed multi-owner parts coalesce below
            inproc = {r for r, _ix in parts
                      if r == self.ctx.rank or r in self._routed_set}
            will_chunk = {r for r, ix in parts
                          if (chunk > 0 and _part_len(ix) > chunk
                              and r not in inproc)}
            # the scatter target exists BEFORE dispatch when a part may
            # stream back chunked: the sinks decode each sub-frame on
            # the recv thread straight into it, overlapping the receive.
            # With chunking live the target is PRIVATE even when the
            # caller passed out= — a stream failing mid-way must raise
            # with the caller's buffer untouched, not torn across two
            # epochs; _assemble commits into out only on full success.
            buf = self._reply_buffer(
                out if inv is None and not will_chunk else None,
                uids.size)
            futs_by_part: Dict[int, Any] = {}
            chunked: Dict[int, bool] = {}
            grp: List[Tuple[int, Tuple[int, slice]]] = []
            if self._fanout and len(parts) > 1:
                grp = [(i, p) for i, p in enumerate(parts)
                       if p[0] in inproc]
                if len(grp) < 2:
                    grp = []
            if grp:
                # multi-owner fan-out to colocated owners: ONE
                # super-frame, one grouped SPMD gather at the other end
                # (object sub-ops — no wire framing in-process)
                subs = []
                for _i, (r, ix) in grp:
                    subs.append((svc.MSG_GET_ROWS,
                                 wire_mod.with_tenant(wire_mod.with_trace(
                                     {"table": self.name,
                                      "wire": "none",
                                      wire_mod.OWNER_META_KEY: r}, tid),
                                     tn),
                                 [uids[ix]]))
                for (i, _p), f in zip(
                        grp, self.ctx.service.multi_local(subs)):
                    futs_by_part[i] = f
            for i, (r, ix) in enumerate(parts):
                if i in futs_by_part:
                    continue
                if r in will_chunk:
                    futs_by_part[i] = self.ctx.service.request(
                        r, svc.MSG_GET_ROWS,
                        wire_mod.with_tenant(wire_mod.with_trace(
                            {"table": self.name, "wire": gw,
                             "chunk": chunk}, tid), tn),
                        [uids[ix]],
                        chunk_sink=_chunk_scatter(
                            buf, _part_index(ix),
                            self.num_col, self.dtype))
                    chunked[r] = True
                else:
                    # legacy executor dispatch (plane off) reads the
                    # ids later: own the bytes; inline = views safe
                    ids_part = (_owned_part(uids, ix)
                                if r in inproc and not self._inline
                                else uids[ix])
                    futs_by_part[i] = self.ctx.service.request(
                        r, svc.MSG_GET_ROWS,
                        wire_mod.with_tenant(wire_mod.with_trace(
                            {"table": self.name, "wire": "none"}, tid),
                            tn),
                        [ids_part], meta_b=meta_b)
            futs = [futs_by_part[i] for i in range(len(parts))]
            if tid is not None:
                _attach_reply_span(futs, "client.get_rows", t_send0, tid,
                                   self.name)

            def _assemble(results):
                for (r, ix), (rmeta, arrays) in zip(parts, results):
                    if chunked.get(r) and rmeta.get("chunks"):
                        continue   # the sinks already scattered this part
                    w = "none" if r in inproc else gw
                    buf[ix] = wire_mod.decode_payload(
                        arrays, w, (_part_len(ix),
                                    self.num_col), self.dtype)
                if inv is None:
                    if (out is not None and buf is not out
                            and self._can_take_reply(out, uids.size)):
                        # chunked scatter used a private buffer: commit
                        # to the caller's ONLY now, after every part
                        # completed successfully. A shape-valid but
                        # dtype/layout-unsuitable out skips this — the
                        # get_rows fallback does the one cast-copy.
                        np.copyto(out, buf)
                        return out
                    return buf
                # re-expand duplicates to original order, into the
                # caller's buffer when it fits
                dest = self._reply_buffer(out, inv.size)
                np.take(buf, inv, axis=0, out=dest)
                return dest

        return futs, _assemble

    def get_rows(self, row_ids, out: Optional[np.ndarray] = None
                 ) -> np.ndarray:
        flat_out = None
        if out is not None:
            # validate the SHAPE up front: the old reshape-then-copyto
            # fallback silently accepted ANY out whose size matched — a
            # (cols, rows) buffer would be filled transposed and read
            # back as garbage rows. Accepted: the exact (n, cols) shape,
            # or an unambiguous FLAT (n*cols,) buffer (the legacy
            # reference-binding surface, handlers.py — row-major fill is
            # its only meaning). Everything else raises.
            want = (np.asarray(row_ids).reshape(-1).size, self.num_col)
            shape = getattr(out, "shape", None)
            if (shape == (want[0] * want[1],)
                    and out.flags.c_contiguous):
                # contiguity required: reshape on a strided 1-D view
                # would COPY, and the fill would never reach the caller
                flat_out, out = out, None   # fill via the copy fallback
            elif shape != want:
                raise ValueError(
                    f"get_rows(out=): out has shape {shape}, required "
                    f"{want} (or flat ({want[0] * want[1]},))")
        host = self.wait(self.get_rows_async(row_ids, out=out))
        if flat_out is not None:
            np.copyto(flat_out.reshape(host.shape), host)
            return flat_out
        if out is not None and host is not out:
            # fallback for dtype/layout mismatches the reply scatter
            # could not take directly (shapes already validated equal)
            np.copyto(out, host)
            return out
        return host

    def get_row(self, row_id: int) -> np.ndarray:
        return self.get_rows([row_id])[0]

    def add_row(self, row_id: int, values,
                opt: Optional[AddOption] = None) -> None:
        self.add_rows([row_id], np.asarray(values).reshape(1, -1), opt)

    def set_rows(self, row_ids, values) -> None:
        """Overwrite rows (load/master-init plumbing; no updater).
        Duplicate ids are ill-defined for an overwrite, so ids must be
        unique (checkpoint load passes ranges)."""
        self._zoo_dirty()
        ids = np.asarray(row_ids, np.int64).reshape(-1)
        vals = np.asarray(values, self.dtype).reshape(-1, self.num_col)
        if vals.shape[0] != ids.size:
            raise ValueError("set_rows: one value row per id required")
        order = np.argsort(ids, kind="stable")
        uids, vals = ids[order], vals[order]   # sorted, vals kept aligned
        if uids.size > 1 and np.any(uids[1:] == uids[:-1]):
            raise ValueError("set_rows requires unique row ids")
        if np.any((uids < 0) | (uids >= self.num_row)):
            raise IndexError(f"row id out of range [0, {self.num_row})")
        # order fence: earlier native adds must be acked before this
        # overwrite travels the python conn (different sockets = no FIFO),
        # and queued windowed adds must leave first (same-conn FIFO)
        self._native_flush()
        self._flush_window()
        meta = {"table": self.name}
        futs = [self.ctx.service.request(r, svc.MSG_SET_ROWS, meta,
                                         [uids[m], vals[m]])
                for r, m in self._by_owner(uids)]
        if self._train_cache is not None:
            # not a replayable add: drop + poison, AFTER the frames
            # entered the conn FIFOs — an overwrite logged before
            # dispatch lets a get slip into the window, fetch
            # pre-overwrite rows from the shard and cache them under a
            # current fill token, permanently stale
            self._train_cache.on_overwrite(uids)
        self.wait(self._track(futs, lambda rs: None))

    # ------------------------------------------------------------------ #
    # whole-table ops
    # ------------------------------------------------------------------ #
    def add_async(self, delta, opt: Optional[AddOption] = None) -> int:
        opt = opt or AddOption(worker_id=self.ctx.rank)
        self._zoo_dirty()
        # fence: queued windowed row adds must land before a whole-table
        # delta (floating-point accumulation does not commute bit-wise)
        self._flush_window()
        try:
            return self._add_full_dispatch(delta, opt)
        finally:
            if self._train_cache is not None:
                # whole-table delta: conservative wholesale drop, AFTER
                # the frames entered the conn FIFOs — a clear logged
                # before dispatch lets a get slip into the window, fetch
                # pre-add rows from the shard and cache them under a
                # current fill token, permanently stale
                self._train_cache.clear()

    def _add_full_dispatch(self, delta, opt: AddOption) -> int:
        with monitor(f"table[{self.name}].add"):
            delta = np.ascontiguousarray(
                np.asarray(delta, self.dtype).reshape(self.shape))
            if self._native_ok:
                meta_b = self._add_meta_b(opt)
                futs = [_native_add(self.ctx.service, r, svc.MSG_ADD_FULL,
                                    meta_b, None, delta[a:b])
                        for r, a, b in self._ranges]
                return self._track(futs)
            futs = []
            for r, a, b in self._ranges:
                w = self._wire_for(r)
                if w == "1bit":
                    # per-owner error feedback: this rank's slice shape is
                    # fixed, so the residual's positions are stable — the
                    # quantization error of each payload rides the next
                    # one (1-bit SGD), and the filter's (bits, scales)
                    # blobs ARE the frame payload. Encode under the
                    # filter lock: filter_in reads and writes the
                    # residual, and threaded adds must not double-apply
                    # the same compensation
                    from multiverso_tpu.utils.filters import OneBitsFilter
                    with self._add_filter_lock:
                        filt = self._add_filters.get(r)
                        if filt is None:
                            filt = self._add_filters[r] = OneBitsFilter(
                                block=wire_mod.ONEBIT_BLOCK)
                        _, bits, scales = filt.filter_in(delta[a:b])
                    arrays = [bits, scales]
                elif w == "topk":
                    # same per-owner error-feedback rule as 1bit: the
                    # slice shape is fixed, so residual positions are
                    # stable — without the filter the ~97% of gradient
                    # mass off the top-k support would be PERMANENTLY
                    # dropped every call (unbounded systematic bias); the
                    # stateless encode is only for row batches, whose row
                    # sets change between calls
                    from multiverso_tpu.utils.filters import (TopKFilter,
                                                              default_topk)
                    with self._add_filter_lock:
                        filt = self._add_filters.get(r)
                        if filt is None:
                            filt = self._add_filters[r] = TopKFilter(
                                default_topk((b - a) * self.num_col))
                        _, idx, topv = filt.filter_in(delta[a:b])
                    arrays = [idx, topv]
                else:
                    arrays = wire_mod.encode_payload(delta[a:b], w)
                meta = {"table": self.name, "opt": opt._asdict()}
                if w != "none":
                    meta["wire"] = w
                futs.append(self.ctx.service.request(
                    r, svc.MSG_ADD_FULL, meta, arrays,
                    meta_b=self._add_meta_b(opt, w)))
        return self._track(futs)

    def add(self, delta, opt: Optional[AddOption] = None) -> None:
        self.wait(self.add_async(delta, opt))

    def get_async(self) -> int:
        self._flush_window()   # read-your-writes for windowed adds
        with monitor(f"table[{self.name}].get"):
            ranges = list(self._ranges)
            host = np.empty(self.shape, self.dtype)
            chunked: Dict[int, bool] = {}
            if self._native_ok:
                futs = [_native_get(self.ctx.service, r, svc.MSG_GET_FULL,
                                    self._plain_meta_b, None,
                                    np.empty((b - a, self.num_col),
                                             self.dtype))
                        for r, a, b in ranges]
            else:
                chunk = int(config.get_flag("get_chunk_rows"))
                futs = []
                for r, a, b in ranges:
                    w = self._get_wire_for(r)
                    if (chunk > 0 and (b - a) > chunk
                            and r != self.ctx.rank):
                        # streamed whole-shard pull: sub-frames scatter
                        # into this range's rows as they land
                        futs.append(self.ctx.service.request(
                            r, svc.MSG_GET_FULL,
                            {"table": self.name, "wire": w,
                             "chunk": chunk},
                            chunk_sink=_chunk_scatter(
                                host[a:b], None, self.num_col,
                                self.dtype)))
                        chunked[r] = True
                    else:
                        futs.append(self.ctx.service.request(
                            r, svc.MSG_GET_FULL,
                            {"table": self.name, "wire": w}))

            def _assemble(results):
                for (r, a, b), (rmeta, arrays) in zip(ranges, results):
                    if chunked.get(r) and rmeta.get("chunks"):
                        continue   # scattered by the sinks already
                    host[a:b] = wire_mod.decode_payload(
                        arrays, self._get_wire_for(r),
                        (b - a, self.num_col), self.dtype)
                return host

        return self._track(futs, _assemble)

    def get(self, out: Optional[np.ndarray] = None) -> np.ndarray:
        host = self.wait(self.get_async())
        if out is not None:
            np.copyto(out.reshape(self.shape), host)
            return out
        return host

    # ------------------------------------------------------------------ #
    # checkpoint (whole-table via the service; every rank may call, only
    # rank 0's stream is real under checkpoint.save)
    # ------------------------------------------------------------------ #
    _STATE_MARKER = 0x4D565553   # "MVUS": updater state follows the data

    def store(self, stream) -> None:
        # checkpoints are durable state: always pull full precision, even
        # when the table's live traffic rides a compressed wire
        saved, self._wire = self._wire, "none"
        try:
            np.save(stream, self.get(), allow_pickle=False)
        finally:
            self._wire = saved
        # per-owner updater state (sync tables persist theirs, table.py
        # store(); restoring without it would silently reset adagrad/adam
        # accumulators). Stored per shard — async shards legitimately
        # diverge (e.g. adam step counts advance at each owner's own rate),
        # so there is no meaningful global reassembly.
        np.save(stream, np.array([self._STATE_MARKER, len(self._ranges)],
                                 np.int64), allow_pickle=False)
        timeout = config.get_flag("ps_timeout")
        for r, _, _ in self._ranges:
            meta, leaves = svc.await_reply(
                self.ctx.service.request(r, svc.MSG_GET_STATE,
                                         {"table": self.name}),
                timeout, f"table[{self.name}] state from {r}")
            np.save(stream, np.array([len(leaves)], np.int64),
                    allow_pickle=False)
            for leaf in leaves:
                np.save(stream, leaf, allow_pickle=False)

    def load(self, stream, _data: Optional[np.ndarray] = None) -> None:
        self._load(stream, only_local=False, _data=_data)

    def load_local(self, stream) -> None:
        """Restore ONLY this rank's owned row range (+ its updater state)
        from a full-table checkpoint stream — elastic shard recovery: a
        restarted owner reloads its shard without touching the peers'
        NEWER live state (a full load() would roll everyone back)."""
        self._load(stream, only_local=True)

    def _load(self, stream, only_local: bool,
              _data: Optional[np.ndarray] = None) -> None:
        data = np.load(stream) if _data is None else _data
        if data.shape != self.shape:
            raise ValueError(f"checkpoint shape {data.shape} != {self.shape}")
        me = self.ctx.rank
        for r, a, b in self._ranges:
            if not only_local or r == me:
                self.set_rows(np.arange(a, b), data[a:b])
        try:
            header = np.load(stream)
        except EOFError:
            # ONLY a clean end-of-stream means "legacy checkpoint without
            # updater state" (np.load raises EOFError at a clean boundary,
            # ValueError/OSError mid-read) — a truncated or corrupt
            # trailer must fail the restore, not silently keep stale
            # optimizer accumulators
            log.info("table[%s]: checkpoint predates updater-state "
                        "persistence; optimizer accumulators keep their "
                        "current values", self.name)
            return
        if header.size != 2 or int(header[0]) != self._STATE_MARKER:
            raise ValueError(
                f"table[{self.name}]: unrecognized checkpoint trailer "
                "(not an async-table stream?)")
        if int(header[1]) != len(self._ranges):
            raise ValueError(
                f"table[{self.name}]: checkpoint has per-shard updater "
                f"state for {int(header[1])} owners but the world now has "
                f"{len(self._ranges)} — shard accumulators cannot be "
                "remapped; restore with the original world size")
        timeout = config.get_flag("ps_timeout")
        for r, _, _ in self._ranges:
            n = int(np.load(stream)[0])
            leaves = [np.load(stream) for _ in range(n)]
            if only_local and r != me:
                continue
            svc.await_reply(
                self.ctx.service.request(r, svc.MSG_SET_STATE,
                                         {"table": self.name}, leaves),
                timeout, f"table[{self.name}] state to {r}")


class _SparseGetMixin:
    """Worker-side half of the stale-row protocol, shared by the range-
    sharded and hash-sharded sparse tables: per-worker row cache + the
    stale-only pull.

    Pipeline-safe: ``get_rows_sparse_async`` lets a prefetch thread pull
    block N+1 while block N trains — the reference had to DOUBLE its
    per-worker state slots to tolerate exactly this overlap
    (ref src/table/matrix.cpp:407-418 is_pipeline). Here the server reply
    carries the stale rows atomically with the bits it cleared, so
    overlapped pulls need only a per-worker cache lock; an out-of-order
    wait() at worst self-heals with a plain re-pull, never serves wrong
    data."""

    def _worker_cache(self, worker_id: int):
        from multiverso_tpu.tables.sparse_matrix_table import _RowCache
        if not (0 <= worker_id < self._n_workers):
            raise IndexError(f"worker_id {worker_id} out of range "
                             f"[0, {self._n_workers})")
        with self._caches_lock:
            entry = self._caches.get(worker_id)
            if entry is None:
                entry = self._caches[worker_id] = (
                    _RowCache(self.num_col, self.dtype),
                    threading.Lock(), {})   # cache, lock, row -> pull seq
        return entry

    def _next_seq(self) -> int:
        with self._caches_lock:
            self._pull_seq += 1
            return self._pull_seq

    def get_rows_sparse_async(self, row_ids,
                              worker_id: Optional[int] = None) -> int:
        """Dispatch a stale-only pull; ``wait(msg_id)`` returns the rows.
        Multiple pulls for the same worker may be in flight (the
        double-buffer pattern, ref async_buffer.h + matrix.cpp:407-418)."""
        worker_id = self.ctx.rank if worker_id is None else worker_id
        cache, cache_lock, seqs = self._worker_cache(worker_id)
        self._flush_window()   # read-your-writes for windowed adds
        with monitor(f"table[{self.name}].get_rows_sparse"):
            uids, _, inv = self._prep(row_ids)
            parts = list(self._by_owner(uids))
            meta = {"table": self.name, "sparse": True,
                    "worker_id": int(worker_id)}
            meta_b = wire_mod.pack_meta(meta)
            # resolve peers BEFORE taking the cache lock: a down owner's
            # rendezvous lookup + connect can take ps_connect_timeout
            # (30 s default), and holding the lock across it would stall
            # every other pull and wait() for this worker — including the
            # training thread — instead of just traffic to that owner
            for r, _ in parts:
                if r != self.ctx.rank:
                    try:
                        self.ctx.service._peer(r)
                    except svc.PSError:
                        pass   # request() below fails fast via backoff
            with cache_lock:
                # seq is allocated AND the requests are sent under the
                # cache lock, so per worker: seq order == wire send order
                # == server processing order (one conn per owner, FIFO) —
                # the ordering the version filter below relies on
                seq = self._next_seq()
                futs = [self.ctx.service.request(r, svc.MSG_GET_ROWS, meta,
                                                 [uids[m]], meta_b=meta_b)
                        for r, m in parts]

        def _finalize(results):
            transferred = 0
            with cache_lock:
                for (r, m), (_, (mask, rows)) in zip(parts, results):
                    stale = uids[m][mask.astype(bool)]
                    if stale.size == 0:
                        continue
                    # version filter: an out-of-order wait() must not let
                    # an OLDER pull's rows overwrite data a newer pull
                    # already cached (the server bit is clear by now, so
                    # the revert would be served forever)
                    keep = np.array([seqs.get(int(i), -1) < seq
                                     for i in stale.tolist()])
                    fresh_ids = stale[keep]
                    if fresh_ids.size:
                        cache.put(fresh_ids, rows[keep])
                        for i in fresh_ids.tolist():
                            seqs[int(i)] = seq
                        transferred += int(fresh_ids.size)
                try:
                    out = cache.take(uids)
                except KeyError:
                    # self-healing: a reply that cleared dirty bits on the
                    # server was lost (timeout/conn drop) or is being
                    # waited out of dispatch order — re-pull the gap with a
                    # plain get. The reference had the same window and no
                    # recovery (matrix.cpp clears up_to_date_ before the
                    # reply crosses MPI).
                    _, found = cache._locate(uids)
                    missing = uids[~found]
                    heal_seq = self._next_seq()  # plain get: newest data
                    cache.put(missing, self.get_rows(missing))
                    for i in missing.tolist():
                        seqs[int(i)] = heal_seq
                    transferred += int(missing.size)
                    out = cache.take(uids)
            self.last_transfer_rows = transferred
            return out if inv is None else out[inv]

        return self._track(futs, _finalize)

    def get_rows_sparse(self, row_ids, worker_id: Optional[int] = None
                        ) -> np.ndarray:
        return self.wait(self.get_rows_sparse_async(row_ids, worker_id))


class AsyncSparseMatrixTable(_SparseGetMixin, AsyncMatrixTable):
    """Stale-row protocol on the uncoordinated plane (ref src/table/
    matrix.cpp:432-572 — the reference's async server's sparse mode):
    ``get_rows_sparse(ids, worker_id)`` transfers ONLY the rows that
    changed since this worker last pulled them; fresh rows come from the
    worker-side row cache. Dirty bits live on each owning shard, per
    worker — exactly the ``up_to_date_[worker][row]`` bookkeeping."""

    def __init__(self, num_row: int, num_col: int, dtype=np.float32,
                 updater=None, name: str = "async_sparse_matrix",
                 init=None, seed=None, init_scale: float = 0.0,
                 num_workers: Optional[int] = None,
                 send_window_ms: Optional[float] = None,
                 get_window_ms: Optional[float] = None,
                 ctx: Optional[svc.PSContext] = None):
        ctx = ctx if ctx is not None else svc.default_context()
        self._n_workers = num_workers or max(ctx.world, 1)
        super().__init__(num_row, num_col, dtype=dtype, updater=updater,
                         name=name, init=init, seed=seed,
                         init_scale=init_scale,
                         shard_workers=self._n_workers,
                         send_window_ms=send_window_ms,
                         get_window_ms=get_window_ms, ctx=ctx)
        self._caches: Dict[int, Any] = {}
        self._caches_lock = threading.Lock()
        self._pull_seq = 0
        self.last_transfer_rows = -1   # diagnostic: rows over the wire


class AsyncSparseKVTable(_SparseGetMixin, _AsyncBase):
    """Hash-sharded sparse-KEY table: arbitrary non-negative int64 keys,
    owner = ``key % world`` — the uncoordinated home of the reference's
    app-defined sparse LR tables (ref Applications/LogisticRegression/src/
    util/sparse_table.h:1-306 SparseWorkerTable/SparseServerTable;
    model/ps_model.cpp:24-41 creates them for sparse/FTRL runs). With
    ``updater="ftrl"`` each key's row is the ready weight recomputed from
    the z/n state (ftrl_sparse_table.h:1-90) — workers push raw gradients.
    Slots materialize server-side on first touch; a Get of a fresh key
    returns zeros (= FTRL's w for empty state)."""

    def __init__(self, num_col: int, dtype=np.float32,
                 updater: Union[str, updaters_lib.Updater, None] = None,
                 name: str = "async_sparse_kv",
                 num_row: Optional[int] = None,
                 num_workers: Optional[int] = None,
                 send_window_ms: Optional[float] = None,
                 ctx: Optional[svc.PSContext] = None):
        super().__init__(ctx, name)
        self.num_col = int(num_col)
        self.dtype = np.dtype(dtype)
        self.num_row = num_row   # optional key bound (enables dense get())
        self._n_workers = num_workers or max(self.ctx.world, 1)
        self.updater = _resolve_updater(updater, self._n_workers, self.dtype)
        from multiverso_tpu.ps.shard import HashShard
        self._shard = HashShard(self.num_col, self.dtype, self.updater,
                                name, num_workers=self._n_workers)
        # shard= is stats-only here: hash shards never register natively
        # (the native gate requires an exact host-backed RowShard)
        self.ctx.service.register_handler(name, self._shard.handle,
                                          shard=self._shard)
        self._caches: Dict[int, Any] = {}
        self._caches_lock = threading.Lock()
        self._pull_seq = 0
        self.last_transfer_rows = -1
        self._make_window(send_window_ms)
        self.table_id = _maybe_register_in_zoo(self)

    def raw(self):
        return self._shard._data

    # --------------------------- partitioning ------------------------- #
    def _prep(self, keys, values: Optional[np.ndarray] = None):
        return _dedupe_batch(keys, self.num_col, self.dtype,
                             self.num_row, values)

    def _by_owner(self, uids: np.ndarray):
        owners = uids % self.ctx.world
        for r in np.unique(owners):
            yield int(r), owners == r

    # --------------------------- key ops ------------------------------ #
    def add_rows_async(self, keys, values,
                       opt: Optional[AddOption] = None) -> int:
        opt = opt or AddOption(worker_id=self.ctx.rank)
        self._zoo_dirty()
        with monitor(f"table[{self.name}].add_rows"):
            uids, vals, _ = self._prep(keys, values)
            tid = ttrace.new_id() if ttrace.enabled() else None
            tn = _tenants.current()
            if self._window is not None:
                # send window: per-owner key batches queue and ship as
                # one (multi-op) frame — see _SendWindow. Single-owner
                # batches skip the mask partitioning (small-add hot path).
                t_enq0 = time.time() if tid is not None else 0.0
                owners = uids % self.ctx.world
                r0 = int(owners[0])
                if uids.size == 1 or not np.any(owners != r0):
                    # deferred read: own the bytes (see the matrix table)
                    if vals is values or vals.base is not None:
                        vals = vals.copy()
                    parts = [(r0, uids, vals)]
                else:
                    parts = [(r, uids[m], vals[m])
                             for r, m in self._by_owner(uids)]
                mid = self._track(
                    self._window.submit(parts, opt, tid, tenant=tn),
                    op="ps.add")
                if tid is not None:
                    ttrace.add_span("client.enqueue", t_enq0, time.time(),
                                    trace=tid,
                                    args={"table": self.name,
                                          "rows": int(uids.size)})
                return mid
            meta = wire_mod.with_tenant(wire_mod.with_trace(
                {"table": self.name, "opt": opt._asdict()}, tid), tn)
            meta_b = wire_mod.pack_meta(meta)
            futs = [self.ctx.service.request(r, svc.MSG_ADD_ROWS, meta,
                                             [uids[m], vals[m]],
                                             meta_b=meta_b)
                    for r, m in self._by_owner(uids)]
        return self._track(futs, op="ps.add")

    def add_rows(self, keys, values,
                 opt: Optional[AddOption] = None) -> None:
        self.wait(self.add_rows_async(keys, values, opt))

    def get_rows_async(self, keys) -> int:
        self._flush_window()   # read-your-writes for windowed adds
        with monitor(f"table[{self.name}].get_rows"):
            uids, _, inv = self._prep(keys)
            parts = list(self._by_owner(uids))
            meta = wire_mod.with_tenant({"table": self.name},
                                        _tenants.current())
            meta_b = wire_mod.pack_meta(meta)
            futs = [self.ctx.service.request(
                        r, svc.MSG_GET_ROWS, meta,
                        [uids[m]], meta_b=meta_b)
                    for r, m in parts]

            def _assemble(results):
                out = np.empty((uids.size, self.num_col), self.dtype)
                for (r, m), (_, arrays) in zip(parts, results):
                    out[m] = arrays[0]
                return out if inv is None else out[inv]

        return self._track(futs, _assemble, op="ps.get")

    def get_rows(self, keys) -> np.ndarray:
        return self.wait(self.get_rows_async(keys))

    def get(self) -> np.ndarray:
        """Dense (num_row, num_col) view; needs the key bound."""
        if self.num_row is None:
            raise ValueError(f"table[{self.name}] is unbounded; get() needs "
                             "num_row (or use get_rows/key enumeration)")
        return self.get_rows(np.arange(self.num_row))

    # --------------------------- checkpoint --------------------------- #
    def store(self, stream) -> None:
        """(keys, rows, per-key updater state) per owner — the reference
        stubbed KV Store/Load (kv_table.h:101-119); here it round-trips."""
        self._flush_window()   # the dump must see this caller's queued adds
        timeout = config.get_flag("ps_timeout")
        np.save(stream, np.array([self.ctx.world], np.int64),
                allow_pickle=False)
        for r in range(self.ctx.world):
            meta, arrays = svc.await_reply(
                self.ctx.service.request(
                    r, svc.MSG_GET_STATE, {"table": self.name, "dump": True}),
                timeout, f"table[{self.name}] dump from {r}")
            np.save(stream, np.array([len(arrays)], np.int64),
                    allow_pickle=False)
            for a in arrays:
                np.save(stream, a, allow_pickle=False)

    def load(self, stream) -> None:
        self._load(stream, only_local=False)

    def load_local(self, stream) -> None:
        """Elastic shard recovery: restore only this rank's hash shard."""
        self._load(stream, only_local=True)

    def _load(self, stream, only_local: bool) -> None:
        # stale pre-restore deltas must not land on top of restored state
        self._flush_window()
        world = int(np.load(stream)[0])
        if world != self.ctx.world:
            raise ValueError(
                f"table[{self.name}]: checkpoint written at world={world}, "
                f"now {self.ctx.world} — hash shards cannot be remapped")
        timeout = config.get_flag("ps_timeout")
        for r in range(self.ctx.world):
            n = int(np.load(stream)[0])
            arrays = [np.load(stream) for _ in range(n)]
            if only_local and r != self.ctx.rank:
                continue
            svc.await_reply(
                self.ctx.service.request(
                    r, svc.MSG_SET_STATE, {"table": self.name, "dump": True},
                    arrays),
                timeout, f"table[{self.name}] restore to {r}")


class AsyncArrayTable(_AsyncBase):
    """1-D async table: contiguous-range sharding of a vector
    (ref src/table/array_table.cpp:11-21 worker offsets). Implemented as a
    single-column matrix — ranges ARE row blocks."""

    def __init__(self, size: int, dtype=np.float32,
                 updater=None, name: str = "async_array",
                 init: Optional[np.ndarray] = None, wire: str = "none",
                 ctx: Optional[svc.PSContext] = None):
        super().__init__(ctx, name)
        self.size = int(size)
        self.dtype = np.dtype(dtype)
        init2d = (np.asarray(init, self.dtype).reshape(self.size, 1)
                  if init is not None else None)
        self._m = AsyncMatrixTable(self.size, 1, dtype=dtype,
                                   updater=updater, name=name,
                                   init=init2d, wire=wire, ctx=self.ctx)
        self.table_id = self._m.table_id

    def raw(self):
        return self._m.raw()

    def add_async(self, values, opt: Optional[AddOption] = None) -> int:
        return self._m.add_async(
            np.asarray(values, self.dtype).reshape(self.size, 1), opt)

    def add(self, values, opt: Optional[AddOption] = None) -> None:
        self._m.wait(self.add_async(values, opt))

    def get_async(self) -> int:
        return self._m.get_async()

    def get(self, out: Optional[np.ndarray] = None) -> np.ndarray:
        host = self._m.get().reshape(self.size)
        if out is not None:
            np.copyto(out.reshape(self.size), host)
            return out
        return host

    def wait(self, msg_id: int) -> Any:
        res = self._m.wait(msg_id)
        return res.reshape(self.size) if isinstance(res, np.ndarray) else res

    def flush(self) -> None:
        self._m.flush()

    def store(self, stream) -> None:
        self._m.store(stream)   # (size, 1) data + per-owner updater state

    def load(self, stream) -> None:
        data = np.load(stream)
        if data.ndim == 1:   # legacy 1-D array-table stream stays loadable
            data = data.reshape(self.size, 1)
        self._m.load(stream, _data=data)

    def load_local(self, stream) -> None:
        self._m.load_local(stream)


class AsyncMatrixTableOption:
    """ref DEFINE_TABLE_TYPE option parity for ``mv.create_table`` on the
    uncoordinated plane."""

    def __init__(self, num_row: int, num_col: int, dtype=np.float32,
                 updater=None, init=None, seed=None,
                 init_scale: float = 0.0):
        self.num_row, self.num_col = num_row, num_col
        self.dtype, self.updater = dtype, updater
        self.init, self.seed, self.init_scale = init, seed, init_scale

    def build(self, name: str = "async_matrix") -> "AsyncMatrixTable":
        return AsyncMatrixTable(self.num_row, self.num_col,
                                dtype=self.dtype, updater=self.updater,
                                name=name, init=self.init, seed=self.seed,
                                init_scale=self.init_scale)


class AsyncArrayTableOption:
    def __init__(self, size: int, dtype=np.float32, updater=None,
                 init=None):
        self.size, self.dtype, self.updater, self.init = (size, dtype,
                                                          updater, init)

    def build(self, name: str = "async_array") -> "AsyncArrayTable":
        return AsyncArrayTable(self.size, dtype=self.dtype,
                               updater=self.updater, name=name,
                               init=self.init)


class AsyncKVTable(_AsyncBase):
    """Hash-sharded async KV table (ref include/multiverso/table/
    kv_table.h:44-54 ``key % num_servers``). ``get`` reads the
    server-aggregated value directly — uncoordinated, exactly the
    reference's Get semantics (no collective involved)."""

    def __init__(self, name: str = "async_kv",
                 ctx: Optional[svc.PSContext] = None):
        super().__init__(ctx, name)
        self._shard = KVShard(name)
        # shard= is stats-only (KV shards are host dicts, never native)
        self.ctx.service.register_handler(name, self._shard.handle,
                                          shard=self._shard)
        self.table_id = _maybe_register_in_zoo(self)

    def _owner(self, key: int) -> int:
        return int(key) % self.ctx.world

    def add(self, keys: Iterable[int], values: Iterable) -> None:
        keys = np.asarray(list(keys), np.int64)
        vals = np.asarray(list(values), np.float64)
        meta = {"table": self.name}
        futs = []
        for r in range(self.ctx.world):
            m = (keys % self.ctx.world) == r
            if m.any():
                futs.append(self.ctx.service.request(
                    r, svc.MSG_KV_ADD, meta, [keys[m], vals[m]]))
        self.wait(self._track(futs, lambda rs: None))

    def get(self, keys: Optional[Iterable[int]] = None,
            global_: bool = True) -> Dict[int, float]:
        """Aggregated read off the hash shards. ``global_`` is accepted for
        sync-KVTable API compatibility and ignored: an async Get is always
        the server-aggregated value (ref kv_table.h:44-99)."""
        meta = {"table": self.name}
        out: Dict[int, float] = {}
        if keys is None:
            futs = [self.ctx.service.request(
                        r, svc.MSG_KV_GET, dict(meta, all=True), [])
                    for r in range(self.ctx.world)]
        else:
            karr = np.asarray(list(keys), np.int64)
            uk = np.unique(karr)   # dedupe: a key lives on exactly ONE shard
            futs = []
            for r in range(self.ctx.world):
                m = (uk % self.ctx.world) == r
                if m.any():
                    futs.append(self.ctx.service.request(
                        r, svc.MSG_KV_GET, meta, [uk[m]]))
        timeout = config.get_flag("ps_timeout")
        for f in futs:
            _, arrays = svc.await_reply(f, timeout,
                                        f"table[{self.name}] kv get")
            for k, v in zip(arrays[0].tolist(), arrays[1].tolist()):
                out[int(k)] = v   # assignment: shards are disjoint by hash
        if keys is not None:
            return {int(k): out.get(int(k), 0) for k in karr}
        return out

    def __getitem__(self, key: int):
        return self.get([key])[int(key)]

    def store(self, stream) -> None:
        items = sorted(self.get().items())
        np.save(stream, np.array([k for k, _ in items], np.int64),
                allow_pickle=False)
        np.save(stream, np.array([v for _, v in items], np.float64),
                allow_pickle=False)

    def load(self, stream) -> None:
        keys = np.load(stream)
        vals = np.load(stream)
        with self._shard._lock:
            self._shard._store = {}
        # re-add only this rank's hash shard so the global view is restored
        # exactly once
        m = (keys % self.ctx.world) == self.ctx.rank
        if m.any():
            meta = {"table": self.name}
            self.wait(self._track([self.ctx.service.request(
                self.ctx.rank, svc.MSG_KV_ADD, meta,
                [keys[m], vals[m]])], lambda rs: None))
