"""PS wire format: framed messages of JSON meta + raw numpy blobs.

TPU-native equivalent of the reference's message framing
(ref: include/multiverso/message.h:26-69 — 8-int header + vector<Blob>;
serialized into one buffer per send, mpi_net.h:195-216). Here the header is
a fixed struct and each blob is a length-prefixed numpy array (dtype/shape
header + raw bytes, no pickling), so a message deserializes with zero
copies beyond the socket reads. The framing is deliberately simple enough
that a native (C++) transport can speak it; the Python implementation
releases the GIL inside ``recv_into``/``sendall`` so handler threads and
device dispatch overlap.

Frame layout (little-endian)::

    magic   4s   b"MVPS"
    type    u16  message type (service.py MSG_*)
    flags   u16  reserved
    msg_id  i64  request/reply correlation id
    metalen u32  length of the UTF-8 JSON meta dict
    narr    u32  number of numpy blobs
    meta    bytes[metalen]
    narr x: dlen u8, dtype bytes[dlen], ndim u8, shape i64[ndim], raw bytes

Safety: reads are bounded (MAX_META, MAX_BLOB) so a garbage or malicious
peer can't OOM the process with one header.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Dict, List, Sequence, Tuple

import numpy as np

MAGIC = b"MVPS"
_HEADER = struct.Struct("<4sHHqII")
MAX_META = 64 << 20
MAX_BLOB = 4 << 30


class WireError(RuntimeError):
    pass


def to_wire(arr: np.ndarray, wire: str) -> np.ndarray:
    """Payload-side codec for a wire mode ("none" | "bf16"): the ONE place
    wire formats are encoded, shared by client sends and shard replies.
    The receiving side decodes implicitly — ``np.asarray(x, table_dtype)``
    casts back."""
    if wire == "bf16":
        import ml_dtypes
        return np.asarray(arr).astype(ml_dtypes.bfloat16)
    return arr


def _recv_exact(sock: socket.socket, n: int, *, sof: bool = False
                ) -> memoryview:
    """Read exactly ``n`` bytes. ``sof`` (start-of-frame): a timeout with
    ZERO bytes consumed is an idle socket and re-raises as TimeoutError so
    callers may keep the connection; any timeout after bytes were consumed
    desyncs the framing and is fatal (WireError)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            r = sock.recv_into(view[got:], n - got)
        except TimeoutError:
            if sof and got == 0:
                raise
            raise WireError("timeout mid-message (framing lost)") from None
        if r == 0:
            raise WireError("peer closed connection mid-message")
        got += r
    return memoryview(buf)


def encode(msg_type: int, msg_id: int, meta: Dict,
           arrays: Sequence[np.ndarray] = ()) -> bytes:
    meta_b = json.dumps(meta).encode()
    parts: List[bytes] = [
        _HEADER.pack(MAGIC, msg_type, 0, msg_id, len(meta_b), len(arrays)),
        meta_b,
    ]
    for a in arrays:
        # asarray, not ascontiguousarray: the latter promotes 0-d to 1-d,
        # and tobytes() already linearizes non-contiguous layouts
        a = np.asarray(a)
        # custom dtypes (bfloat16 etc.) stringify as '<V2' which does NOT
        # round-trip; their registered NAME does
        ds = a.dtype.str
        if np.dtype(ds) != a.dtype:
            ds = a.dtype.name
        dt = ds.encode()
        parts.append(struct.pack("<B", len(dt)))
        parts.append(dt)
        parts.append(struct.pack("<B", a.ndim))
        parts.append(struct.pack(f"<{a.ndim}q", *a.shape))
        parts.append(a.tobytes())
    return b"".join(parts)


def send(sock: socket.socket, msg_type: int, msg_id: int, meta: Dict,
         arrays: Sequence[np.ndarray] = ()) -> None:
    sock.sendall(encode(msg_type, msg_id, meta, arrays))


def recv(sock: socket.socket) -> Tuple[int, int, Dict, List[np.ndarray]]:
    """Read one message; returns (msg_type, msg_id, meta, arrays).
    Raises TimeoutError (connection still usable) only when the socket was
    idle — i.e. the timeout hit before any byte of a frame arrived."""
    head = _recv_exact(sock, _HEADER.size, sof=True)
    magic, msg_type, _flags, msg_id, metalen, narr = _HEADER.unpack(head)
    if magic != MAGIC:
        raise WireError(f"bad magic {bytes(magic)!r}")
    if metalen > MAX_META:
        raise WireError(f"meta too large ({metalen} bytes)")
    meta = json.loads(bytes(_recv_exact(sock, metalen)) or b"{}")
    arrays: List[np.ndarray] = []
    for _ in range(narr):
        (dlen,) = struct.unpack("<B", _recv_exact(sock, 1))
        dtype = np.dtype(bytes(_recv_exact(sock, dlen)).decode())
        (ndim,) = struct.unpack("<B", _recv_exact(sock, 1))
        shape = struct.unpack(f"<{ndim}q",
                              _recv_exact(sock, 8 * ndim)) if ndim else ()
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if ndim \
            else dtype.itemsize
        if nbytes > MAX_BLOB:
            raise WireError(f"blob too large ({nbytes} bytes)")
        raw = _recv_exact(sock, nbytes)
        arrays.append(np.frombuffer(raw, dtype=dtype).reshape(shape).copy())
    return msg_type, msg_id, meta, arrays
