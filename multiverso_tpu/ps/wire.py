"""PS wire format: framed messages of JSON meta + raw numpy blobs.

TPU-native equivalent of the reference's message framing
(ref: include/multiverso/message.h:26-69 — 8-int header + vector<Blob>;
serialized into one buffer per send, mpi_net.h:195-216). Here the header is
a fixed struct and each blob is a length-prefixed numpy array (dtype/shape
header + raw bytes, no pickling), so a message deserializes with zero
copies beyond the socket reads. The framing is deliberately simple enough
that a native (C++) transport can speak it; the Python implementation
releases the GIL inside ``recv_into``/``sendall`` so handler threads and
device dispatch overlap.

Frame layout (little-endian)::

    magic   4s   b"MVPS"
    type    u16  message type (service.py MSG_*)
    flags   u16  reserved
    msg_id  i64  request/reply correlation id
    metalen u32  length of the UTF-8 JSON meta dict
    narr    u32  number of numpy blobs
    paylen  i64  total bytes after the header (meta + all blobs)
    meta    bytes[metalen]
    narr x: dlen u8, dtype bytes[dlen], ndim u8, shape i64[ndim], raw bytes

``paylen`` exists so a frame body reads in ONE ``recv_into`` — under GIL
contention every socket read pays a GIL reacquisition (measured ~100 us
with a saturated core), so per-field reads made small messages 3-4x more
expensive than their bytes. Arrays decode as zero-copy views into the
frame buffer.

Safety: reads are bounded (MAX_META, MAX_BLOB, MAX_FRAME) so a garbage or
malicious peer can't OOM the process with one header.

Telemetry: a request's trace ID travels in the JSON meta under
:data:`TRACE_META_KEY` (``"tr"``) — an int minted by telemetry/trace.py
at the client, echoed into the serve/apply spans at the owning shard.
MSG_BATCH inner frames each carry their OWN meta (and therefore their own
trace ID), so a windowed multi-op frame preserves per-SUB-OP correlation
end to end (a client-merged group ships one sub-op carrying its first
logical op's ID; the full set rides the client window spans). Absent
key = untraced request (the default); the binary frame layout is
unchanged either way.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Dict, List, Sequence, Tuple

import numpy as np

MAGIC = b"MVPS"
_HEADER = struct.Struct("<4sHHqIIq")
_U8 = struct.Struct("<B")
MAX_META = 64 << 20
MAX_BLOB = 4 << 30
# total-frame sanity bound: must admit legitimate multi-blob frames (a
# checkpoint dump is [keys, rows, every updater-state leaf] in ONE frame),
# so it bounds garbage headers, not real payloads
MAX_FRAME = MAX_META + 8 * MAX_BLOB


class WireError(RuntimeError):
    pass


# JSON-meta key carrying the per-request trace ID (see module docstring)
TRACE_META_KEY = "tr"

# Exactly-once replay meta (elastic failover, docs/FAILOVER.md). A
# windowed add frame (MSG_ADD_ROWS / MSG_BATCH shipped by a replay-
# enabled _SendWindow) stamps its OUTER meta with the sending client's
# identity and a per-(client, table) monotonic sequence number; the
# owning shard dedupes by per-client high-water mark (a frame arriving
# twice — replay racing a late ack, or a survivor re-flushing to a
# restored incarnation — applies exactly once). Replies echo the
# shard's DURABLE (checkpointed) high-water mark for that client, which
# is the client's retention-prune signal. The binary frame layout is
# unchanged; unstamped frames behave exactly as before. The native C++
# server's meta whitelist does not know these keys, so stamped frames
# always punt to the Python handler — dedupe runs under the native
# shard mutex there, one implementation on both wire planes.
REPLAY_CLIENT_KEY = "cl"     # request: client identity string
REPLAY_SEQ_KEY = "seq"       # request: per-(client, table) sequence
REPLAY_DURABLE_KEY = "dseq"  # reply: durable high-water mark for cl
REPLAY_DUP_KEY = "dup"       # reply: frame was a dedup'd duplicate

# Multi-owner super-frame sub-op addressing (MSG_MULTI, ps/spmd.py):
# each inner frame of a super-frame names its OWNING rank here, so the
# receiving process can dispatch it to the right colocated shard. The
# native C++ server's meta whitelist does not know the key — a
# super-frame always punts to Python, like MSG_BATCH. Absent key = the
# receiving rank owns the sub-op.
OWNER_META_KEY = "ow"

# Tenant attribution (telemetry/tenants.py): the effective tenant id of
# the CALLER rides here on add/get/window/pull frames so the owning
# shard can account per-tenant op/byte counters. Stamped ONLY for
# non-default tenants — default traffic keeps the cached meta bytes and
# the native fast path. The native C++ server's meta whitelist does not
# know the key, so stamped frames punt to the Python handler like every
# modern meta key: one accounting implementation on both wire planes.
TENANT_META_KEY = "tn"


def with_trace(meta: Dict, trace) -> Dict:
    """Meta dict + trace ID (no-op passthrough for ``trace=None`` so
    call sites stay branch-free)."""
    if trace is None:
        return meta
    meta = dict(meta)
    meta[TRACE_META_KEY] = trace
    return meta


def with_tenant(meta: Dict, tenant) -> Dict:
    """Meta dict + tenant id (no-op passthrough for the default tenant
    so call sites stay branch-free, mirroring :func:`with_trace`)."""
    if not tenant:
        return meta
    meta = dict(meta)
    meta[TENANT_META_KEY] = tenant
    return meta


ONEBIT_BLOCK = 1024   # per-block scale granularity of the "1bit" wire


class ChunkedReply:
    """A streamed get reply: ``meta`` is the FINAL frame's meta (carries
    ``chunks``/``rows`` so the client knows the stream's shape) and
    ``chunks`` an iterator of ``(chunk_meta, chunk_arrays)`` sub-frames.
    A handler returns one of these instead of a blob list when the
    client asked for a chunk-streamed reply (request meta ``"chunk"``);
    the service sends each sub-frame as ``MSG_REPLY_CHUNK`` under the
    request's msg_id as the iterator yields — so the peer's decode +
    ``out=`` scatter overlaps the network receive — and closes the
    stream with an ordinary ``MSG_REPLY_OK`` carrying ``meta``. An
    exception raised mid-iteration becomes a ``MSG_REPLY_ERR`` like any
    handler failure; the client discards accumulated chunks on ERR."""

    __slots__ = ("meta", "chunks")

    def __init__(self, meta: Dict, chunks):
        self.meta, self.chunks = meta, chunks


def to_wire(arr: np.ndarray, wire: str) -> np.ndarray:
    """Single-blob codec for a wire mode ("none" | "bf16"): shared by
    client sends and shard replies. The receiving side decodes implicitly
    — ``np.asarray(x, table_dtype)`` casts back. Multi-blob modes
    ("1bit") go through :func:`encode_payload`."""
    if wire == "bf16":
        import ml_dtypes
        return np.asarray(arr).astype(ml_dtypes.bfloat16)
    return arr


def encode_payload(arr: np.ndarray, wire: str) -> List[np.ndarray]:
    """The ONE place PS payloads are wire-encoded: an array -> the blob
    list that travels in the frame. "none" -> [arr]; "bf16" -> [bf16];
    "1bit" -> [sign bits, per-block scales] (~29x fewer bytes; matches
    the device codec in ops/wire_codec bit-for-bit, so an encoded frame
    decodes identically at either endpoint — no decode/re-encode hop);
    "topk" -> [i32 idx, f32 vals] of the ~3% largest-|x| entries
    (~16x fewer bytes). 1bit/topk are stateless at THIS layer: error
    feedback (residuals) belongs to the endpoint that owns the stream
    (ps/tables.py for adds)."""
    if wire == "1bit":
        from multiverso_tpu.utils import filters
        bits, scales = filters.onebit_encode_np(
            np.asarray(arr, np.float32).reshape(-1), ONEBIT_BLOCK)
        return [bits, scales]
    if wire == "topk":
        from multiverso_tpu.utils import filters
        idx, vals = filters.topk_encode_np(
            np.asarray(arr, np.float32).reshape(-1))
        return [idx, vals]
    return [to_wire(arr, wire)]


def decode_payload(arrays: Sequence[np.ndarray], wire: str,
                   shape: Tuple[int, ...], dtype) -> np.ndarray:
    """Inverse of :func:`encode_payload` (the other endpoint)."""
    if wire == "1bit":
        from multiverso_tpu.utils import filters
        n = int(np.prod(shape, dtype=np.int64))
        flat = filters.onebit_decode_np(np.asarray(arrays[0]),
                                        np.asarray(arrays[1]), n,
                                        ONEBIT_BLOCK)
        return flat.reshape(shape).astype(dtype, copy=False)
    if wire == "topk":
        from multiverso_tpu.utils import filters
        n = int(np.prod(shape, dtype=np.int64))
        flat = filters.topk_decode_np(arrays[0], arrays[1], n)
        return flat.reshape(shape).astype(dtype, copy=False)
    return np.asarray(arrays[0], dtype).reshape(shape)


def _recv_exact(sock: socket.socket, n: int, *, sof: bool = False
                ) -> memoryview:
    """Read exactly ``n`` bytes. ``sof`` (start-of-frame): a timeout with
    ZERO bytes consumed is an idle socket and re-raises as TimeoutError so
    callers may keep the connection; any timeout after bytes were consumed
    desyncs the framing and is fatal (WireError)."""
    try:
        buf = bytearray(n)
    except MemoryError:
        raise WireError(f"cannot buffer {n}-byte frame") from None
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            r = sock.recv_into(view[got:], n - got)
        except TimeoutError:
            if sof and got == 0:
                raise
            raise WireError("timeout mid-message (framing lost)") from None
        if r == 0:
            raise WireError("peer closed connection mid-message")
        got += r
    return memoryview(buf)


def pack_meta(meta: Dict) -> bytes:
    """Pre-serialize a meta dict. Ops that fan one logical request out to
    many owners serialize the (identical) meta once, not once per peer."""
    return json.dumps(meta).encode()


def _frame_parts(msg_type: int, msg_id: int, meta,
                 arrays: Sequence[np.ndarray]) -> List:
    """Frame as a buffer list (header+meta+per-array header, array bodies
    interleaved as zero-copy memoryviews where the layout allows)."""
    meta_b = meta if isinstance(meta, (bytes, bytearray)) else \
        json.dumps(meta).encode()
    parts: List = [None, meta_b]   # header patched once paylen is known
    paylen = len(meta_b)
    for a in arrays:
        # asarray, not ascontiguousarray: the latter promotes 0-d to 1-d,
        # and the non-contiguous fallback below linearizes via tobytes()
        a = np.asarray(a)
        # custom dtypes (bfloat16 etc.) stringify as '<V2' which does NOT
        # round-trip; their registered NAME does
        ds = a.dtype.str
        if np.dtype(ds) != a.dtype:
            ds = a.dtype.name
        dt = ds.encode()
        head = struct.pack(f"<B{len(dt)}sB{a.ndim}q",
                           len(dt), dt, a.ndim, *a.shape)
        try:   # custom dtypes (bfloat16) and 0-d views can't always export
            body = (a.data.cast("B") if a.flags.c_contiguous
                    else memoryview(a.tobytes()))
        except (ValueError, TypeError):
            body = memoryview(a.tobytes())
        parts.append(head)
        parts.append(body)
        paylen += len(head) + a.nbytes
    parts[0] = _HEADER.pack(MAGIC, msg_type, 0, msg_id, len(meta_b),
                            len(arrays), paylen)
    return parts


def encode(msg_type: int, msg_id: int, meta,
           arrays: Sequence[np.ndarray] = ()) -> bytes:
    return b"".join(bytes(p) if isinstance(p, memoryview) else p
                    for p in _frame_parts(msg_type, msg_id, meta, arrays))


def send(sock: socket.socket, msg_type: int, msg_id: int, meta,
         arrays: Sequence[np.ndarray] = ()) -> None:
    """Send one frame with ``sendmsg`` scatter-gather: array payloads go
    to the kernel straight from their own buffers — no join/tobytes copy
    of the (dominant) data bytes. ``meta`` may be a dict or pre-packed
    ``pack_meta`` bytes."""
    views = [p if isinstance(p, memoryview) else memoryview(p)
             for p in _frame_parts(msg_type, msg_id, meta, arrays)]
    while views:
        sent = sock.sendmsg(views)
        while views and sent >= len(views[0]):   # drop fully-sent parts
            sent -= len(views[0])
            views.pop(0)
        if views and sent:                        # resume mid-part
            views[0] = views[0][sent:]


def recv(sock: socket.socket) -> Tuple[int, int, Dict, List[np.ndarray]]:
    """Read one message; returns (msg_type, msg_id, meta, arrays).
    Raises TimeoutError (connection still usable) only when the socket was
    idle — i.e. the timeout hit before any byte of a frame arrived.
    Arrays are zero-copy views into the frame buffer (each frame owns its
    buffer, so views never alias across messages)."""
    head = _recv_exact(sock, _HEADER.size, sof=True)
    magic, msg_type, _flags, msg_id, metalen, narr, paylen = \
        _HEADER.unpack(head)
    if magic != MAGIC:
        raise WireError(f"bad magic {bytes(magic)!r}")
    if metalen > MAX_META:
        raise WireError(f"meta too large ({metalen} bytes)")
    if paylen < metalen or paylen > MAX_FRAME:
        raise WireError(f"frame length out of bounds ({paylen} bytes)")
    body = _recv_exact(sock, paylen)
    meta, arrays = _parse_body(body, metalen, narr, paylen)
    return msg_type, msg_id, meta, arrays


def parse_frame(frame: bytes) -> Tuple[int, int, Dict, List[np.ndarray]]:
    """Parse one complete frame already in memory (header + body) — the
    entry point for frames handed over by the native transport's punt
    callback (native/mv_ps.cpp). Same validation as :func:`recv`; arrays
    are views into ``frame``, whose immutability/lifetime the views pin."""
    if len(frame) < _HEADER.size:
        raise WireError("short frame")
    magic, msg_type, _flags, msg_id, metalen, narr, paylen = \
        _HEADER.unpack_from(frame)
    if magic != MAGIC:
        raise WireError(f"bad magic {bytes(magic)!r}")
    if metalen > MAX_META or paylen < metalen or paylen > MAX_FRAME:
        raise WireError("frame length out of bounds")
    body = memoryview(frame)[_HEADER.size:]
    if len(body) != paylen:
        raise WireError(f"frame body {len(body)} != paylen {paylen}")
    meta, arrays = _parse_body(body, metalen, narr, paylen)
    return msg_type, msg_id, meta, arrays


# bound on logical sub-ops per MSG_BATCH frame: far above any real send
# window (batch_window_ops defaults to 64), small enough that a garbage
# header can't make the unpack loop spin
MAX_BATCH_OPS = 4096


def pack_batch(subframes: Sequence[bytes]) -> List[np.ndarray]:
    """Pack complete inner frames (each a full :func:`encode` output —
    header + meta + blobs, so every sub-op keeps its own meta and codec
    wire) as the blob list of ONE outer MSG_BATCH frame. Each blob is
    length-prefixed by the ordinary frame layout; the outer frame costs
    one send, one recv, and one reply for the whole window."""
    if not subframes:
        raise WireError("empty batch")
    if len(subframes) > MAX_BATCH_OPS:
        raise WireError(f"batch of {len(subframes)} sub-ops exceeds "
                        f"MAX_BATCH_OPS ({MAX_BATCH_OPS})")
    return [np.frombuffer(f, np.uint8) for f in subframes]


def unpack_batch(arrays: Sequence[np.ndarray]
                 ) -> List[Tuple[int, Dict, List[np.ndarray]]]:
    """Inverse of :func:`pack_batch`: the received blob list back into
    ``(msg_type, meta, arrays)`` sub-ops, in window order. Sub-arrays are
    zero-copy views into the outer frame's buffer (same lifetime rule as
    :func:`recv`). Inner msg_ids are the window indices — correlation
    lives on the OUTER frame; they are only used to name a failing
    sub-op."""
    if len(arrays) > MAX_BATCH_OPS:
        raise WireError(f"batch of {len(arrays)} sub-ops exceeds "
                        f"MAX_BATCH_OPS ({MAX_BATCH_OPS})")
    out = []
    for blob in arrays:
        msg_type, _mid, meta, arrs = parse_frame(np.ascontiguousarray(blob))
        out.append((msg_type, meta, arrs))
    return out


def peek_msg_id(frame: bytes) -> int:
    """msg_id from a frame whose header is known-sane (the native
    transport validates magic/bounds before punting) — lets a server
    send a bound ERR reply even when the BODY fails to parse."""
    if len(frame) < _HEADER.size:
        raise WireError("short frame")
    return _HEADER.unpack_from(frame)[3]


def _parse_body(body, metalen: int, narr: int, paylen: int
                ) -> Tuple[Dict, List[np.ndarray]]:
    try:
        meta = json.loads(bytes(body[:metalen]) or b"{}")
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        # corrupt meta must surface as a WireError like every other
        # malformed-body shape — callers key their fail-fast paths on it
        # (the native plane's _punt replies ERR instead of parking the
        # peer for the full ps_timeout)
        raise WireError(f"malformed meta json: {e}") from None
    arrays: List[np.ndarray] = []
    off = metalen
    try:
        for _ in range(narr):
            (dlen,) = _U8.unpack_from(body, off)
            off += 1
            dtype = np.dtype(bytes(body[off:off + dlen]).decode())
            off += dlen
            (ndim,) = _U8.unpack_from(body, off)
            off += 1
            shape = struct.unpack_from(f"<{ndim}q", body, off) if ndim else ()
            off += 8 * ndim
            if any(d < 0 for d in shape):
                # a negative dim would make count=-1, which frombuffer
                # reads as "the rest of the buffer" — garbage accepted
                # silently and the cursor walked backwards
                raise WireError(f"negative dim in blob shape {shape}")
            count = int(np.prod(shape, dtype=np.int64)) if ndim else 1
            nbytes = count * dtype.itemsize
            if nbytes > MAX_BLOB or off + nbytes > paylen:
                raise WireError(f"blob out of bounds ({nbytes} bytes)")
            arrays.append(np.frombuffer(body, dtype=dtype, count=count,
                                        offset=off).reshape(shape))
            off += nbytes
    except (struct.error, ValueError, TypeError) as e:
        # TypeError: np.dtype() on a garbage dtype string
        raise WireError(f"malformed frame: {e}") from None
    return meta, arrays
