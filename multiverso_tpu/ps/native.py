"""ctypes loader + wrappers for the native async-PS transport (libmv_ps.so).

See native/mv_ps.cpp for what lives in C++ and why. This module is the
thin Python face of it:

* :func:`server_new` / :func:`serve_fd` / :func:`register_shard` — the
  server half, used by :class:`~multiverso_tpu.ps.service.PSService` to
  adopt accepted connections into C++ threads and to register host-backed
  linear shards for zero-Python serving. Messages C++ cannot serve arrive
  back through the punt callback as raw frames.
* :class:`NativeConn` — the client half: counted fire-and-forget adds and
  buffer-filling gets over one persistent connection, with a C++ recv
  thread (no Python wakeup per reply).

Everything degrades gracefully: if the .so is missing it is built on
first use when a toolchain is present (same pattern as native/__init__),
else ``available()`` is False and the pure-Python plane runs unchanged.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Optional, Tuple

import numpy as np

_lib = None
_lock = threading.Lock()
_build_failed = False

# ctypes signature for the punt callback: (conn_id, frame_ptr, frame_len).
# Invoked from a C++ connection thread; ctypes acquires the GIL.
PUNT_CB = ctypes.CFUNCTYPE(None, ctypes.c_uint64,
                           ctypes.POINTER(ctypes.c_ubyte), ctypes.c_int64)


def _try_load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        from multiverso_tpu.native import build_and_load
        lib = build_and_load("libmv_ps.so", "mv_ps.cpp",
                             extra_flags=("-pthread",))
        if lib is None:
            _build_failed = True
            return None
        vp, i64, u64, i32, dbl = (ctypes.c_void_p, ctypes.c_int64,
                                  ctypes.c_uint64, ctypes.c_int,
                                  ctypes.c_double)
        cp, ccp = ctypes.c_char_p, ctypes.c_char_p
        lib.mvps_server_new.restype = vp
        lib.mvps_server_new.argtypes = [PUNT_CB, i32]
        lib.mvps_server_adopt.restype = i32
        lib.mvps_server_adopt.argtypes = [vp, i32]
        lib.mvps_register_shard.restype = vp
        lib.mvps_register_shard.argtypes = [vp, cp, i64, i64, i64, i32,
                                            dbl, vp, vp, i64]
        lib.mvps_unregister_shard.restype = i32
        lib.mvps_unregister_shard.argtypes = [vp, cp]
        lib.mvps_shard_pin_lock.argtypes = [vp]
        lib.mvps_shard_pin_unlock.argtypes = [vp]
        lib.mvps_shard_pin_stats.argtypes = [vp, ctypes.POINTER(u64),
                                             ctypes.POINTER(u64)]
        lib.mvps_shard_pin_free.argtypes = [vp]
        lib.mvps_send_raw.restype = i32
        lib.mvps_send_raw.argtypes = [vp, u64, ctypes.c_char_p, i64]
        lib.mvps_server_close.argtypes = [vp]
        lib.mvps_server_free.argtypes = [vp]
        lib.mvnet_connect.restype = vp
        lib.mvnet_connect.argtypes = [ccp, i32, dbl, dbl]
        lib.mvnet_add.restype = i64
        lib.mvnet_add.argtypes = [vp, i32, ctypes.c_char_p, i64, vp, i64,
                                  vp, i64, cp, vp, i32,
                                  ctypes.POINTER(i64)]
        lib.mvnet_take_add_error.restype = i32
        lib.mvnet_take_add_error.argtypes = [vp, i64, ctypes.c_char_p, i32]
        lib.mvnet_adds_done.restype = i64
        lib.mvnet_adds_done.argtypes = [vp]
        lib.mvnet_adds_issued.restype = i64
        lib.mvnet_adds_issued.argtypes = [vp]
        lib.mvnet_wait_adds.restype = i32
        lib.mvnet_wait_adds.argtypes = [vp, i64, dbl]
        lib.mvnet_get_send.restype = i64
        lib.mvnet_get_send.argtypes = [vp, i32, ctypes.c_char_p, i64, vp,
                                       i64, vp, i64]
        lib.mvnet_get_wait.restype = i32
        lib.mvnet_get_wait.argtypes = [vp, i64, dbl]
        lib.mvnet_get_cancel.argtypes = [vp, i64]
        lib.mvnet_add_fanout.restype = i32
        lib.mvnet_add_fanout.argtypes = [ctypes.POINTER(vp), i32, i32,
                                         i64, ctypes.c_char_p, i64, vp,
                                         i64, vp, i64, cp, i64,
                                         ctypes.POINTER(i64),
                                         ctypes.POINTER(i64)]
        lib.mvnet_get_fanout.restype = i32
        lib.mvnet_get_fanout.argtypes = [ctypes.POINTER(vp), i32, i32,
                                         i64, ctypes.c_char_p, i64, vp,
                                         i64, vp, i64,
                                         ctypes.POINTER(i64)]
        lib.mvnet_dead.restype = i32
        lib.mvnet_dead.argtypes = [vp]
        lib.mvnet_last_error.argtypes = [vp, ctypes.c_char_p, i32]
        lib.mvnet_shutdown.argtypes = [vp]
        lib.mvnet_free.argtypes = [vp]
        _lib = lib
        return _lib


def available() -> bool:
    return _try_load() is not None


# ------------------------------------------------------------------ #
# server half
# ------------------------------------------------------------------ #
def server_new(punt_cb: Callable[[int, bytes], None], rank: int
               ) -> Tuple[int, object]:
    """Create a native server. ``punt_cb(conn_id, frame_bytes)`` receives
    frames C++ couldn't serve (it must reply via :func:`send_raw` or let
    the request time out at the client). Returns ``(handle, keepalive)``
    — the caller must keep ``keepalive`` (the CFUNCTYPE object) alive as
    long as the server exists, or ctypes frees the trampoline under C++."""
    lib = _try_load()
    assert lib is not None

    def _cb(conn_id, ptr, length):
        try:
            punt_cb(int(conn_id), ctypes.string_at(ptr, length))
        except BaseException:   # noqa: BLE001 — C++ can't take exceptions
            pass                # handler already replied ERR where possible

    cfunc = PUNT_CB(_cb)
    handle = lib.mvps_server_new(cfunc, int(rank))
    return handle, cfunc


def serve_fd(server: int, fd: int) -> bool:
    lib = _try_load()
    return lib.mvps_server_adopt(server, fd) == 0


def register_shard(server: int, name: str, lo: int, n: int, ncol: int,
                   data: np.ndarray, sign: float,
                   dirty: Optional[np.ndarray], nworkers: int
                   ) -> Optional[int]:
    """Register a host-backed linear shard for native serving. ``data``
    must be the shard's live, C-contiguous numpy buffer (float32/float64);
    ``dirty`` its bool [nworkers, n] bit matrix or None. The CALLER owns
    both buffers' lifetime (the Python shard object outlives the
    registration via the service's handler reference). Returns a PIN — a
    stable handle to THIS shard object for lock/stats, immune to same-name
    re-registration — or None if the shard can't be served natively. Free
    the pin with :func:`shard_pin_free` when the shard dies."""
    lib = _try_load()
    if data.dtype == np.float32:
        itemsize = 4
    elif data.dtype == np.float64:
        itemsize = 8
    else:
        return None
    if not data.flags.c_contiguous:
        return None
    if dirty is not None and (dirty.dtype != np.bool_
                              or not dirty.flags.c_contiguous):
        return None
    return lib.mvps_register_shard(
        server, name.encode(), lo, n, ncol, itemsize, float(sign),
        data.ctypes.data, dirty.ctypes.data if dirty is not None else None,
        nworkers) or None


def unregister_shard(server: int, name: str) -> None:
    lib = _try_load()
    lib.mvps_unregister_shard(server, name.encode())


def shard_pin_lock(pin: int) -> None:
    _try_load().mvps_shard_pin_lock(pin)


def shard_pin_unlock(pin: int) -> None:
    _try_load().mvps_shard_pin_unlock(pin)


def shard_pin_stats(pin: int) -> Tuple[int, int]:
    lib = _try_load()
    adds = ctypes.c_uint64()
    applies = ctypes.c_uint64()
    lib.mvps_shard_pin_stats(pin, ctypes.byref(adds), ctypes.byref(applies))
    return adds.value, applies.value


def shard_pin_free(pin: int) -> None:
    lib = _lib   # no load/build at interpreter teardown
    if lib is not None:
        lib.mvps_shard_pin_free(pin)


def send_raw(server: int, conn_id: int, frame: bytes) -> bool:
    lib = _try_load()
    return lib.mvps_send_raw(server, conn_id, frame, len(frame)) == 0


def server_close(server: int) -> None:
    lib = _try_load()
    lib.mvps_server_close(server)


def server_free(server: int) -> None:
    lib = _try_load()
    lib.mvps_server_free(server)


# ------------------------------------------------------------------ #
# client half
# ------------------------------------------------------------------ #
class NativeConnError(RuntimeError):
    pass


class NativeConn:
    """One native client connection (counted adds + buffer-filling gets).

    NOT thread-safe at the Python level beyond what the C++ side gives:
    concurrent adds/gets are fine (C++ locks internally); close() must not
    race in-flight calls (the service guards it with its peers lock)."""

    __slots__ = ("_h", "_lib", "closed")

    def __init__(self, addr: str, connect_timeout: float,
                 io_timeout: float):
        lib = _try_load()
        if lib is None:
            raise NativeConnError("libmv_ps.so unavailable")
        host, port = addr.rsplit(":", 1)
        h = lib.mvnet_connect(host.encode(), int(port),
                              float(connect_timeout), float(io_timeout))
        if not h:
            raise NativeConnError(f"cannot connect to {addr}")
        self._h = h
        self._lib = lib
        self.closed = False

    def last_error(self) -> str:
        buf = ctypes.create_string_buffer(512)
        self._lib.mvnet_last_error(self._h, buf, len(buf))
        return buf.value.decode(errors="replace")

    def dead(self) -> bool:
        return self.closed or bool(self._lib.mvnet_dead(self._h))

    def add(self, msg_type: int, meta_b: bytes, ids: Optional[np.ndarray],
            vals: np.ndarray) -> Tuple[int, int]:
        """Counted fire-and-forget add; returns ``(seq, msg_id)`` — seq
        for :meth:`wait_adds` (completion), msg_id for
        :meth:`take_add_error` (this op's own server error, if any).
        ``ids`` (int64, contiguous) may be None for ADD_FULL. Raises on a
        dead connection."""
        if ids is not None:
            assert ids.dtype == np.int64 and ids.flags.c_contiguous
        assert vals.flags.c_contiguous
        ds = vals.dtype.str
        shape = (ctypes.c_int64 * vals.ndim)(*vals.shape)
        seq_out = ctypes.c_int64()
        mid = self._lib.mvnet_add(
            self._h, msg_type, meta_b, len(meta_b),
            ids.ctypes.data if ids is not None else None,
            ids.size if ids is not None else 0,
            vals.ctypes.data, vals.nbytes, ds.encode(), shape, vals.ndim,
            ctypes.byref(seq_out))
        if mid < 0:
            raise NativeConnError(f"native add failed: {self.last_error()}")
        return int(seq_out.value), int(mid)

    def adds_done(self) -> int:
        return int(self._lib.mvnet_adds_done(self._h))

    def adds_issued(self) -> int:
        """Highest add seq issued — read under the C-side issue lock, so
        a flush fence built on it can never under-wait a racing add."""
        return int(self._lib.mvnet_adds_issued(self._h))

    def wait_adds(self, seq: int, timeout: float) -> None:
        """Block until all adds up to ``seq`` are acknowledged. Raises
        TimeoutError or NativeConnError (dead connection). Per-op server
        errors are separate: :meth:`take_add_error`."""
        rc = self._lib.mvnet_wait_adds(self._h, seq, float(timeout))
        if rc == 0:
            return
        if rc == -1:
            raise TimeoutError(f"native adds not acked within {timeout}s")
        raise NativeConnError(self.last_error() or "native add failed")

    def take_add_error(self, msg_id: int) -> Optional[str]:
        """The ERR-reply message for add ``msg_id`` (consumed), or None."""
        buf = ctypes.create_string_buffer(512)
        if self._lib.mvnet_take_add_error(self._h, msg_id, buf, len(buf)):
            return buf.value.decode(errors="replace")
        return None

    def get_send(self, msg_type: int, meta_b: bytes,
                 ids: Optional[np.ndarray], out: np.ndarray) -> int:
        """Dispatch a get whose reply payload fills ``out`` (exact-size
        contiguous buffer). Returns the wait id."""
        if ids is not None:
            assert ids.dtype == np.int64 and ids.flags.c_contiguous
        assert out.flags.c_contiguous and out.flags.writeable
        mid = self._lib.mvnet_get_send(
            self._h, msg_type, meta_b, len(meta_b),
            ids.ctypes.data if ids is not None else None,
            ids.size if ids is not None else 0,
            out.ctypes.data, out.nbytes)
        if mid < 0:
            raise NativeConnError(f"native get failed: {self.last_error()}")
        return int(mid)

    def get_wait(self, mid: int, timeout: float) -> None:
        rc = self._lib.mvnet_get_wait(self._h, mid, float(timeout))
        if rc == 0:
            return
        if rc == -1:
            raise TimeoutError(f"native get: no reply within {timeout}s")
        raise NativeConnError(self.last_error() or "native get failed")

    def get_cancel(self, mid: int) -> None:
        """Drop a pending get; afterwards the recv loop can never touch
        the op's out buffer (abandoned-future safety)."""
        self._lib.mvnet_get_cancel(self._h, mid)

    @property
    def handle(self) -> int:
        return self._h

    def close(self) -> None:
        """Sever the connection (idempotent). The C++ Client is NOT freed
        here — outstanding futures may still call into it (every call on a
        shut-down conn safely reports dead); it's freed when the last
        Python reference drops."""
        if not self.closed:
            self.closed = True
            self._lib.mvnet_shutdown(self._h)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.mvnet_free(self._h)
                self._h = None
        except Exception:   # noqa: BLE001 — interpreter teardown
            pass


def add_fanout(conns, world: int, mod_owner: bool, rows_per: int,
               meta_b: bytes, ids: np.ndarray, vals: np.ndarray):
    """Partition an add batch by owner and send per-owner frames in C.
    ``conns``: one NativeConn or None per rank. Returns
    ``[(rank, conn, seq, mid) | (rank, None, -1, -1)]`` for each rank
    that owns rows (None conn = unreachable/dead: caller fails that
    part). Raises only on caller bugs (owner out of range)."""
    lib = _try_load()
    assert ids.dtype == np.int64 and ids.flags.c_contiguous
    assert vals.flags.c_contiguous and vals.ndim == 2
    handles = (ctypes.c_void_p * world)(
        *[c.handle if c is not None and not c.dead() else None
          for c in conns])
    out_seq = (ctypes.c_int64 * world)()
    out_mid = (ctypes.c_int64 * world)()
    rc = lib.mvnet_add_fanout(
        handles, world, 1 if mod_owner else 0, rows_per,
        meta_b, len(meta_b), ids.ctypes.data, ids.size,
        vals.ctypes.data, vals.strides[0], vals.dtype.str.encode(),
        vals.shape[1], out_seq, out_mid)
    if rc < 0:
        raise ValueError("add_fanout: row owner out of range")
    out = []
    for r in range(world):
        if out_mid[r] == -2:
            continue
        if out_mid[r] == -1:
            out.append((r, None, -1, -1))
        else:
            out.append((r, conns[r], int(out_seq[r]), int(out_mid[r])))
    return out


def get_fanout(conns, world: int, mod_owner: bool, rows_per: int,
               meta_b: bytes, ids: np.ndarray, out: np.ndarray):
    """Per-owner GET_ROWS whose replies scatter into ``out`` (k, ncol) at
    the original batch positions — reassembly happens in the C++ recv
    thread. Same return shape as :func:`add_fanout` (seq slot unused)."""
    lib = _try_load()
    assert ids.dtype == np.int64 and ids.flags.c_contiguous
    assert out.flags.c_contiguous and out.ndim == 2
    assert out.shape[0] == ids.size
    handles = (ctypes.c_void_p * world)(
        *[c.handle if c is not None and not c.dead() else None
          for c in conns])
    out_mid = (ctypes.c_int64 * world)()
    rc = lib.mvnet_get_fanout(
        handles, world, 1 if mod_owner else 0, rows_per,
        meta_b, len(meta_b), ids.ctypes.data, ids.size,
        out.ctypes.data, out.strides[0], out_mid)
    if rc < 0:
        raise ValueError("get_fanout: row owner out of range")
    res = []
    for r in range(world):
        if out_mid[r] == -2:
            continue
        if out_mid[r] == -1:
            res.append((r, None, -1, -1))
        else:
            res.append((r, conns[r], 0, int(out_mid[r])))
    return res
