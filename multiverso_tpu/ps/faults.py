"""Fault-injection wire plane: seeded, declarative, off by default.

Every robustness guarantee built since PR 7 — exactly-once replay,
bounded staleness, failover recovery — was proven against exactly one
fault shape (SIGKILL a shard; overload a replica). This module makes
the *other* degraded states deterministically provokable, at the same
boundaries where they occur in production: the ``_Peer`` client send
path and the ``_serve_conn`` server loop in ``ps/service.py``.

Fault kinds (per-(src, dst) rules, a declarative JSON scenario spec):

* ``drop`` — the frame silently never reaches the wire (the caller's
  timeout is the only signal, like a lossy link);
* ``delay`` — the send sleeps ``delay_ms`` ± ``jitter_ms`` first (a
  slow wire; backpressures senders to that peer like a real one);
* ``duplicate`` — the encoded frame is sent twice (the shard's replay
  sequence channels must dedupe the second apply);
* ``reorder`` — the frame is held back and released AFTER the next
  frame(s) to the same peer, up to ``depth`` held at once (bounded
  reorder; the shard's gap-set channels must apply both exactly once);
* ``partition`` — one-way src→dst: every send raises a synthetic
  connection reset before touching the socket, so the peer is observed
  dead, replay re-arms, and reconnects keep failing until the rule
  deactivates (heal) — the TCP-visible shape of a real partition;
* ``reset`` — one injected connection reset (then traffic resumes on
  the reconnect);
* ``slow_serve`` — the SERVER sleeps ``delay_ms`` before handling a
  data request (a slow rank, not a slow wire);
* ``drop_reply`` — the server handles the request but never sends the
  reply (an ack lost after the apply: the replay plane must dedupe
  the client's retry).

Determinism (the reproducibility contract the chaos bench and the
golden-sequence tests assert): every probabilistic decision is a pure
function of ``(seed, rule index, src, dst, per-pair message index)`` —
no wall clock, no shared RNG stream — so the same seed + spec + the
same per-pair message sequence injects the identical fault sequence,
event for event. Rules gated by a ``phase`` name flip active/inactive
only when the driver calls :func:`set_phase` (explicit, not
wall-clock), keeping phased scenarios reproducible too; ``from_s`` /
``until_s`` wall-clock windows exist for free-running chaos and are
documented as reproducible at scenario granularity only.

Cost discipline (acceptance: ``bench_small_add`` must hold the PR-2
0.03–0.06 ms band with this module compiled in): the plane follows the
flightrec/devstats null-object pattern — module global :data:`PLANE`
is :class:`NullFaultPlane` unless a spec is armed, and every hook site
guards on ``PLANE.armed`` (one global load + one attribute load); with
the flag off no injection codepath is reachable at all.

Observability: every injected fault records ``EV_FAULT_INJECT`` on the
flight-recorder ring (note = the kind), arming/disarming records
``EV_FAULT_PLANE`` — so injected and organic faults are distinguishable
in ``tools/postmortem.py`` timelines (its "injected faults" section
separates them), and a chaos run's dump is self-describing.

Scenario spec (JSON; :func:`load_spec` accepts a path or inline JSON)::

    {"seed": 7,
     "rules": [
       {"kind": "duplicate", "src": 0, "dst": 1, "p": 0.3,
        "msg_types": ["MSG_BATCH", "MSG_ADD_ROWS"]},
       {"kind": "partition", "src": "*", "dst": 1,
        "phase": "partitioned"},
       {"kind": "slow_serve", "rank": 1, "delay_ms": 50, "p": 1.0}]}

Scope: the fault plane hooks the PYTHON wire plane only (the chaos
bench runs ``ps_native=False``); natively-served ops bypass it, the
same documented rule as tracing and the flight recorder.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from multiverso_tpu.telemetry import flightrec as _flight
from multiverso_tpu.utils import config, log

config.define_string(
    "faults_spec", "",
    "chaos scenario spec for the fault-injection wire plane "
    "(ps/faults.py): a JSON file path, or inline JSON when it starts "
    "with '{'. Empty = plane disarmed (the null object; zero "
    "injection codepaths reachable). docs/FAILOVER.md 'Chaos "
    "scenarios'")
config.define_int(
    "faults_seed", 0,
    "seed for the fault plane's deterministic decision streams: the "
    "same seed + spec + per-(src,dst) message sequence injects the "
    "identical fault sequence (a spec's own \"seed\" key wins over "
    "this flag)")

KINDS = ("drop", "delay", "duplicate", "reorder", "partition", "reset",
         "slow_serve", "drop_reply")
_SEND_KINDS = ("drop", "delay", "duplicate", "reorder", "partition",
               "reset")
_SERVE_KINDS = ("slow_serve", "drop_reply")


class InjectedFault(ConnectionResetError):
    """Synthetic connection reset raised at an injected partition /
    reset point. Subclasses ConnectionResetError so the existing
    OSError handling in ``_Peer.request`` treats it exactly like a
    real peer death (that is the point) while postmortems can still
    tell it apart by type name."""


def _msg_type_ids(names) -> Optional[frozenset]:
    """Spec ``msg_types`` (names like "MSG_ADD_ROWS" or raw ints) to an
    id set; None = every type. Lazy service import (service imports
    this module at module scope)."""
    if not names:
        return None
    out = set()
    for n in names:
        if isinstance(n, int):
            out.add(n)
        else:
            from multiverso_tpu.ps import service as svc
            v = getattr(svc, str(n), None)
            if not isinstance(v, int):
                raise ValueError(f"faults spec: unknown msg type {n!r}")
            out.add(v)
    return frozenset(out)


class Rule:
    """One declarative fault rule, validated up front so a typo'd spec
    fails at arm time, not silently mid-chaos."""

    __slots__ = ("idx", "kind", "src", "dst", "p", "msg_types",
                 "delay_ms", "jitter_ms", "depth", "phase", "from_s",
                 "until_s", "count", "max_count")

    def __init__(self, idx: int, spec: Dict[str, Any]):
        self.idx = idx
        self.kind = spec.get("kind")
        if self.kind not in KINDS:
            raise ValueError(f"faults spec rule {idx}: unknown kind "
                             f"{self.kind!r} (one of {KINDS})")
        # slow_serve/drop_reply are server-side: "rank" names the slow
        # rank (the serving side has no peer identity for the client)
        self.src = spec.get("src", "*")
        self.dst = spec.get("dst", spec.get("rank", "*"))
        self.p = float(spec.get("p", 1.0))
        self.msg_types = _msg_type_ids(spec.get("msg_types"))
        self.delay_ms = float(spec.get("delay_ms", 0.0))
        self.jitter_ms = float(spec.get("jitter_ms", 0.0))
        self.depth = max(int(spec.get("depth", 1)), 1)
        self.phase = spec.get("phase")
        self.from_s = spec.get("from_s")
        self.until_s = spec.get("until_s")
        self.max_count = spec.get("max_count")   # None = unbounded
        self.count = 0

    def matches(self, src: int, dst: int, msg_type: int,
                phase: Optional[str], t_s: float) -> bool:
        if self.phase is not None and self.phase != phase:
            return False
        if self.from_s is not None and t_s < self.from_s:
            return False
        if self.until_s is not None and t_s >= self.until_s:
            return False
        if self.src != "*" and int(self.src) != src:
            return False
        if self.dst != "*" and int(self.dst) != dst:
            return False
        if self.msg_types is not None and msg_type not in self.msg_types:
            return False
        if self.max_count is not None and self.count >= self.max_count:
            return False
        return True


def _draw(seed: int, rule_idx: int, src: int, dst: int, n: int) -> float:
    """Deterministic uniform [0,1) from the decision coordinates — a
    fresh, integer-keyed Random per decision so one rule's draws can
    never shift another's (stateful streams would), and int keys so
    PYTHONHASHSEED never enters. Off the hot path by construction (the
    plane is armed)."""
    key = (seed * 1000003) ^ (rule_idx * 8191) ^ (src * 131071) \
        ^ (dst * 524287) ^ (n * 2654435761)
    return random.Random(key).random()


class SendPlan:
    """What the hook site should do with one outbound frame."""

    __slots__ = ("drop", "delay_s", "duplicate", "reorder", "hold_s",
                 "depth", "reset", "kinds")

    def __init__(self):
        self.drop = False
        self.delay_s = 0.0
        self.duplicate = False
        self.reorder = False
        # reorder release valve: a held frame ships after the NEXT
        # frame to the peer or after this long, whichever first — a
        # blocking caller awaiting the held frame's own ack must not
        # deadlock waiting for traffic it is itself the source of
        self.hold_s = 0.025
        # bounded reorder: frames held back at once (the rule's depth,
        # clamped by the hook site's own safety cap)
        self.depth = 1
        self.reset = False
        self.kinds: List[str] = []


class NullFaultPlane:
    """The disarmed plane: hook sites check ``armed`` and never call
    anything else — flag-off keeps every injection codepath
    unreachable (the flightrec/devstats null-object rule)."""

    armed = False

    def stats(self) -> Dict[str, Any]:
        return {}


class FaultPlane:
    """One armed scenario: rules + deterministic per-pair streams +
    the injected-fault log the golden tests compare."""

    armed = True

    def __init__(self, spec: Dict[str, Any],
                 seed: Optional[int] = None, rank: int = 0):
        rules = spec.get("rules")
        if not isinstance(rules, list) or not rules:
            raise ValueError("faults spec: 'rules' must be a non-empty "
                             "list")
        self.rules = [Rule(i, r) for i, r in enumerate(rules)]
        self.seed = int(spec.get("seed", seed if seed is not None
                                 else config.get_flag("faults_seed")))
        self.rank = int(rank)
        self.phase: Optional[str] = spec.get("phase")
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        # per-(src,dst) outbound message index: the determinism axis
        self._msg_n: Dict[Tuple[int, int], int] = {}
        self.counts: Dict[str, int] = {}
        # bounded injected-fault log (the golden-sequence evidence):
        # (pair msg index, kind, src, dst, msg_type)
        self.log: List[Tuple[int, str, int, int, int]] = []
        self._log_cap = 4096

    # ------------------------------------------------------------------ #
    def set_phase(self, phase: Optional[str]) -> None:
        """Flip phase-gated rules (explicit, reproducible — never
        wall-clock). Records the transition on the ring."""
        self.phase = phase
        _flight.record(_flight.EV_FAULT_PLANE,
                       note=f"phase={phase or '-'}")

    def configure(self, rank: int) -> None:
        self.rank = int(rank)

    def _note(self, kind: str, n: int, src: int, dst: int,
              msg_type: int, msg_id: int = -1,
              extra: str = "") -> None:
        """Record one injected fault. Caller holds ``self._lock`` —
        the whole decision loop runs under it, so per-rule counts
        (max_count), the injected log, and the ring events stay
        consistent and deterministic under concurrent senders."""
        self.counts[kind] = self.counts.get(kind, 0) + 1
        if len(self.log) < self._log_cap:
            self.log.append((n, kind, src, dst, msg_type))
        _flight.record(_flight.EV_FAULT_INJECT, peer=dst,
                       msg_type=msg_type, msg_id=msg_id,
                       note=f"{kind}{extra} src={src}")

    # ------------------------------------------------------------------ #
    def plan_send(self, dst: int, msg_type: int, msg_id: int = -1,
                  src: Optional[int] = None) -> Optional[SendPlan]:
        """Decide this outbound frame's fate. ``src`` is the sending
        rank (the peer registry threads it through; in-process
        multi-rank worlds would otherwise all report the last
        configured rank). None = untouched (the overwhelmingly common
        case even under chaos). The per-pair message index advances
        for every frame, matched or not, so rule activation never
        shifts the decision stream."""
        if src is None or src < 0:
            src = self.rank
        t_s = time.monotonic() - self._t0
        plan: Optional[SendPlan] = None
        # ONE lock hold over the whole decision: the per-pair index,
        # every rule's max_count check-and-increment, and the injected
        # log commit together — concurrent senders can neither
        # overshoot a one-shot rule nor interleave the log
        with self._lock:
            n = self._msg_n.get((src, dst), 0)
            self._msg_n[(src, dst)] = n + 1
            matched: List[Tuple[Rule, str]] = []
            for rule in self.rules:
                if rule.kind not in _SEND_KINDS:
                    continue
                if not rule.matches(src, dst, msg_type, self.phase,
                                    t_s):
                    continue
                if rule.p < 1.0 and _draw(self.seed, rule.idx, src,
                                          dst, n) >= rule.p:
                    continue
                rule.count += 1
                if plan is None:
                    plan = SendPlan()
                extra = ""
                if rule.kind in ("drop", "partition"):
                    plan.drop = plan.drop or rule.kind == "drop"
                    plan.reset = plan.reset or rule.kind == "partition"
                elif rule.kind == "delay":
                    j = rule.jitter_ms * (
                        2.0 * _draw(self.seed, rule.idx + 10007, src,
                                    dst, n) - 1.0)
                    d = max(rule.delay_ms + j, 0.0) / 1e3
                    plan.delay_s += d
                    extra = f":{d * 1e3:.1f}ms"
                elif rule.kind == "duplicate":
                    plan.duplicate = True
                elif rule.kind == "reorder":
                    plan.reorder = True
                    plan.depth = max(plan.depth, rule.depth)
                    if rule.delay_ms > 0:
                        plan.hold_s = rule.delay_ms / 1e3
                matched.append((rule, extra))
            if plan is not None:
                # note only the kinds that take EFFECT at the hook site
                # (stats/log/ring are what operators and the golden
                # tests trust): a terminal reset/partition suppresses
                # drop/duplicate/reorder (the frame never ships), a
                # drop suppresses duplicate/reorder, a reorder hold
                # suppresses duplicate (the held frame ships once).
                # Delay always happened — the sleep runs first. The
                # decision DRAWS above are unaffected (per-rule keyed),
                # so suppression never shifts the streams.
                for rule, extra in matched:
                    k = rule.kind
                    if plan.reset and k in ("drop", "duplicate",
                                            "reorder"):
                        continue
                    if plan.drop and k in ("duplicate", "reorder"):
                        continue
                    if plan.reorder and k == "duplicate":
                        continue
                    plan.kinds.append(k)
                    self._note(k, n, src, dst, msg_type, msg_id, extra)
        return plan

    def plan_serve(self, msg_type: int, msg_id: int = -1,
                   rank: Optional[int] = None) -> Tuple[float, bool]:
        """Server-side decision for one received data request:
        (slow-serve sleep seconds, drop the reply?). ``rank`` is the
        SERVING rank (dst; the serve loop threads it through for
        in-process multi-rank worlds); the requester's identity is
        unknown at the conn (src = -1 in the decision coordinates and
        the log)."""
        dst = self.rank if rank is None or rank < 0 else int(rank)
        t_s = time.monotonic() - self._t0
        sleep_s, drop_reply = 0.0, False
        with self._lock:   # same one-hold rule as plan_send
            n = self._msg_n.get((-1, dst), 0)
            self._msg_n[(-1, dst)] = n + 1
            for rule in self.rules:
                if rule.kind not in _SERVE_KINDS:
                    continue
                if not rule.matches(-1, dst, msg_type, self.phase,
                                    t_s):
                    continue
                if rule.p < 1.0 and _draw(self.seed, rule.idx, -1,
                                          dst, n) >= rule.p:
                    continue
                rule.count += 1
                if rule.kind == "slow_serve":
                    j = rule.jitter_ms * (
                        2.0 * _draw(self.seed, rule.idx + 10007, -1,
                                    dst, n) - 1.0)
                    d = max(rule.delay_ms + j, 0.0) / 1e3
                    sleep_s += d
                    self._note("slow_serve", n, -1, dst, msg_type,
                               msg_id, f":{d * 1e3:.1f}ms")
                else:
                    drop_reply = True
                    self._note("drop_reply", n, -1, dst, msg_type,
                               msg_id)
        return sleep_s, drop_reply

    # ------------------------------------------------------------------ #
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"seed": self.seed, "phase": self.phase,
                    "injected": dict(self.counts),
                    "rules": len(self.rules),
                    "logged": len(self.log)}

    def log_snapshot(self) -> List[Tuple[int, str, int, int, int]]:
        with self._lock:
            return list(self.log)


# ---------------------------------------------------------------------- #
# module plane: the null object unless armed
# ---------------------------------------------------------------------- #
NULL = NullFaultPlane()
PLANE: Any = NULL
_arm_lock = threading.Lock()


def enabled() -> bool:
    return PLANE.armed


def load_spec(spec) -> Dict[str, Any]:
    """A dict passes through; a string is inline JSON (starts with
    '{') or a file path."""
    if isinstance(spec, dict):
        return spec
    s = str(spec).strip()
    if s.startswith("{"):
        return json.loads(s)
    with open(s) as f:
        return json.load(f)


def arm(spec, seed: Optional[int] = None,
        rank: Optional[int] = None) -> FaultPlane:
    """Build + bind the process fault plane (replaces any previous
    one). Records the arming on the ring so a chaos run's dump is
    self-describing."""
    global PLANE
    plane = FaultPlane(load_spec(spec), seed=seed,
                       rank=rank if rank is not None else
                       getattr(PLANE, "rank", 0))
    with _arm_lock:
        PLANE = plane
    _flight.record(_flight.EV_FAULT_PLANE,
                   note=f"armed seed={plane.seed} "
                        f"rules={len(plane.rules)}")
    log.info("fault plane armed: %d rules, seed %d", len(plane.rules),
             plane.seed)
    return plane


def disarm() -> None:
    global PLANE
    with _arm_lock:
        was = PLANE
        PLANE = NULL
    if was.armed:
        _flight.record(_flight.EV_FAULT_PLANE, note="disarmed")


def configure(rank: int) -> None:
    """Adopt this process's rank (PSService init) and arm from the
    ``faults_spec`` flag / ``$MV_FAULTS_SPEC`` when set and the plane
    is not already armed — the flag path chaos bench workers use. One
    flag read when disarmed; nothing else runs."""
    if PLANE.armed:
        PLANE.configure(rank)
        return
    spec = config.get_flag("faults_spec") or os.environ.get(
        "MV_FAULTS_SPEC", "")
    if spec:
        try:
            arm(spec, rank=rank)
        except Exception as e:   # noqa: BLE001 — a bad spec must be
            # loud but must not take the service down with it
            log.error("fault plane arm failed (%s: %s); plane stays "
                      "disarmed", type(e).__name__, e)
