"""Mesh-native SPMD data plane: process colocation + stacked shard groups.

Two halves, both opt-in by flag (compiled in everywhere, disarmed by
default — the fault-plane discipline):

**Process colocation registry + fan-out routing** (flag ``ps_fanout``).
Every :class:`~multiverso_tpu.ps.service.PSService` registers here under
``(world key, rank)`` — the world key is the rendezvous identity, so two
independent worlds in one process can never cross-route. With the flag
armed, a client's python-plane request to a COLOCATED rank skips the
localhost socket and dispatches on the client's serial local executor
straight into the owning service's handler (the general form of the
local-rank short-circuit that always existed) — per-(client, owner)
FIFO holds because every routed op of one client rides ONE executor
queue, so read-your-writes and the send-window fences keep their exact
contract. Multi-owner fan-outs coalesce into ONE ``MSG_MULTI``
super-frame per destination process (service._handle_multi dispatches
the sub-ops across the colocated shards), so an N-shard row op costs
one dispatch, not N socket round-trips — the reference's worker-side
``Partition`` fan-out collapsed to its minimum transport cost.

**Stacked shard groups** (flag ``ps_spmd_stack``). Colocated
``RowShard``\\ s of one table stop being N independent lock+jit islands:
their storage pools into ONE ``(S, R, C)`` device array sharded over a
local ``("shards",)`` mesh axis, and the apply/gather paths compile to
ONE per-device SPMD program (ops/spmd_apply.py) that applies every
local shard's pending wave — or serves every shard's row gather — in a
single dispatch. Shards keep their identity (locks, pins, stats,
replay channels, checkpoints all per shard); only the buffer and the
dispatch are pooled. Classic per-shard reads materialize a lazy slab
view (cached per plane epoch; pinned views survive the stack's donated
swaps because a slice is its own buffer). Exotic mutations (set_rows,
whole-table adds, state restores) EVICT the shard back to classic
storage — always-safe, never wrong.

Lock order (everywhere): shard locks BEFORE the plane lock. The plane
never takes a shard lock; admit/evict take every member's lock in
sorted order, then the plane's.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from multiverso_tpu.telemetry import flightrec as _flight
from multiverso_tpu.telemetry import memstats as _memstats
from multiverso_tpu.utils import config, log

config.define_bool(
    "ps_fanout", False,
    "process-coalesced fan-out routing for the async PS: python-plane "
    "requests to COLOCATED ranks (same process, same world) skip the "
    "localhost socket and dispatch in-process, and multi-owner row ops "
    "ship as ONE multi-owner super-frame (MSG_MULTI) per destination "
    "process instead of one frame per shard. Off by default: the wire "
    "benches and chaos planes measure the socket path; tools/"
    "bench_scale.py arms it for the mesh scale curve")
config.define_bool(
    "ps_spmd_stack", False,
    "pool colocated same-table device-backed row shards into one "
    "mesh-sharded (S, rows, cols) stacked array and compile the "
    "apply/gather paths to ONE per-device SPMD program per dispatch "
    "(ops/spmd_apply.py). Engages only for shards that are not "
    "host-numpy mode, not natively registered, not locally sharded, "
    "with a row-local-state updater and no sparse dirty-bit protocol; "
    "anything else keeps the classic per-shard path")


# ---------------------------------------------------------------------- #
# process colocation registry
# ---------------------------------------------------------------------- #
_REG_LOCK = threading.RLock()
# (world key, rank) -> PSService (live services only; close() removes)
_SERVICES: Dict[Tuple[Any, int], Any] = {}
# world key -> {table name -> MeshStack}
_PLANES: Dict[Tuple[Any, str], "MeshStack"] = {}


def proc_key(rendezvous) -> Optional[Tuple]:
    """World identity for colocation decisions: services may only route
    to each other when they share BOTH a process and a rendezvous (two
    independent in-process worlds must never cross-route). ``None`` =
    no rendezvous = single-rank world, nothing to route."""
    if rendezvous is None:
        return None
    d = getattr(rendezvous, "_dir", None)
    if d is not None:
        import os
        return ("file", os.path.abspath(d))
    ns = getattr(rendezvous, "_ns", None)
    if ns is not None:
        return ("jaxkv", ns)
    return ("obj", id(rendezvous))


def register_service(service) -> None:
    key = getattr(service, "_proc_key", None)
    if key is None:
        return
    with _REG_LOCK:
        _SERVICES[(key, service.rank)] = service


def unregister_service(service) -> None:
    key = getattr(service, "_proc_key", None)
    if key is None:
        return
    with _REG_LOCK:
        cur = _SERVICES.get((key, service.rank))
        if cur is service:
            del _SERVICES[(key, service.rank)]


def colocated_service(key, rank: int):
    """The LIVE colocated service for ``(key, rank)``, or None. A closed
    service that never unregistered (crash-shaped teardown) is pruned
    here so routing observes its death like a dead socket would."""
    if key is None:
        return None
    with _REG_LOCK:
        svc = _SERVICES.get((key, int(rank)))
        if svc is None:
            return None
        if getattr(svc, "_closed", False):
            del _SERVICES[(key, int(rank))]
            return None
        return svc


def colocated_ranks(key) -> List[int]:
    if key is None:
        return []
    with _REG_LOCK:
        return sorted(r for (k, r), s in _SERVICES.items()
                      if k == key and not getattr(s, "_closed", False))


def reset_registry() -> None:
    """Test isolation: drop every registration (leaked services keep
    their threads; the registry must not keep routing to them)."""
    with _REG_LOCK:
        _SERVICES.clear()
        _PLANES.clear()


# ---------------------------------------------------------------------- #
# stacked shard groups
# ---------------------------------------------------------------------- #
def shard_eligible(shard) -> bool:
    """Stacked-grouping eligibility — every condition is a documented
    invariant the pooled layout preserves by CONSTRUCTION, everything
    else keeps the classic path (never wrong, only ungrouped):

    * device-backed (``_np_mode`` shards apply with in-place numpy at
      ~20 us — pooling them would ADD a dispatch, and the native C++
      server may hold their raw buffer pointer);
    * not natively registered, not locally device-sharded (the group IS
      the device placement);
    * a ROW_LOCAL_STATE updater (per-row elementwise with row-aligned
      state, so a stacked zero-delta scratch lane is a no-op — adam's
      global step counter would miscount);
    * no sparse dirty-bit protocol (its mask snapshot is coupled to the
      per-shard lock discipline)."""
    from multiverso_tpu.ps.shard import RowShard
    from multiverso_tpu.updaters import ROW_LOCAL_STATE
    return (type(shard) is RowShard
            and not shard._np_mode
            and shard._native_ref is None
            and shard._local_sharding is None
            and shard._dirty is None
            and type(shard.updater) in ROW_LOCAL_STATE)


def try_join(service, table: str, shard) -> Optional["MeshStack"]:
    """Admit ``shard`` to its table's process-wide stacked group when
    the flag is armed and the shard qualifies. Called from
    ``PSService.register_handler`` — the one point where (service,
    table, shard) meet. Returns the plane when the shard ended up
    grouped (it activates at the second member)."""
    key = getattr(service, "_proc_key", None)
    if (key is None or not config.get_flag("ps_spmd_stack")
            or not shard_eligible(shard)):
        return None
    with _REG_LOCK:
        plane = _PLANES.get((key, table))
        if plane is None:
            plane = _PLANES[(key, table)] = MeshStack(table)
    try:
        plane.admit(shard, service)
    except Exception as e:   # noqa: BLE001 — grouping is an optimization
        log.error("spmd: admit of %s shard [%d,%d) failed (%s); shard "
                  "stays classic", table, shard.lo, shard.hi, e)
        return None
    return plane


def release_service(service) -> None:
    """Evict the closing service's shards from their planes (they keep
    working standalone — e.g. for a final failover checkpoint save) and
    drop the service from the routing registry. A plane left with no
    live members is dropped — its stacked device array must not outlive
    the world it served."""
    unregister_service(service)
    key = getattr(service, "_proc_key", None)
    if key is None:
        return
    with _REG_LOCK:
        planes = [(kt, p) for kt, p in _PLANES.items() if kt[0] == key]
    for _kt, p in planes:
        p.release_owner(service)
    with _REG_LOCK:
        for kt, p in planes:
            with p.lock:
                dead = not any(m is not None for m in p.members) \
                    and not p._pending
                if dead:
                    p.stack = None
                    p.ustate = None
                    p._progs.clear()
            if dead and _PLANES.get(kt) is p:
                del _PLANES[kt]


class MeshStack:
    """One table's process-wide stacked shard group (see module doc).

    ``members[slot]`` is the slot's RowShard (None = evicted slot; its
    stack lane goes stale and is simply never addressed again). The
    stack activates at the second admitted member — a lone shard stays
    classic, so single-rank worlds never pay the stacked layout."""

    def __init__(self, table: str):
        self.table = table
        self.lock = threading.RLock()
        # serializes admit/evict/rebuild end to end (OUTERMOST, before
        # any member shard lock): two concurrent admits each capturing
        # the roster and committing a rebuild would otherwise overwrite
        # each other's member list — a shard left pointing at a lane a
        # DIFFERENT shard owns is silent cross-shard corruption
        self._admit_lock = threading.Lock()
        self.members: List[Any] = []      # slot -> shard (or None)
        self._owners: List[Any] = []      # slot -> owning service
        self._pending: List[Tuple[Any, Any]] = []   # pre-activation
        self.stack = None                 # (S, R, C) device array
        self.ustate = None                # tree, leaves (S, ...)
        self.epoch = 0
        self.mesh = None
        self._row_axes = None
        self._padded: Optional[Tuple[int, int]] = None
        self._dtype = None
        self._updater = None
        self._progs: Dict[Any, Any] = {}
        self._slot_applies: Dict[int, int] = {}
        self._slot_waves: Dict[int, Dict[int, int]] = {}
        self._dispatches = 0
        self._registered_mem = False

    # ------------------------------------------------------------------ #
    @property
    def active(self) -> bool:
        return self.stack is not None

    def slot_of(self, shard) -> Optional[int]:
        for i, m in enumerate(self.members):
            if m is shard:
                return i
        return None

    def admit(self, shard, service) -> None:
        """Admit one shard; activates (builds the stack) at 2+ live
        members. Compatibility is checked against the group (same
        dtype/cols/updater type); an incompatible shard stays classic.
        Serialized end to end on ``_admit_lock``: a concurrent admit's
        rebuild committing over a stale roster would strand this
        shard's ``_plane_slot`` on a lane a DIFFERENT shard owns."""
        with self._admit_lock:
            with self.lock:
                have = [s for s in self.members if s is not None]
                ref = have[0] if have else (self._pending[0][0]
                                            if self._pending else None)
                if ref is not None:
                    if (shard.dtype != ref.dtype
                            or shard.num_col != ref.num_col
                            or type(shard.updater)
                            is not type(ref.updater)):
                        raise ValueError("incompatible shard for group "
                                         f"{self.table}")
                if any(s is shard for s in self.members) or any(
                        s is shard for s, _ in self._pending):
                    return
            # activation/growth mutates member storage: take every
            # member shard's lock (sorted by row range — deterministic
            # order), then the plane lock (the global order)
            self._rebuild(extra=[(shard, service)])

    def release_owner(self, service) -> None:
        with self.lock:
            shards = [s for s, o in zip(self.members, self._owners)
                      if s is not None and o is service]
            self._pending = [(s, o) for s, o in self._pending
                             if o is not service]
        for s in shards:
            self.evict(s)

    # ------------------------------------------------------------------ #
    def _locked_members(self, shards):
        import contextlib
        stack = contextlib.ExitStack()
        for s in sorted(shards, key=lambda x: (x.lo, id(x))):
            stack.enter_context(s._lock)
        return stack

    def _rebuild(self, extra: Sequence[Tuple[Any, Any]] = ()) -> None:
        """(Re)build the stacked storage from the members' CURRENT
        state + ``extra`` joiners. Runs with every involved shard's lock
        held (applies quiesced), then the plane lock."""
        import jax

        with self.lock:
            live = [(s, o) for s, o in zip(self.members, self._owners)
                    if s is not None]
            joiners = list(self._pending) + [
                (s, o) for s, o in extra
                if not any(s is m for m, _ in live)]
            roster = live + joiners
        if len(roster) < 2:
            with self.lock:
                self._pending = joiners
            return
        shards = [s for s, _ in roster]
        with self._locked_members(shards):
            with self.lock:
                r_max = max(s._padded[0] for s in shards)
                cols = shards[0].num_col
                dtype = shards[0].dtype

                def _pad_rows(arr, axis):
                    arr = np.asarray(arr)
                    if arr.shape[axis] == r_max:
                        return arr
                    widths = [(0, 0)] * arr.ndim
                    widths[axis] = (0, r_max - arr.shape[axis])
                    return np.pad(arr, widths)

                datas, states = [], []
                for s in shards:
                    # raw storage: a grouped member's _data/_ustate
                    # properties would route back here
                    d = (np.asarray(s._data_raw) if s._plane is None
                         else np.asarray(self._slot_data(s)))
                    datas.append(_pad_rows(d, 0))
                    st = (s._ustate_raw if s._plane is None
                          else self._slot_state(s))
                    leaves, treedef = jax.tree.flatten(st)
                    axes = [s._state_row_axis(l) for l in
                            jax.tree.leaves(st)]
                    states.append((
                        [(_pad_rows(l, ax) if ax >= 0 else np.asarray(l))
                         for l, ax in zip(leaves, axes)], treedef))
                host_stack = np.stack(datas)
                tdef = states[0][1]
                host_state = [np.stack([st[0][i] for st in states])
                              for i in range(len(states[0][0]))]
                mesh = self._make_mesh(len(shards))
                self.mesh = mesh
                self.stack = self._place(host_stack, mesh)
                self.ustate = jax.tree.unflatten(
                    tdef, [self._place(l, mesh) for l in host_state])
                self._padded = (r_max, cols)
                self._dtype = dtype
                self._updater = shards[0].updater
                self._progs.clear()
                self.epoch += 1
                self.members = list(shards)
                self._owners = [o for _, o in roster]
                self._pending = []
                # row-axis tree from the normalized padded shape
                for s in shards:
                    s._padded = (r_max, cols)
                self._row_axes = jax.tree.map(
                    shards[0]._state_row_axis,
                    jax.tree.unflatten(tdef, states[0][0]))
                state_nb = sum(int(l.nbytes) for l in host_state)
                for i, s in enumerate(shards):
                    s._plane = self
                    s._plane_slot = i
                    s._view_cache = None
                    s._ustate_view_cache = None
                    s._data_raw = None
                    s._ustate_raw = None
                    # static ledger share (per-shard memory_stats must
                    # never materialize a view just to report bytes)
                    s._mem_state_bytes = state_nb // len(shards)
                if not self._registered_mem:
                    self._registered_mem = True
                    _memstats.register(f"spmd[{self.table}]", self)
        log.debug("spmd: %s stacked %d shards over %s", self.table,
                  len(shards), "host" if self.mesh is None else
                  f"{self.mesh.devices.size}-device mesh")

    def _make_mesh(self, s: int):
        import jax
        local = jax.local_devices()
        g = min(s, len(local))
        while g > 1 and s % g:
            g -= 1
        if g <= 1:
            return None
        from jax.sharding import Mesh
        return Mesh(np.asarray(local[:g]), ("shards",))

    def _place(self, host, mesh):
        import jax
        import jax.numpy as jnp
        if mesh is None:
            return jnp.asarray(host)
        from jax.sharding import NamedSharding, PartitionSpec as P
        nd = np.ndim(host)
        spec = P("shards", *([None] * (nd - 1)))
        return jax.device_put(host, NamedSharding(mesh, spec))

    # ------------------------------------------------------------------ #
    # per-shard materialized views (classic read paths, checkpoints)
    # ------------------------------------------------------------------ #
    def _slice_prog(self):
        import jax
        fn = self._progs.get("slice")
        if fn is None:
            from multiverso_tpu.ops import spmd_apply
            fn = self._progs["slice"] = spmd_apply.build_slice()
        return fn

    def _slot_data(self, shard):
        """Caller holds the plane lock: the shard's current slab."""
        import numpy as _np
        fn = self._slice_prog()
        return fn(self.stack, _np.int32(shard._plane_slot))

    def _slot_state(self, shard):
        import jax
        import numpy as _np
        fn = self._slice_prog()
        return jax.tree.map(
            lambda l: fn(l, _np.int32(shard._plane_slot)), self.ustate)

    def view(self, shard):
        """The shard's slab as its own device buffer, cached per plane
        epoch (a stack swap invalidates it; pinned old views stay valid
        — a slice is an independent buffer, so the stack's donated
        applies can never touch it)."""
        with self.lock:
            if (shard._view_cache is not None
                    and shard._view_epoch == self.epoch):
                return shard._view_cache
            v = self._slot_data(shard)
            shard._view_cache = v
            shard._view_epoch = self.epoch
            return v

    def ustate_view(self, shard):
        with self.lock:
            cached = shard._ustate_view_cache
            if cached is not None:
                return cached
            v = self._slot_state(shard)
            shard._ustate_view_cache = v
            return v

    def evict(self, shard) -> None:
        """Materialize the shard back to classic per-shard storage (the
        always-safe fallback for exotic mutations and teardown). The
        slot's stack lane goes stale and is never addressed again.
        ``_admit_lock`` first (the outermost admit/evict serializer):
        an eviction racing a concurrent admit's rebuild could otherwise
        be re-admitted from the rebuild's stale roster."""
        with self._admit_lock, shard._lock:
            with self.lock:
                if shard._plane is not self:
                    return
                data = self._slot_data(shard)
                ustate = self._slot_state(shard)
                slot = shard._plane_slot
                shard._data_raw = data
                shard._ustate_raw = ustate
                shard._plane = None
                shard._plane_slot = None
                shard._view_cache = None
                shard._ustate_view_cache = None
                self.members[slot] = None
        log.debug("spmd: %s slot %d evicted to classic storage",
                  self.table, slot)

    # ------------------------------------------------------------------ #
    # the SPMD dispatch paths
    # ------------------------------------------------------------------ #
    def _bucket(self, n: int) -> int:
        """Shared power-of-two bucket for one dispatch round — the same
        shape rule every row path uses (matrix_table._bucket_size), so
        the compiled-program set is bounded and steady state never
        recompiles."""
        from multiverso_tpu.tables.matrix_table import _bucket_size
        return _bucket_size(n, self._padded[0])

    def _apply_prog(self, bucket: int):
        key = ("apply", bucket)
        fn = self._progs.get(key)
        if fn is None:
            from multiverso_tpu.ops import spmd_apply
            fn = self._progs[key] = spmd_apply.build_apply(
                self._updater, self._row_axes, self.mesh)
        return fn

    def _gather_prog(self, bucket: int):
        key = ("gather", bucket)
        fn = self._progs.get(key)
        if fn is None:
            from multiverso_tpu.ops import spmd_apply
            fn = self._progs[key] = spmd_apply.build_gather(self.mesh)
        return fn

    def apply_rows(self, shard, local: np.ndarray, vals: np.ndarray,
                   opt) -> None:
        """Single-shard apply through the stacked program (the classic
        ``_apply_rows`` body of a grouped shard redirects here; caller
        holds the shard's lock — plane lock nests inside, the global
        order)."""
        self.apply_grouped([(shard, local, vals, opt)])

    def apply_grouped(self, entries: Sequence[Tuple[Any, np.ndarray,
                                                    np.ndarray, Any]]
                      ) -> None:
        """Apply one wave ROUND — at most one (ids, vals, opt) per
        member shard — as ONE donated SPMD dispatch. Shards without
        pending work ride along as all-scratch zero-delta lanes (the
        same padding discipline every row path uses). Raises on a
        malformed entry BEFORE dispatch; the program itself is
        conflict-free by construction (per-shard disjoint slabs)."""
        import time as _time
        from multiverso_tpu.ops import spmd_apply
        from multiverso_tpu.telemetry import devstats as _devstats
        from multiverso_tpu.updaters import AddOption

        t0 = _time.perf_counter()
        with self.lock:
            s_count = len(self.members)
            by_slot: Dict[int, Tuple[Any, np.ndarray, np.ndarray, Any]] \
                = {}
            for shard, local, vals, opt in entries:
                if shard._plane is not self:
                    raise RuntimeError(
                        f"{shard.name}: not grouped in this plane")
                slot = shard._plane_slot
                if slot in by_slot:
                    raise RuntimeError(
                        f"{self.table}: two waves for slot {slot} in one "
                        "round")
                by_slot[slot] = (shard, np.asarray(local, np.int64),
                                 np.asarray(vals), opt)
            bucket = self._bucket(max(
                v[1].size for v in by_slot.values()))
            cols = self._padded[1]
            ids = np.empty((s_count, bucket), np.int32)
            dvals = np.zeros((s_count, bucket, cols), self._dtype)
            opts: List[Any] = []
            for slot in range(s_count):
                ent = by_slot.get(slot)
                m = self.members[slot]
                scratch = m.scratch if m is not None else 0
                if ent is None:
                    ids[slot] = scratch
                    opts.append(AddOption())
                    continue
                _, local, vals, opt = ent
                ids[slot, : local.size] = local
                ids[slot, local.size:] = scratch
                dvals[slot, : vals.shape[0]] = vals
                opts.append(opt if opt is not None else AddOption())
            fn = self._apply_prog(bucket)
            scope = _devstats.mesh_scope(self.mesh) \
                if self.mesh is not None else None
            try:
                if scope is not None:
                    scope.__enter__()
                self.stack, self.ustate = fn(
                    self.stack, self.ustate, ids, dvals,
                    spmd_apply.opt_leaves(opts))
            finally:
                if scope is not None:
                    scope.__exit__(None, None, None)
            self.epoch += 1
            self._dispatches += 1
            nbytes = 0
            for slot, (shard, local, vals, _o) in by_slot.items():
                shard._version += 1
                shard._view_cache = None
                shard._ustate_view_cache = None
                self._slot_applies[slot] = \
                    self._slot_applies.get(slot, 0) + 1
                nbytes += vals.nbytes
        ms = (_time.perf_counter() - t0) * 1e3
        for slot, (shard, local, vals, _o) in by_slot.items():
            shard._mon_apply.observe_ms(ms)
        _flight.beat("apply")
        _flight.record(_flight.EV_APPLY, nbytes=nbytes,
                       note=f"spmd ops={len(by_slot)}")

    def gather_grouped(self, pairs: Sequence[Tuple[Any, np.ndarray]]
                       ) -> List[np.ndarray]:
        """Serve every pair's row gather in ONE dispatch; returns the
        per-pair OWNED host row blocks in input order. Ids are
        shard-local and validated by the caller."""
        with self.lock:
            s_count = len(self.members)
            bucket = self._bucket(max(p[1].size for p in pairs))
            ids = np.empty((s_count, bucket), np.int32)
            rows_of: Dict[int, int] = {}
            order: List[Tuple[int, int]] = []
            for shard, local in pairs:
                if shard._plane is not self:
                    raise RuntimeError(
                        f"{shard.name}: not grouped in this plane")
                slot = shard._plane_slot
                if slot in rows_of:
                    raise RuntimeError(
                        f"{self.table}: duplicate gather slot {slot}")
                ids[slot, : local.size] = local
                ids[slot, local.size:] = shard.scratch
                rows_of[slot] = local.size
                order.append((slot, local.size))
            for slot in range(s_count):
                if slot not in rows_of:
                    m = self.members[slot]
                    ids[slot] = m.scratch if m is not None else 0
            fn = self._gather_prog(bucket)
            out = np.asarray(fn(self.stack, ids))
        return [np.ascontiguousarray(out[slot, :n])
                for slot, n in order]

    # ------------------------------------------------------------------ #
    # observability
    # ------------------------------------------------------------------ #
    def stats_for(self, shard) -> Optional[Dict[str, Any]]:
        """The shard's slice of the plane for ``stats()['spmd']``:
        placement (slot -> device) + its share of grouped applies —
        what mvtop's placement panel renders."""
        with self.lock:
            if shard._plane is not self:
                return None
            slot = shard._plane_slot
            total = sum(self._slot_applies.values()) or 0
            mine = self._slot_applies.get(slot, 0)
            if self.mesh is not None:
                # NamedSharding splits the shard axis into CONTIGUOUS
                # blocks: slots [k*S/G, (k+1)*S/G) live on device k
                devs = list(self.mesh.devices.reshape(-1))
                per = max(len(self.members) // len(devs), 1)
                dev = str(devs[min(slot // per, len(devs) - 1)])
            else:
                dev = "host"
            return {
                "group": self.table,
                "slot": slot,
                "members": sum(1 for m in self.members if m is not None),
                "device": dev,
                "applies": mine,
                "apply_share": (round(mine / total, 4) if total else 0.0),
                "dispatches": self._dispatches,
                "stack_bytes": int(getattr(self.stack, "nbytes", 0)),
            }

    def memory_stats(self) -> Dict[str, Any]:
        """Byte-ledger gauges for the pooled storage (the per-shard
        gauges report their slab SHARE; this is the stack itself, incl.
        lanes kept alive by evicted slots)."""
        import jax
        with self.lock:
            stack_nb = int(getattr(self.stack, "nbytes", 0))
            state_nb = sum(int(getattr(l, "nbytes", 0))
                           for l in jax.tree.leaves(self.ustate))
            live = sum(1 for m in self.members if m is not None)
            return {"stack_bytes": stack_nb,
                    "ustate_bytes": state_nb,
                    "slots": len(self.members),
                    "live_slots": live,
                    "dispatches": self._dispatches}
