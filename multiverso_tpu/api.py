"""Public API facade.

TPU-native equivalent of the reference MV_* surface
(ref: include/multiverso/multiverso.h:9-61, src/multiverso.cpp). Snake_case is
the Python-native spelling; ``MV_*`` aliases are provided for drop-in parity
with the reference bindings (ref binding/python/multiverso/api.py).

The Net bind/connect calls (MV_NetBind/MV_NetConnect, ZMQ-without-machinefile
membership) map onto JAX's distributed runtime initialization:
``net_init(coordinator, num_processes, process_id)`` wraps
``jax.distributed.initialize`` — pod/topology discovery replaces explicit
endpoint wiring.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu.utils import config, log
from multiverso_tpu.zoo import Zoo


def init(argv: Optional[List[str]] = None,
         mesh: Optional[jax.sharding.Mesh] = None,
         sync: Optional[bool] = None,
         updater: Optional[str] = None) -> None:
    """ref MV_Init (src/multiverso.cpp:10). Keyword conveniences mirror the
    Python binding's init(sync=...) -> '-sync=true' argv injection
    (ref binding/python/multiverso/api.py:29-34)."""
    if sync is not None:
        config.set_flag("sync", sync)
    if updater is not None:
        config.set_flag("updater_type", updater)
    Zoo.get().start(argv, mesh=mesh)


def shutdown(finalize: bool = True) -> None:
    """ref MV_ShutDown."""
    Zoo.get().stop(finalize)


def barrier() -> None:
    """ref MV_Barrier."""
    Zoo.get().barrier()


def rank() -> int:
    return Zoo.get().rank()


def size() -> int:
    return Zoo.get().size()


def num_workers() -> int:
    return Zoo.get().num_workers()


def num_servers() -> int:
    return Zoo.get().num_servers()


def worker_id() -> int:
    return Zoo.get().worker_id()


def server_id() -> int:
    return Zoo.get().server_id()


def worker_id_to_rank(wid: int) -> int:
    return Zoo.get().worker_id_to_rank(wid)


def server_id_to_rank(sid: int) -> int:
    return Zoo.get().server_id_to_rank(sid)


def mesh() -> jax.sharding.Mesh:
    return Zoo.get().mesh()


def is_master_worker() -> bool:
    """ref binding convention: worker 0 initializes shared values
    (binding/python/multiverso/tables.py:50-57)."""
    return worker_id() == 0


def create_table(option: Any, name: Optional[str] = None):
    """ref MV_CreateTable (multiverso.h:31-37): build from an Option struct and
    barrier afterwards so every process sees the table."""
    if not hasattr(option, "build"):
        raise TypeError(
            f"create_table expects a table Option (ArrayTableOption, "
            f"MatrixTableOption, ...), got {type(option).__name__}: "
            f"{option!r}")
    table = option.build(name) if name is not None else option.build()
    barrier()
    return table


def aggregate(data: Union[np.ndarray, jax.Array], size: Optional[int] = None
              ) -> np.ndarray:
    """ref MV_Aggregate (src/multiverso.cpp, allreduce 'ma' mode): in-place sum
    across workers. On TPU this is one psum over the mesh — the entire
    Bruck/recursive-halving engine (src/net/allreduce_engine.cpp) and its
    topology math collapse into a single XLA AllReduce routed on ICI.

    Single-process: identity (one worker). Multi-process: sums the per-process
    arrays over DCN/ICI via a tiny jitted collective.
    """
    arr = np.asarray(data)
    if size is not None:
        arr = arr.reshape(-1)[:size]
    zoo = Zoo.get()
    if zoo.size() == 1:
        out = arr
    else:
        # ONE device AllReduce (collectives.process_sum) — not allgather +
        # numpy: per-host cost must stay O(size) on a pod, not O(world*size)
        from multiverso_tpu.parallel.collectives import process_sum
        out = process_sum(arr)
    if isinstance(data, np.ndarray):
        # ndarray.flat assigns through views, so non-contiguous inputs
        # (reshape(-1) would silently copy) still get the in-place write.
        data.flat[: out.size] = out.reshape(-1)
        return data
    return out


def net_init(coordinator_address: Optional[str] = None,
             num_processes: Optional[int] = None,
             process_id: Optional[int] = None) -> int:
    """ref MV_NetBind/MV_NetConnect analogue: bring up the multi-controller
    runtime explicitly when not launched under a pod scheduler."""
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        return 0
    except Exception as e:  # pragma: no cover - environment dependent
        log.error("net_init failed: %s", e)
        return -1


# ---- reference Python-binding name parity (ref api.py:54 workers_num —
# the TUTORIAL.md surface a binding user types verbatim) ------------------- #
workers_num = num_workers
servers_num = num_servers

# ---- MV_* parity aliases -------------------------------------------------- #
MV_Init = init
MV_ShutDown = shutdown
MV_Barrier = barrier
MV_Rank = rank
MV_Size = size
MV_NumWorkers = num_workers
MV_NumServers = num_servers
MV_WorkerId = worker_id
MV_ServerId = server_id
MV_WorkerIdToRank = worker_id_to_rank
MV_ServerIdToRank = server_id_to_rank
MV_CreateTable = create_table
MV_Aggregate = aggregate
