// Native transport + serving loop for the async-PS plane.
//
// TPU-native equivalent of the reference's C++ network/server hot path
// (ref: src/net/mpi_net.h:195-216 serialized send; src/server.cpp:36-58
// Server::ProcessAdd/ProcessGet applying row deltas as they arrive;
// src/communicator.cpp:39-48 one recv loop per peer). The Python plane
// (ps/service.py, ps/wire.py) defined the wire format so that "a native
// (C++) transport can speak it" — this file is that transport.
//
// Why it exists: the measured per-message floor of the pure-Python plane
// is ~200 us (framing + GIL reacquisitions + thread wakeups), which caps
// aggregate messages/s on a saturated host and made async-PS throughput
// FALL with world size. Here a message costs a few microseconds:
//
//  * SERVER: accepted connection fds are adopted from Python; each gets a
//    C++ thread that reads frames, serves the hot ops (ADD_ROWS/GET_ROWS/
//    SET_ROWS/ADD_FULL/GET_FULL/PING) on registered host-backed shards
//    with plain row arithmetic — the reference server was exactly this, a
//    C++ `+=` over received rows — and PUNTS anything else (unknown
//    tables, sparse/stale protocol, compressed wires, checkpoint state,
//    stateful updaters) to a Python callback, synchronously, so per-
//    connection FIFO order is preserved for the protocols that rely on it.
//  * CLIENT: framed sends built with writev straight from caller buffers
//    (no Python bytes joins), one C++ recv thread per connection
//    completing counted adds (no per-reply Python wakeup) and copying get
//    replies into caller-provided numpy buffers.
//
// The wire format is wire.py's, byte for byte:
//   header <4sHHqIIq>: magic "MVPS", u16 type, u16 flags, i64 msg_id,
//                      u32 metalen, u32 narr, i64 paylen
//   body: meta JSON, then per blob: u8 dlen, dtype str, u8 ndim,
//         i64 shape[ndim], raw bytes.
//
// Thread-safety contract with Python: a registered shard's buffer is only
// ever mutated under its mvps mutex; Python's punt handlers for the same
// table are wrapped in mvps_shard_lock/unlock by ps/service.py, so C++
// applies and Python applies (bf16 wire, checkpoint restore) serialize on
// the same lock. No GIL is taken anywhere on the hot path.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>
#include <limits.h>

#include <algorithm>
#include <atomic>
#include <cctype>
#include <chrono>
#include <condition_variable>
#include <cstddef>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

#pragma pack(push, 1)
struct WireHeader {
  char magic[4];
  uint16_t type;
  uint16_t flags;
  int64_t msg_id;
  uint32_t metalen;
  uint32_t narr;
  int64_t paylen;
};
#pragma pack(pop)
static_assert(sizeof(WireHeader) == 32, "wire header layout");

constexpr char kMagic[4] = {'M', 'V', 'P', 'S'};
constexpr int64_t kMaxMeta = 64ll << 20;
constexpr int64_t kMaxBlob = 4ll << 30;
constexpr int64_t kMaxFrame = kMaxMeta + 8 * kMaxBlob;

// message types (ps/service.py)
constexpr int MSG_REPLY_OK = 1;
constexpr int MSG_REPLY_ERR = 2;
constexpr int MSG_PING = 0x10;
constexpr int MSG_ADD_ROWS = 0x11;
constexpr int MSG_GET_ROWS = 0x12;
constexpr int MSG_SET_ROWS = 0x13;
constexpr int MSG_ADD_FULL = 0x14;
constexpr int MSG_GET_FULL = 0x15;

// ---------------------------------------------------------------------
// socket helpers
// ---------------------------------------------------------------------
bool recv_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;  // EOF, error, or timeout: connection is done
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_iov(int fd, struct iovec* iov, int cnt) {
  // chunk at IOV_MAX: the row-gather fanout sends one iovec entry per
  // (non-contiguous) table row, which can exceed the kernel limit.
  // sendmsg+MSG_NOSIGNAL, not writev: a peer-closed socket must yield
  // EPIPE, not a SIGPIPE that kills a non-Python embedder outright
  // (Python ignores the signal; a plain C host does not).
  while (cnt > 0) {
    struct msghdr mh = {};
    mh.msg_iov = iov;
    mh.msg_iovlen = static_cast<size_t>(std::min(cnt, IOV_MAX));
    ssize_t r = ::sendmsg(fd, &mh, MSG_NOSIGNAL);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    while (cnt > 0 && static_cast<size_t>(r) >= iov[0].iov_len) {
      r -= iov[0].iov_len;
      ++iov;
      --cnt;
    }
    if (cnt > 0 && r > 0) {
      iov[0].iov_base = static_cast<uint8_t*>(iov[0].iov_base) + r;
      iov[0].iov_len -= r;
    }
  }
  return true;
}

// ---------------------------------------------------------------------
// tiny JSON scanner — just enough for the metas OUR clients emit.
// Anything unexpected sets ok=false and the frame punts to Python.
// ---------------------------------------------------------------------
struct MetaScan {
  bool ok = false;          // parsed, and every key is whitelisted
  std::string table;        // meta["table"]
  std::string wire;         // meta["wire"] (empty = absent)
  bool sparse = false;      // meta["sparse"] truthy (stale-row get)
  int64_t worker_id = -1;   // meta["worker_id"] (sparse protocol)
};

const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
    ++p;
  return p;
}

// returns nullptr on malformed input; else one-past-end of the value
const char* skip_value(const char* p, const char* end, int depth);

const char* parse_string(const char* p, const char* end, std::string* out) {
  if (p >= end || *p != '"') return nullptr;
  ++p;
  while (p < end && *p != '"') {
    if (*p == '\\') {
      ++p;
      if (p >= end) return nullptr;
      // escapes never appear in table names we serve; punt via fail
      return nullptr;
    }
    if (out) out->push_back(*p);
    ++p;
  }
  if (p >= end) return nullptr;
  return p + 1;
}

const char* skip_object(const char* p, const char* end, int depth) {
  if (depth > 8 || p >= end || *p != '{') return nullptr;
  p = skip_ws(p + 1, end);
  if (p < end && *p == '}') return p + 1;
  while (p < end) {
    p = parse_string(p, end, nullptr);
    if (!p) return nullptr;
    p = skip_ws(p, end);
    if (p >= end || *p != ':') return nullptr;
    p = skip_value(skip_ws(p + 1, end), end, depth + 1);
    if (!p) return nullptr;
    p = skip_ws(p, end);
    if (p < end && *p == ',') {
      p = skip_ws(p + 1, end);
      continue;
    }
    if (p < end && *p == '}') return p + 1;
    return nullptr;
  }
  return nullptr;
}

const char* skip_array(const char* p, const char* end, int depth) {
  if (depth > 8 || p >= end || *p != '[') return nullptr;
  p = skip_ws(p + 1, end);
  if (p < end && *p == ']') return p + 1;
  while (p < end) {
    p = skip_value(p, end, depth + 1);
    if (!p) return nullptr;
    p = skip_ws(p, end);
    if (p < end && *p == ',') {
      p = skip_ws(p + 1, end);
      continue;
    }
    if (p < end && *p == ']') return p + 1;
    return nullptr;
  }
  return nullptr;
}

const char* skip_value(const char* p, const char* end, int depth) {
  if (p >= end || depth > 8) return nullptr;
  if (*p == '"') return parse_string(p, end, nullptr);
  if (*p == '{') return skip_object(p, end, depth);
  if (*p == '[') return skip_array(p, end, depth);
  if (!strncmp(p, "true", std::min<ptrdiff_t>(4, end - p)) && end - p >= 4)
    return p + 4;
  if (!strncmp(p, "false", std::min<ptrdiff_t>(5, end - p)) && end - p >= 5)
    return p + 5;
  if (!strncmp(p, "null", std::min<ptrdiff_t>(4, end - p)) && end - p >= 4)
    return p + 4;
  // number
  const char* q = p;
  while (q < end && (isdigit(static_cast<unsigned char>(*q)) || *q == '-' ||
                     *q == '+' || *q == '.' || *q == 'e' || *q == 'E'))
    ++q;
  return q == p ? nullptr : q;
}

// Whitelist scan: natively servable metas contain only {"table", "opt",
// "wire", "sparse", "worker_id"}. "opt" is skipped whole: the native path
// only serves shards whose updaters are opt-INSENSITIVE stateless
// accumulates (registration guarantees it), so its contents cannot
// matter; "sparse"/"worker_id" drive the natively-served stale-row GET
// branch. Any other key ("dump", "all", future extensions) punts the
// frame to Python.
MetaScan scan_meta(const char* p, size_t len) {
  MetaScan m;
  const char* end = p + len;
  p = skip_ws(p, end);
  if (p >= end || *p != '{') return m;
  p = skip_ws(p + 1, end);
  if (p < end && *p == '}') {
    m.ok = true;  // empty meta (PING)
    return m;
  }
  while (p < end) {
    std::string key;
    p = parse_string(p, end, &key);
    if (!p) return m;
    p = skip_ws(p, end);
    if (p >= end || *p != ':') return m;
    p = skip_ws(p + 1, end);
    if (key == "table") {
      p = parse_string(p, end, &m.table);
    } else if (key == "wire") {
      p = parse_string(p, end, &m.wire);
    } else if (key == "opt") {
      p = skip_object(p, end, 0);
    } else if (key == "sparse") {
      // json.dumps(True) -> "true"; anything else punts via parse fail
      if (end - p >= 4 && !strncmp(p, "true", 4)) {
        m.sparse = true;
        p += 4;
      } else if (end - p >= 5 && !strncmp(p, "false", 5)) {
        p += 5;
      } else {
        return m;
      }
    } else if (key == "worker_id") {
      // bounded digit parse: the buffer is NOT null-terminated, so
      // strtoll could walk past `end`
      int64_t v = 0;
      const char* q = p;
      while (q < end && isdigit(static_cast<unsigned char>(*q)) &&
             v < (1ll << 40))
        v = v * 10 + (*q++ - '0');
      if (q == p) return m;   // non-numeric (or negative): punt
      m.worker_id = v;
      p = q;
    } else {
      return m;  // unknown key: punt
    }
    if (!p) return m;
    p = skip_ws(p, end);
    if (p < end && *p == ',') {
      p = skip_ws(p + 1, end);
      continue;
    }
    if (p < end && *p == '}') {
      m.ok = true;
      return m;
    }
    return m;
  }
  return m;
}

// ---------------------------------------------------------------------
// blob parsing/building
// ---------------------------------------------------------------------
struct Blob {
  std::string dtype;         // e.g. "<i8", "<f4"
  std::vector<int64_t> shape;
  const uint8_t* data = nullptr;
  int64_t nbytes = 0;
  int64_t count = 0;
};

// parse blobs from a frame body; returns false on malformed layout
bool parse_blobs(const uint8_t* body, int64_t paylen, uint32_t metalen,
                 uint32_t narr, std::vector<Blob>* out) {
  int64_t off = metalen;
  for (uint32_t i = 0; i < narr; ++i) {
    if (off + 1 > paylen) return false;
    uint8_t dlen = body[off];
    off += 1;
    if (off + dlen + 1 > paylen) return false;
    Blob b;
    b.dtype.assign(reinterpret_cast<const char*>(body + off), dlen);
    off += dlen;
    uint8_t ndim = body[off];
    off += 1;
    if (off + 8ll * ndim > paylen) return false;
    b.count = 1;
    for (int d = 0; d < ndim; ++d) {
      int64_t s;
      memcpy(&s, body + off, 8);
      off += 8;
      if (s < 0) return false;
      // overflow guard: a wrapped count would make nbytes pass the bounds
      // check while the claimed shape promises far more data (the Python
      // parser is protected by reshape(); this port must check itself)
      if (s != 0 && b.count > kMaxBlob / s) return false;
      b.shape.push_back(s);
      b.count *= s;
    }
    // itemsize from the numpy dtype string's trailing digits
    size_t di = 0;
    while (di < b.dtype.size() &&
           !isdigit(static_cast<unsigned char>(b.dtype[di])))
      ++di;
    if (di >= b.dtype.size()) return false;
    int64_t itemsize = atoll(b.dtype.c_str() + di);
    if (itemsize <= 0 || itemsize > 16) return false;
    b.nbytes = b.count * itemsize;
    if (b.nbytes > kMaxBlob || off + b.nbytes > paylen) return false;
    b.data = body + off;
    off += b.nbytes;
    out->push_back(std::move(b));
  }
  return true;
}

// append one blob header to a byte vector
void put_blob_header(std::vector<uint8_t>* v, const char* dtype,
                     const int64_t* shape, int ndim) {
  size_t dlen = strlen(dtype);
  v->push_back(static_cast<uint8_t>(dlen));
  v->insert(v->end(), dtype, dtype + dlen);
  v->push_back(static_cast<uint8_t>(ndim));
  for (int i = 0; i < ndim; ++i) {
    const auto* p = reinterpret_cast<const uint8_t*>(&shape[i]);
    v->insert(v->end(), p, p + 8);
  }
}

void put_header(std::vector<uint8_t>* v, int type, int64_t msg_id,
                uint32_t metalen, uint32_t narr, int64_t paylen) {
  WireHeader h;
  memcpy(h.magic, kMagic, 4);
  h.type = static_cast<uint16_t>(type);
  h.flags = 0;
  h.msg_id = msg_id;
  h.metalen = metalen;
  h.narr = narr;
  h.paylen = paylen;
  const auto* p = reinterpret_cast<const uint8_t*>(&h);
  v->insert(v->end(), p, p + sizeof(h));
}

std::string json_escape(const std::string& s) {
  std::string out;
  for (char c : s) {
    if (c == '"' || c == '\\')
      out.push_back('\\'), out.push_back(c);
    else if (static_cast<unsigned char>(c) < 0x20)
      out += ' ';
    else
      out.push_back(c);
  }
  return out;
}

// ---------------------------------------------------------------------
// server
// ---------------------------------------------------------------------
struct Shard {
  std::string name;
  int64_t lo, n, ncol;
  int itemsize;          // 4 (f32) or 8 (f64)
  std::string dtype;     // "<f4" / "<f8"
  double sign;           // +1 accumulate, -1 sgd
  uint8_t* data;         // numpy buffer, rows (n+pad, ncol), C-contiguous
  uint8_t* dirty;        // bool [nworkers, n] or nullptr
  int64_t nworkers;
  std::mutex mu;
  std::atomic<uint64_t> adds{0}, applies{0};
};

using PuntCb = void (*)(uint64_t conn_id, const uint8_t* frame,
                        int64_t frame_len);

struct SrvConn {
  int fd;
  uint64_t id;
  std::mutex wmu;
  std::thread th;
  // lifecycle: a finished conn SHUTS DOWN its fd but does not close it
  // (closing would let the kernel reuse the fd number while a stale
  // mvps_send_raw still targets it) and stays in the registry until
  // reaped by the next adopt or by server close — both join the thread
  // first, so a SrvConn is never destroyed with a joinable thread.
  std::atomic<bool> done{false};
};

struct Server {
  PuntCb cb;
  int rank;
  std::atomic<bool> closed{false};
  std::mutex smu;  // shard registry
  std::unordered_map<std::string, std::shared_ptr<Shard>> shards;
  std::mutex cmu;  // conn registry
  std::unordered_map<uint64_t, std::shared_ptr<SrvConn>> conns;
  uint64_t next_conn = 1;
};

std::shared_ptr<Shard> find_shard(Server* s, const std::string& name) {
  std::lock_guard<std::mutex> g(s->smu);
  auto it = s->shards.find(name);
  return it == s->shards.end() ? nullptr : it->second;
}

void send_reply(Server* s, const std::shared_ptr<SrvConn>& c, int type,
                int64_t msg_id, const std::string& meta,
                const uint8_t* blob_head, size_t blob_head_len,
                const uint8_t* payload, int64_t payload_len, uint32_t narr) {
  std::vector<uint8_t> head;
  head.reserve(sizeof(WireHeader) + meta.size() + blob_head_len);
  put_header(&head, type, msg_id, static_cast<uint32_t>(meta.size()), narr,
             static_cast<int64_t>(meta.size()) + blob_head_len + payload_len);
  head.insert(head.end(), meta.begin(), meta.end());
  if (blob_head_len)
    head.insert(head.end(), blob_head, blob_head + blob_head_len);
  struct iovec iov[2];
  iov[0].iov_base = head.data();
  iov[0].iov_len = head.size();
  int cnt = 1;
  if (payload_len) {
    iov[1].iov_base = const_cast<uint8_t*>(payload);
    iov[1].iov_len = static_cast<size_t>(payload_len);
    cnt = 2;
  }
  std::lock_guard<std::mutex> g(c->wmu);
  send_iov(c->fd, iov, cnt);  // failure: conn thread will see EOF soon
}

void reply_ok_empty(Server* s, const std::shared_ptr<SrvConn>& c,
                    int64_t msg_id) {
  send_reply(s, c, MSG_REPLY_OK, msg_id, "{}", nullptr, 0, nullptr, 0, 0);
}

void reply_err(Server* s, const std::shared_ptr<SrvConn>& c, int64_t msg_id,
               const std::string& what) {
  std::string meta = "{\"error\": \"" + json_escape(what) + "\"}";
  send_reply(s, c, MSG_REPLY_ERR, msg_id, meta, nullptr, 0, nullptr, 0, 0);
}

// Blob payloads sit at arbitrary offsets inside the frame buffer (the
// meta length decides), so typed access must go through an alignment
// gate: aligned data is used in place, misaligned data is copied once
// into the (max_align'd) scratch vector.
const uint8_t* aligned_blob(const Blob& b, size_t align,
                            std::vector<uint8_t>* scratch) {
  if (reinterpret_cast<uintptr_t>(b.data) % align == 0) return b.data;
  scratch->assign(b.data, b.data + b.nbytes);
  return scratch->data();
}

// localize + bounds-check ids; returns false (and fills err) on violation
bool localize(const Shard& sh, const Blob& ids, std::vector<int64_t>* out,
              std::string* err) {
  out->resize(static_cast<size_t>(ids.count));
  for (int64_t i = 0; i < ids.count; ++i) {
    int64_t id;  // memcpy read: the blob may be misaligned in the frame
    memcpy(&id, ids.data + 8 * i, 8);
    int64_t l = id - sh.lo;
    if (l < 0 || l >= sh.n) {
      *err = "row ids outside shard [" + std::to_string(sh.lo) + ", " +
             std::to_string(sh.lo + sh.n) + ") of " + sh.name;
      return false;
    }
    (*out)[i] = l;
  }
  return true;
}

void mark_dirty(Shard& sh, const std::vector<int64_t>& local) {
  if (!sh.dirty) return;
  for (int64_t w = 0; w < sh.nworkers; ++w) {
    uint8_t* row = sh.dirty + w * sh.n;
    for (int64_t l : local) row[l] = 1;
  }
}

template <typename T>
void apply_add(Shard& sh, const std::vector<int64_t>& local,
               const uint8_t* vals, double sign) {
  const T* v = reinterpret_cast<const T*>(vals);
  T* d = reinterpret_cast<T*>(sh.data);
  const int64_t ncol = sh.ncol;
  if (sign > 0) {
    for (size_t i = 0; i < local.size(); ++i) {
      T* row = d + local[i] * ncol;
      const T* src = v + static_cast<int64_t>(i) * ncol;
      for (int64_t j = 0; j < ncol; ++j) row[j] += src[j];
    }
  } else {
    for (size_t i = 0; i < local.size(); ++i) {
      T* row = d + local[i] * ncol;
      const T* src = v + static_cast<int64_t>(i) * ncol;
      for (int64_t j = 0; j < ncol; ++j) row[j] -= src[j];
    }
  }
}

template <typename T>
void apply_full(Shard& sh, const uint8_t* vals, double sign) {
  const T* v = reinterpret_cast<const T*>(vals);
  T* d = reinterpret_cast<T*>(sh.data);
  const int64_t total = sh.n * sh.ncol;
  if (sign > 0)
    for (int64_t i = 0; i < total; ++i) d[i] += v[i];
  else
    for (int64_t i = 0; i < total; ++i) d[i] -= v[i];
}

// serve one hot frame natively; returns false if it must punt to Python
bool serve_native(Server* s, const std::shared_ptr<SrvConn>& c,
                  const WireHeader& h, const uint8_t* body,
                  std::vector<uint8_t>* scratch) {
  if (h.type == MSG_PING) {
    std::string meta = "{\"rank\": " + std::to_string(s->rank) + "}";
    send_reply(s, c, MSG_REPLY_OK, h.msg_id, meta, nullptr, 0, nullptr, 0,
               0);
    return true;
  }
  if (h.type != MSG_ADD_ROWS && h.type != MSG_GET_ROWS &&
      h.type != MSG_SET_ROWS && h.type != MSG_ADD_FULL &&
      h.type != MSG_GET_FULL)
    return false;
  MetaScan m = scan_meta(reinterpret_cast<const char*>(body), h.metalen);
  if (!m.ok || m.table.empty()) return false;
  if (!m.wire.empty() && m.wire != "none") return false;  // bf16 wire
  auto sh = find_shard(s, m.table);
  if (!sh) return false;  // unregistered table: Python handles (or waits)
  std::vector<Blob> blobs;
  if (!parse_blobs(body, h.paylen, h.metalen, h.narr, &blobs)) return false;

  std::string err;
  std::vector<int64_t> local;
  switch (h.type) {
    case MSG_ADD_ROWS: {
      if (blobs.size() != 2 || blobs[0].dtype != "<i8" ||
          blobs[1].dtype != sh->dtype)
        return false;
      const Blob &ids = blobs[0], &vals = blobs[1];
      if (ids.count == 0 || vals.shape.size() != 2 ||
          vals.shape[0] < ids.count || vals.shape[1] != sh->ncol)
        return false;
      if (!localize(*sh, ids, &local, &err)) {
        reply_err(s, c, h.msg_id, err);
        return true;
      }
      {
        const uint8_t* vdata =
            aligned_blob(vals, static_cast<size_t>(sh->itemsize), scratch);
        std::lock_guard<std::mutex> g(sh->mu);
        if (sh->itemsize == 4)
          apply_add<float>(*sh, local, vdata, sh->sign);
        else
          apply_add<double>(*sh, local, vdata, sh->sign);
        mark_dirty(*sh, local);
      }
      sh->adds.fetch_add(1, std::memory_order_relaxed);
      sh->applies.fetch_add(1, std::memory_order_relaxed);
      reply_ok_empty(s, c, h.msg_id);
      return true;
    }
    case MSG_SET_ROWS: {
      if (blobs.size() != 2 || blobs[0].dtype != "<i8" ||
          blobs[1].dtype != sh->dtype)
        return false;
      const Blob &ids = blobs[0], &vals = blobs[1];
      if (ids.count == 0 || vals.shape.size() != 2 ||
          vals.shape[0] < ids.count || vals.shape[1] != sh->ncol)
        return false;
      if (!localize(*sh, ids, &local, &err)) {
        reply_err(s, c, h.msg_id, err);
        return true;
      }
      {
        std::lock_guard<std::mutex> g(sh->mu);
        for (size_t i = 0; i < local.size(); ++i)
          memcpy(sh->data + local[i] * sh->ncol * sh->itemsize,
                 vals.data + static_cast<int64_t>(i) * sh->ncol *
                                 sh->itemsize,
                 static_cast<size_t>(sh->ncol) * sh->itemsize);
        mark_dirty(*sh, local);
      }
      reply_ok_empty(s, c, h.msg_id);
      return true;
    }
    case MSG_GET_ROWS: {
      if (blobs.size() != 1 || blobs[0].dtype != "<i8") return false;
      const Blob& ids = blobs[0];
      if (ids.count == 0) return false;
      if (m.sparse) {
        // stale-row protocol (ref matrix.cpp:475-572 GetOption.worker_id
        // + stale filter; python twin: RowShard.handle sparse branch):
        // read+clear this worker's dirty bits and reply
        // [mask bool[k], stale rows] — bits and gather under ONE lock
        // hold so the reply is atomic with the bits it cleared.
        if (!sh->dirty) {
          reply_err(s, c, h.msg_id,
                    sh->name + " was not created with num_workers; "
                    "sparse gets need dirty-bit tracking");
          return true;
        }
        if (m.worker_id < 0 || m.worker_id >= sh->nworkers)
          return false;  // odd worker_id: let Python shape the error
        if (!localize(*sh, ids, &local, &err)) {
          reply_err(s, c, h.msg_id, err);
          return true;
        }
        const int64_t rowbytes = sh->ncol * sh->itemsize;
        std::vector<uint8_t> mask(static_cast<size_t>(ids.count));
        int64_t nstale = 0;
        {
          std::lock_guard<std::mutex> g(sh->mu);
          uint8_t* bits = sh->dirty + m.worker_id * sh->n;
          // mask FIRST, clear second: a duplicate id in one request must
          // see the same bit at every occurrence (python-twin parity —
          // its vectorized mask read happens before the clear)
          for (int64_t i = 0; i < ids.count; ++i) {
            mask[i] = bits[local[i]] ? 1 : 0;
            nstale += mask[i];
          }
          for (int64_t i = 0; i < ids.count; ++i) bits[local[i]] = 0;
          scratch->resize(static_cast<size_t>(nstale) * rowbytes);
          int64_t w = 0;
          for (int64_t i = 0; i < ids.count; ++i)
            if (mask[i])
              memcpy(scratch->data() + (w++) * rowbytes,
                     sh->data + local[i] * rowbytes,
                     static_cast<size_t>(rowbytes));
        }
        // reply: blob0 = bool mask (numpy '|b1'), blob1 = stale rows
        std::vector<uint8_t> bh;
        int64_t mshape[1] = {ids.count};
        put_blob_header(&bh, "|b1", mshape, 1);
        bh.insert(bh.end(), mask.begin(), mask.end());
        int64_t rshape[2] = {nstale, sh->ncol};
        put_blob_header(&bh, sh->dtype.c_str(), rshape, 2);
        send_reply(s, c, MSG_REPLY_OK, h.msg_id, "{}", bh.data(),
                   bh.size(), scratch->data(),
                   static_cast<int64_t>(scratch->size()), 2);
        return true;
      }
      if (!localize(*sh, ids, &local, &err)) {
        reply_err(s, c, h.msg_id, err);
        return true;
      }
      const int64_t rowbytes = sh->ncol * sh->itemsize;
      scratch->resize(static_cast<size_t>(ids.count) * rowbytes);
      {
        std::lock_guard<std::mutex> g(sh->mu);
        for (size_t i = 0; i < local.size(); ++i)
          memcpy(scratch->data() + static_cast<int64_t>(i) * rowbytes,
                 sh->data + local[i] * rowbytes,
                 static_cast<size_t>(rowbytes));
      }
      std::vector<uint8_t> bh;
      int64_t shape[2] = {ids.count, sh->ncol};
      put_blob_header(&bh, sh->dtype.c_str(), shape, 2);
      send_reply(s, c, MSG_REPLY_OK, h.msg_id, "{}", bh.data(), bh.size(),
                 scratch->data(),
                 static_cast<int64_t>(scratch->size()), 1);
      return true;
    }
    case MSG_ADD_FULL: {
      if (blobs.size() != 1 || blobs[0].dtype != sh->dtype) return false;
      const Blob& delta = blobs[0];
      if (delta.count != sh->n * sh->ncol) {
        reply_err(s, c, h.msg_id,
                  "cannot reshape delta to shard (" + std::to_string(sh->n) +
                      ", " + std::to_string(sh->ncol) + ")");
        return true;
      }
      {
        const uint8_t* ddata =
            aligned_blob(delta, static_cast<size_t>(sh->itemsize),
                         scratch);
        std::lock_guard<std::mutex> g(sh->mu);
        if (sh->itemsize == 4)
          apply_full<float>(*sh, ddata, sh->sign);
        else
          apply_full<double>(*sh, ddata, sh->sign);
        if (sh->dirty)
          memset(sh->dirty, 1, static_cast<size_t>(sh->nworkers * sh->n));
      }
      sh->adds.fetch_add(1, std::memory_order_relaxed);
      sh->applies.fetch_add(1, std::memory_order_relaxed);
      reply_ok_empty(s, c, h.msg_id);
      return true;
    }
    case MSG_GET_FULL: {
      const int64_t nbytes = sh->n * sh->ncol * sh->itemsize;
      scratch->resize(static_cast<size_t>(nbytes));
      {
        std::lock_guard<std::mutex> g(sh->mu);
        memcpy(scratch->data(), sh->data, static_cast<size_t>(nbytes));
      }
      std::vector<uint8_t> bh;
      int64_t shape[2] = {sh->n, sh->ncol};
      put_blob_header(&bh, sh->dtype.c_str(), shape, 2);
      send_reply(s, c, MSG_REPLY_OK, h.msg_id, "{}", bh.data(), bh.size(),
                 scratch->data(), nbytes, 1);
      return true;
    }
  }
  return false;
}

void serve_conn(Server* s, std::shared_ptr<SrvConn> c) {
  std::vector<uint8_t> frame, scratch;
  while (!s->closed.load(std::memory_order_acquire)) {
    WireHeader h;
    if (!recv_exact(c->fd, &h, sizeof(h))) break;
    if (memcmp(h.magic, kMagic, 4) != 0) break;
    if (h.metalen > kMaxMeta || h.paylen < h.metalen || h.paylen > kMaxFrame)
      break;
    try {
      frame.resize(sizeof(h) + static_cast<size_t>(h.paylen));
    } catch (const std::bad_alloc&) {
      break;  // garbage length field: kill THIS conn, not the process
    }
    memcpy(frame.data(), &h, sizeof(h));
    if (!recv_exact(c->fd, frame.data() + sizeof(h),
                    static_cast<size_t>(h.paylen)))
      break;
    const uint8_t* body = frame.data() + sizeof(h);
    bool served = false;
    try {
      served = serve_native(s, c, h, body, &scratch);
    } catch (const std::bad_alloc&) {
      break;
    }
    if (served) continue;
    // punt: hand the WHOLE frame to Python, synchronously — the callback
    // (which sends its own reply through mvps_send_raw) returns before
    // the next frame is read, preserving per-connection FIFO order
    if (s->cb && !s->closed.load(std::memory_order_acquire))
      s->cb(c->id, frame.data(), static_cast<int64_t>(frame.size()));
  }
  ::shutdown(c->fd, SHUT_RDWR);
  c->done.store(true, std::memory_order_release);
}

// ---------------------------------------------------------------------
// client
// ---------------------------------------------------------------------
struct GetPending {
  uint8_t* out;
  int64_t out_nbytes;   // exact payload size expected (scatter: rows*rowbytes)
  // scatter mode (get fanout): reply row i lands at out + scatter[i]*rowbytes
  // instead of contiguously — the C++ side reassembles the multi-owner
  // reply straight into the caller's full result buffer
  std::vector<int64_t> scatter;
  int64_t rowbytes = 0;
  bool done = false;
  std::string err;  // empty = ok
};

struct Client {
  int fd = -1;
  std::thread rth;
  std::mutex wmu;
  std::mutex mu;
  std::condition_variable cv;
  int64_t next_id = 0;
  int64_t adds_issued = 0, adds_done = 0;
  bool shut = false;     // mvnet_shutdown ran (join happened)
  bool dead = false;
  std::string dead_err;
  std::string last_err;  // last per-op error for mvnet_last_error
  std::unordered_map<int64_t, int64_t> pending_adds;  // msg_id -> seq
  // ERR replies to counted adds, keyed by msg_id so the error binds to
  // exactly the op that failed (a conn is shared across tables; a sticky
  // conn-level error would misattribute). Bounded: an abandoned future
  // must not grow this forever.
  std::unordered_map<int64_t, std::string> add_errors;
  std::unordered_map<int64_t, std::shared_ptr<GetPending>> gets;
};
constexpr size_t kMaxAddErrors = 1024;

// extract meta["error"] from an ERR reply body (meta JSON); falls back to
// the raw meta text
std::string err_from_meta(const uint8_t* body, uint32_t metalen) {
  std::string meta(reinterpret_cast<const char*>(body), metalen);
  size_t k = meta.find("\"error\"");
  if (k == std::string::npos) return meta;
  size_t q1 = meta.find('"', k + 7 + 1);
  if (q1 == std::string::npos) return meta;
  size_t q2 = meta.find('"', q1 + 1);
  if (q2 == std::string::npos) return meta;
  return meta.substr(q1 + 1, q2 - q1 - 1);
}

void client_recv_loop(Client* c) {
  std::vector<uint8_t> body;
  for (;;) {
    WireHeader h;
    if (!recv_exact(c->fd, &h, sizeof(h))) break;
    if (memcmp(h.magic, kMagic, 4) != 0 || h.metalen > kMaxMeta ||
        h.paylen < h.metalen || h.paylen > kMaxFrame)
      break;
    try {
      body.resize(static_cast<size_t>(h.paylen));
    } catch (const std::bad_alloc&) {
      break;  // corrupt length: connection dies, process survives
    }
    if (!recv_exact(c->fd, body.data(), body.size())) break;
    std::unique_lock<std::mutex> lk(c->mu);
    auto ai = c->pending_adds.find(h.msg_id);
    if (ai != c->pending_adds.end()) {
      c->pending_adds.erase(ai);
      ++c->adds_done;
      if (h.type == MSG_REPLY_ERR && c->add_errors.size() < kMaxAddErrors)
        c->add_errors[h.msg_id] = err_from_meta(body.data(), h.metalen);
      c->cv.notify_all();
      continue;
    }
    auto gi = c->gets.find(h.msg_id);
    if (gi != c->gets.end()) {
      // entry stays in the map (the WAITER erases it): erasing here would
      // make a completed-but-not-yet-waited get indistinguishable from an
      // unknown id
      auto gp = gi->second;
      if (h.type == MSG_REPLY_ERR) {
        gp->err = err_from_meta(body.data(), h.metalen);
      } else {
        // reply layout: meta, then ONE blob whose payload must be exactly
        // the caller's buffer size
        std::vector<Blob> blobs;
        if (!parse_blobs(body.data(), h.paylen, h.metalen, h.narr,
                         &blobs) ||
            blobs.size() != 1) {
          gp->err = "malformed get reply";
        } else if (blobs[0].nbytes != gp->out_nbytes) {
          gp->err = "get reply size mismatch (" +
                    std::to_string(blobs[0].nbytes) + " != " +
                    std::to_string(gp->out_nbytes) + " bytes)";
        } else if (!gp->scatter.empty()) {
          // fanout reassembly: reply rows land at their ORIGINAL batch
          // positions in the caller's full buffer (copies under the lock
          // — a timed-out waiter erases the entry under this same lock,
          // so the copy can never race a freed caller buffer)
          const uint8_t* src = blobs[0].data;
          for (size_t i = 0; i < gp->scatter.size(); ++i)
            memcpy(gp->out + gp->scatter[i] * gp->rowbytes,
                   src + static_cast<int64_t>(i) * gp->rowbytes,
                   static_cast<size_t>(gp->rowbytes));
        } else {
          memcpy(gp->out, blobs[0].data,
                 static_cast<size_t>(gp->out_nbytes));
        }
      }
      gp->done = true;
      c->cv.notify_all();
      continue;
    }
    // reply to an op nobody tracks (timed-out get): drop
  }
  std::unique_lock<std::mutex> lk(c->mu);
  c->dead = true;
  c->dead_err = "connection lost";
  for (auto& kv : c->gets) {
    kv.second->err = "connection lost";
    kv.second->done = true;
  }
  c->gets.clear();
  c->pending_adds.clear();
  c->cv.notify_all();
}

// A fully-built request frame: iov entries point into the owned vectors
// and the caller's ids/vals buffers, so a Frame must outlive its send.
// Building is lock-free; only the send itself (and, for counted adds,
// the msg_id patch + seq assignment) happens under wmu.
struct Frame {
  std::vector<uint8_t> head;       // header + meta (+ ids blob header)
  std::vector<uint8_t> vals_head;
  struct iovec iov[4];
  int cnt = 0;
};

void client_build_frame(Frame* f, int type, int64_t msg_id,
                        const uint8_t* meta, int64_t metalen,
                        const int64_t* ids, int64_t k, const uint8_t* vals,
                        int64_t vnbytes, const char* vdtype,
                        const int64_t* vshape, int vndim) {
  uint32_t narr = 0;
  int64_t paylen = metalen;
  std::vector<uint8_t> ids_head;
  if (ids) {
    int64_t shape[1] = {k};
    put_blob_header(&ids_head, "<i8", shape, 1);
    paylen += static_cast<int64_t>(ids_head.size()) + 8 * k;
    ++narr;
  }
  if (vals) {
    put_blob_header(&f->vals_head, vdtype, vshape, vndim);
    paylen += static_cast<int64_t>(f->vals_head.size()) + vnbytes;
    ++narr;
  }
  f->head.reserve(sizeof(WireHeader) + static_cast<size_t>(metalen) +
                  ids_head.size());
  put_header(&f->head, type, msg_id, static_cast<uint32_t>(metalen), narr,
             paylen);
  f->head.insert(f->head.end(), meta, meta + metalen);
  if (ids) f->head.insert(f->head.end(), ids_head.begin(), ids_head.end());
  f->cnt = 0;
  f->iov[f->cnt].iov_base = f->head.data();
  f->iov[f->cnt++].iov_len = f->head.size();
  if (ids) {
    f->iov[f->cnt].iov_base = const_cast<int64_t*>(ids);
    f->iov[f->cnt++].iov_len = static_cast<size_t>(8 * k);
  }
  if (vals) {
    f->iov[f->cnt].iov_base = f->vals_head.data();
    f->iov[f->cnt++].iov_len = f->vals_head.size();
    f->iov[f->cnt].iov_base = const_cast<uint8_t*>(vals);
    f->iov[f->cnt++].iov_len = static_cast<size_t>(vnbytes);
  }
}

void frame_patch_msg_id(Frame* f, int64_t msg_id) {
  memcpy(f->head.data() + offsetof(WireHeader, msg_id), &msg_id,
         sizeof(msg_id));
}

bool client_send_frame(Client* c, int type, int64_t msg_id,
                       const uint8_t* meta, int64_t metalen,
                       const int64_t* ids, int64_t k, const uint8_t* vals,
                       int64_t vnbytes, const char* vdtype,
                       const int64_t* vshape, int vndim) {
  Frame f;
  client_build_frame(&f, type, msg_id, meta, metalen, ids, k, vals,
                     vnbytes, vdtype, vshape, vndim);
  std::lock_guard<std::mutex> g(c->wmu);
  return send_iov(c->fd, f.iov, f.cnt);
}

void client_mark_dead(Client* c, const char* why) {
  std::unique_lock<std::mutex> lk(c->mu);
  c->dead = true;
  c->dead_err = why;
  for (auto& kv : c->gets) {
    kv.second->err = why;
    kv.second->done = true;
  }
  c->gets.clear();
  c->pending_adds.clear();
  c->cv.notify_all();
}

}  // namespace

// ---------------------------------------------------------------------
// C API
// ---------------------------------------------------------------------
extern "C" {

// ------------------------------- server -------------------------------
void* mvps_server_new(PuntCb cb, int rank) {
  auto* s = new Server();
  s->cb = cb;
  s->rank = rank;
  return s;
}

int mvps_server_adopt(void* srv, int fd) {
  auto* s = static_cast<Server*>(srv);
  if (s->closed.load()) {
    ::close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto c = std::make_shared<SrvConn>();
  c->fd = fd;
  {
    std::lock_guard<std::mutex> g(s->cmu);
    // reap finished conns (join first — see SrvConn lifecycle note) so
    // reconnect churn doesn't grow the registry or leak fds
    for (auto it = s->conns.begin(); it != s->conns.end();) {
      if (it->second->done.load(std::memory_order_acquire)) {
        if (it->second->th.joinable()) it->second->th.join();
        ::close(it->second->fd);
        it = s->conns.erase(it);
      } else {
        ++it;
      }
    }
    c->id = s->next_conn++;
    s->conns[c->id] = c;
  }
  c->th = std::thread(serve_conn, s, c);
  return 0;
}

// Returns an opaque PIN (a heap shared_ptr<Shard>*) identifying THIS
// shard object, stable across same-name re-registration and across server
// close — the Python side locks/reads-stats through the pin, never
// through a name lookup that a re-registration could redirect mid-hold.
// Free with mvps_shard_pin_free when the Python shard dies.
void* mvps_register_shard(void* srv, const char* name, long long lo,
                          long long n, long long ncol, int itemsize,
                          double sign, void* data, void* dirty,
                          long long nworkers) {
  if (itemsize != 4 && itemsize != 8) return nullptr;
  auto* s = static_cast<Server*>(srv);
  auto sh = std::make_shared<Shard>();
  sh->name = name;
  sh->lo = lo;
  sh->n = n;
  sh->ncol = ncol;
  sh->itemsize = itemsize;
  sh->dtype = itemsize == 4 ? "<f4" : "<f8";
  sh->sign = sign;
  sh->data = static_cast<uint8_t*>(data);
  sh->dirty = static_cast<uint8_t*>(dirty);
  sh->nworkers = nworkers;
  {
    std::lock_guard<std::mutex> g(s->smu);
    s->shards[name] = sh;  // replace = re-created table with the same name
  }
  return new std::shared_ptr<Shard>(sh);
}

int mvps_unregister_shard(void* srv, const char* name) {
  auto* s = static_cast<Server*>(srv);
  std::lock_guard<std::mutex> g(s->smu);
  return s->shards.erase(name) ? 0 : -1;
}

// Python punt handlers for natively-registered tables wrap themselves in
// this lock so their buffer mutations serialize with C++ applies
void mvps_shard_pin_lock(void* pin) {
  (*static_cast<std::shared_ptr<Shard>*>(pin))->mu.lock();
}

void mvps_shard_pin_unlock(void* pin) {
  (*static_cast<std::shared_ptr<Shard>*>(pin))->mu.unlock();
}

void mvps_shard_pin_stats(void* pin, unsigned long long* adds,
                          unsigned long long* applies) {
  auto& sh = *static_cast<std::shared_ptr<Shard>*>(pin);
  *adds = sh->adds.load();
  *applies = sh->applies.load();
}

void mvps_shard_pin_free(void* pin) {
  delete static_cast<std::shared_ptr<Shard>*>(pin);
}

// raw pre-framed reply bytes from Python (wire.encode output)
int mvps_send_raw(void* srv, unsigned long long conn_id, const void* buf,
                  long long len) {
  auto* s = static_cast<Server*>(srv);
  std::shared_ptr<SrvConn> c;
  {
    std::lock_guard<std::mutex> g(s->cmu);
    auto it = s->conns.find(conn_id);
    if (it == s->conns.end()) return -1;  // conn died: reply dropped
    c = it->second;
  }
  struct iovec iov;
  iov.iov_base = const_cast<void*>(buf);
  iov.iov_len = static_cast<size_t>(len);
  std::lock_guard<std::mutex> g(c->wmu);
  return send_iov(c->fd, &iov, 1) ? 0 : -1;
}

void mvps_server_close(void* srv) {
  auto* s = static_cast<Server*>(srv);
  s->closed.store(true, std::memory_order_release);
  std::vector<std::shared_ptr<SrvConn>> conns;
  {
    std::lock_guard<std::mutex> g(s->cmu);
    for (auto& kv : s->conns) conns.push_back(kv.second);
    s->conns.clear();
  }
  for (auto& c : conns) ::shutdown(c->fd, SHUT_RDWR);
  for (auto& c : conns) {
    if (c->th.joinable()) c->th.join();
    ::close(c->fd);
  }
}

void mvps_server_free(void* srv) {
  auto* s = static_cast<Server*>(srv);
  mvps_server_close(srv);
  delete s;
}

// ------------------------------- client -------------------------------
void* mvnet_connect(const char* host, int port, double conn_timeout,
                    double io_timeout) {
  struct addrinfo hints = {}, *res = nullptr;
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  char portbuf[16];
  snprintf(portbuf, sizeof(portbuf), "%d", port);
  if (getaddrinfo(host, portbuf, &hints, &res) != 0 || !res) return nullptr;
  int fd = -1;
  for (auto* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    struct timeval tv;
    tv.tv_sec = static_cast<long>(conn_timeout);
    tv.tv_usec = static_cast<long>((conn_timeout - tv.tv_sec) * 1e6);
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // io timeout on SEND only: the recv loop must tolerate an idle socket
  // (python _Peer semantics — waiter timeouts bound blocked replies)
  struct timeval tv;
  tv.tv_sec = static_cast<long>(io_timeout);
  tv.tv_usec = static_cast<long>((io_timeout - tv.tv_sec) * 1e6);
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  auto* c = new Client();
  c->fd = fd;
  c->rth = std::thread(client_recv_loop, c);
  return c;
}

long long mvnet_add(void* conn, int msg_type, const void* meta,
                    long long metalen, const int64_t* ids, long long k,
                    const void* vals, long long vnbytes, const char* vdtype,
                    const int64_t* vshape, int vndim,
                    long long* seq_out) {
  auto* c = static_cast<Client*>(conn);
  Frame f;  // built lock-free; msg_id patched in under wmu below
  client_build_frame(&f, msg_type, /*msg_id=*/0,
                     static_cast<const uint8_t*>(meta), metalen, ids, k,
                     static_cast<const uint8_t*>(vals), vnbytes, vdtype,
                     vshape, vndim);
  int64_t msg_id, seq;
  bool sent;
  {
    // seq assignment and the wire write happen under ONE wmu hold: two
    // threads adding concurrently must hit the wire in seq order, or a
    // reply to the later seq would mark the earlier add's future done
    // (adds_done is a plain counter) while its frame is still unsent —
    // result() could then report success before the op's ERR arrives.
    std::lock_guard<std::mutex> wg(c->wmu);
    {
      std::unique_lock<std::mutex> lk(c->mu);
      if (c->dead) return -1;
      msg_id = c->next_id++;
      seq = ++c->adds_issued;
      c->pending_adds[msg_id] = seq;
    }
    frame_patch_msg_id(&f, msg_id);
    sent = send_iov(c->fd, f.iov, f.cnt);
  }
  if (!sent) {
    client_mark_dead(c, "send failed");
    return -1;
  }
  if (seq_out) *seq_out = seq;
  return msg_id;
}

// 1 = an ERR reply was recorded for this add (message copied to buf, entry
// consumed), 0 = none
int mvnet_take_add_error(void* conn, long long msg_id, char* buf,
                         int buflen) {
  auto* c = static_cast<Client*>(conn);
  std::unique_lock<std::mutex> lk(c->mu);
  auto it = c->add_errors.find(msg_id);
  if (it == c->add_errors.end()) return 0;
  snprintf(buf, static_cast<size_t>(buflen), "%s", it->second.c_str());
  c->add_errors.erase(it);
  return 1;
}

long long mvnet_adds_done(void* conn) {
  auto* c = static_cast<Client*>(conn);
  std::unique_lock<std::mutex> lk(c->mu);
  return c->dead ? -1 : c->adds_done;
}

// highest add sequence issued so far — the fence point for order-
// sensitive callers (read under the same lock adds are issued under, so
// it can never lag a completed mvnet_add on any thread)
long long mvnet_adds_issued(void* conn) {
  auto* c = static_cast<Client*>(conn);
  std::unique_lock<std::mutex> lk(c->mu);
  return c->adds_issued;
}

// 0 = ok (all adds up to seq acked; per-op errors are separate — see
// mvnet_take_add_error), -1 = timeout, -3 = connection dead
int mvnet_wait_adds(void* conn, long long seq, double timeout) {
  auto* c = static_cast<Client*>(conn);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout);
  std::unique_lock<std::mutex> lk(c->mu);
  while (c->adds_done < seq && !c->dead) {
    if (c->cv.wait_until(lk, deadline) == std::cv_status::timeout &&
        c->adds_done < seq && !c->dead)
      return -1;
  }
  if (c->adds_done < seq && c->dead) {
    c->last_err = c->dead_err;
    return -3;
  }
  return 0;
}

long long mvnet_get_send(void* conn, int msg_type, const void* meta,
                         long long metalen, const int64_t* ids,
                         long long k, void* out, long long out_nbytes) {
  auto* c = static_cast<Client*>(conn);
  int64_t msg_id;
  auto gp = std::make_shared<GetPending>();
  gp->out = static_cast<uint8_t*>(out);
  gp->out_nbytes = out_nbytes;
  {
    std::unique_lock<std::mutex> lk(c->mu);
    if (c->dead) return -1;
    msg_id = c->next_id++;
    c->gets[msg_id] = gp;
  }
  if (!client_send_frame(c, msg_type, msg_id,
                         static_cast<const uint8_t*>(meta), metalen, ids, k,
                         nullptr, 0, nullptr, nullptr, 0)) {
    client_mark_dead(c, "send failed");
    return -1;
  }
  return msg_id;
}

// 0 = ok (out filled), -1 = timeout (entry dropped; late reply discarded),
// -2 = server error (message via mvnet_last_error), -3 = connection dead
int mvnet_get_wait(void* conn, long long msg_id, double timeout) {
  auto* c = static_cast<Client*>(conn);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout);
  std::unique_lock<std::mutex> lk(c->mu);
  auto it = c->gets.find(msg_id);
  std::shared_ptr<GetPending> gp =
      it == c->gets.end() ? nullptr : it->second;
  if (!gp) {  // unknown id: dead-swept (map cleared on death) or re-waited
    c->last_err = c->dead ? c->dead_err : "unknown get id";
    return -3;
  }
  while (!gp->done) {
    if (c->cv.wait_until(lk, deadline) == std::cv_status::timeout &&
        !gp->done) {
      c->gets.erase(msg_id);  // late reply must not touch the caller's out
      c->last_err = "timeout";
      return -1;
    }
  }
  c->gets.erase(msg_id);
  if (!gp->err.empty()) {
    c->last_err = gp->err;
    return gp->err == "connection lost" ? -3 : -2;
  }
  return 0;
}

// --------------------------- fan-out ops -------------------------------
// Partition a row batch by owner and send per-owner frames, all inside
// C — the per-owner numpy masking/copying on the Python side was ~100 us
// per 1024x128 op at world=8, a large slice of the client CPU budget.
// owner(id) = mod_owner ? id % world : id / rows_per (the two sharding
// rules of the async tables). Row payloads go out as per-row iovec
// entries straight from the caller's batch buffer — no gather copy.
//
// out_mid[r]: -2 = rank r owns no rows of this batch, -1 = rows present
// but conns[r] is NULL/dead or the send failed, >= 0 = msg_id of the
// counted add on conns[r]. out_seq[r] valid when out_mid[r] >= 0.
// Returns the number of ranks with rows.
int mvnet_add_fanout(void** conns, int world, int mod_owner,
                     long long rows_per, const void* meta,
                     long long metalen, const int64_t* ids, long long k,
                     const void* vals, long long rowbytes,
                     const char* vdtype, long long ncol,
                     long long* out_seq, long long* out_mid) {
  std::vector<std::vector<int64_t>> parts(world);
  for (long long i = 0; i < k; ++i) {
    int64_t r = mod_owner ? ids[i] % world : ids[i] / rows_per;
    if (r < 0 || r >= world) return -1;  // caller validated; belt only
    parts[static_cast<size_t>(r)].push_back(i);
  }
  int nranks = 0;
  std::vector<int64_t> owner_ids;
  std::vector<struct iovec> iov;
  for (int r = 0; r < world; ++r) {
    const auto& idx = parts[r];
    if (idx.empty()) {
      out_mid[r] = -2;
      continue;
    }
    ++nranks;
    auto* c = static_cast<Client*>(conns[r]);
    if (!c) {
      out_mid[r] = -1;
      continue;
    }
    const int64_t cnt = static_cast<int64_t>(idx.size());
    owner_ids.resize(static_cast<size_t>(cnt));
    for (int64_t i = 0; i < cnt; ++i) owner_ids[i] = ids[idx[i]];
    // head buffer: header + meta + ids blob header; ids data; vals blob
    // header; then one iovec entry per row of the original buffer. The
    // msg_id is patched in under wmu below — the frame body itself does
    // not depend on it, so the build stays outside the lock.
    std::vector<uint8_t> head, vals_head;
    int64_t ids_shape[1] = {cnt};
    std::vector<uint8_t> ids_head;
    put_blob_header(&ids_head, "<i8", ids_shape, 1);
    int64_t vshape[2] = {cnt, ncol};
    put_blob_header(&vals_head, vdtype, vshape, 2);
    int64_t paylen = metalen + static_cast<int64_t>(ids_head.size()) +
                     8 * cnt + static_cast<int64_t>(vals_head.size()) +
                     cnt * rowbytes;
    put_header(&head, MSG_ADD_ROWS, /*msg_id=*/0,
               static_cast<uint32_t>(metalen), 2, paylen);
    head.insert(head.end(), static_cast<const uint8_t*>(meta),
                static_cast<const uint8_t*>(meta) + metalen);
    head.insert(head.end(), ids_head.begin(), ids_head.end());
    iov.clear();
    iov.push_back({head.data(), head.size()});
    iov.push_back({owner_ids.data(), static_cast<size_t>(8 * cnt)});
    iov.push_back({vals_head.data(), vals_head.size()});
    const auto* vb = static_cast<const uint8_t*>(vals);
    for (int64_t i = 0; i < cnt; ++i)
      iov.push_back({const_cast<uint8_t*>(vb + idx[i] * rowbytes),
                     static_cast<size_t>(rowbytes)});
    int64_t msg_id, seq;
    bool ok;
    {
      // same seq-order-equals-wire-order rule as mvnet_add
      std::lock_guard<std::mutex> g(c->wmu);
      {
        std::unique_lock<std::mutex> lk(c->mu);
        if (c->dead) {
          out_mid[r] = -1;
          continue;
        }
        msg_id = c->next_id++;
        seq = ++c->adds_issued;
        c->pending_adds[msg_id] = seq;
      }
      memcpy(head.data() + offsetof(WireHeader, msg_id), &msg_id,
             sizeof(msg_id));
      ok = send_iov(c->fd, iov.data(), static_cast<int>(iov.size()));
    }
    if (!ok) {
      client_mark_dead(c, "send failed");
      out_mid[r] = -1;
      continue;
    }
    out_mid[r] = msg_id;
    out_seq[r] = seq;
  }
  return nranks;
}

// Get-side fanout: per-owner GET_ROWS requests whose replies SCATTER into
// the caller's full (k, ncol) buffer at the original batch positions —
// the Python-side reassembly (per-part mask writes) disappears.
// out_mid semantics as in mvnet_add_fanout.
int mvnet_get_fanout(void** conns, int world, int mod_owner,
                     long long rows_per, const void* meta,
                     long long metalen, const int64_t* ids, long long k,
                     void* out, long long rowbytes, long long* out_mid) {
  std::vector<std::vector<int64_t>> parts(world);
  for (long long i = 0; i < k; ++i) {
    int64_t r = mod_owner ? ids[i] % world : ids[i] / rows_per;
    if (r < 0 || r >= world) return -1;
    parts[static_cast<size_t>(r)].push_back(i);
  }
  int nranks = 0;
  std::vector<int64_t> owner_ids;
  for (int r = 0; r < world; ++r) {
    const auto& idx = parts[r];
    if (idx.empty()) {
      out_mid[r] = -2;
      continue;
    }
    ++nranks;
    auto* c = static_cast<Client*>(conns[r]);
    if (!c) {
      out_mid[r] = -1;
      continue;
    }
    const int64_t cnt = static_cast<int64_t>(idx.size());
    auto gp = std::make_shared<GetPending>();
    gp->out = static_cast<uint8_t*>(out);
    gp->out_nbytes = cnt * rowbytes;
    gp->rowbytes = rowbytes;
    gp->scatter = idx;  // original positions for the reply rows
    int64_t msg_id;
    {
      std::unique_lock<std::mutex> lk(c->mu);
      if (c->dead) {
        out_mid[r] = -1;
        continue;
      }
      msg_id = c->next_id++;
      c->gets[msg_id] = gp;
    }
    owner_ids.resize(static_cast<size_t>(cnt));
    for (int64_t i = 0; i < cnt; ++i) owner_ids[i] = ids[idx[i]];
    if (!client_send_frame(c, MSG_GET_ROWS, msg_id,
                           static_cast<const uint8_t*>(meta), metalen,
                           owner_ids.data(), cnt, nullptr, 0, nullptr,
                           nullptr, 0)) {
      client_mark_dead(c, "send failed");
      out_mid[r] = -1;
      continue;
    }
    out_mid[r] = msg_id;
  }
  return nranks;
}

// Drop a pending get without waiting: after this returns, the recv loop
// can never write into the caller's out buffer for this op (erase and
// reply-scatter serialize on the same lock). Called when a get future is
// abandoned (e.g. a sibling owner's failure aborted the whole op) so the
// shared out buffer can be safely garbage-collected.
void mvnet_get_cancel(void* conn, long long msg_id) {
  auto* c = static_cast<Client*>(conn);
  std::unique_lock<std::mutex> lk(c->mu);
  c->gets.erase(msg_id);
}

int mvnet_dead(void* conn) {
  auto* c = static_cast<Client*>(conn);
  std::unique_lock<std::mutex> lk(c->mu);
  return c->dead ? 1 : 0;
}

void mvnet_last_error(void* conn, char* buf, int buflen) {
  auto* c = static_cast<Client*>(conn);
  std::unique_lock<std::mutex> lk(c->mu);
  const std::string& e = c->last_err.empty() ? c->dead_err : c->last_err;
  snprintf(buf, static_cast<size_t>(buflen), "%s", e.c_str());
}

// Shutdown and free are split so Python can sever the connection eagerly
// (drop_native_conn, service close) while outstanding op futures still
// hold the Client — every API call on a shut-down Client is safe (it just
// reports dead). mvnet_free runs only when the LAST Python reference
// drops (NativeConn.__del__).
void mvnet_shutdown(void* conn) {
  auto* c = static_cast<Client*>(conn);
  {
    std::unique_lock<std::mutex> lk(c->mu);
    if (c->shut) return;
    c->shut = true;
  }
  ::shutdown(c->fd, SHUT_RDWR);
  if (c->rth.joinable()) c->rth.join();
  // recv loop has exited and marked dead/failed everything pending
}

void mvnet_free(void* conn) {
  auto* c = static_cast<Client*>(conn);
  mvnet_shutdown(conn);
  ::close(c->fd);
  delete c;
}

}  // extern "C"
