// Sanitizer smoke driver for the native async-PS transport (mv_ps.cpp).
//
// Exercises every exported entry point through real sockets and real
// threads so ASan/UBSan (and TSan, target sanitize_ps_tsan) see the
// actual concurrency: a server with a registered shard and a punt
// callback, two client connections doing adds (single + fanout), gets
// (plain, scatter fanout, full), an error reply, a punted message, a
// cancelled get, and a hard connection drop with futures outstanding.
//
// Build/run: make -C multiverso_tpu/native sanitize_ps
// The smoke asserts on VALUES, not just survival: the shard contents
// after the op sequence must equal the arithmetic done.

#include <arpa/inet.h>
#include <assert.h>
#include <netinet/in.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <thread>
#include <vector>

// C API of mv_ps.cpp
extern "C" {
typedef void (*PuntCb)(uint64_t, const uint8_t*, int64_t);
void* mvps_server_new(PuntCb, int);
int mvps_server_adopt(void*, int);
void* mvps_register_shard(void*, const char*, long long, long long,
                          long long, int, double, void*, void*, long long);
void mvps_shard_pin_lock(void*);
void mvps_shard_pin_unlock(void*);
void mvps_shard_pin_stats(void*, unsigned long long*, unsigned long long*);
void mvps_shard_pin_free(void*);
int mvps_send_raw(void*, unsigned long long, const void*, long long);
void mvps_server_close(void*);
void mvps_server_free(void*);
void* mvnet_connect(const char*, int, double, double);
long long mvnet_add(void*, int, const void*, long long, const int64_t*,
                    long long, const void*, long long, const char*,
                    const int64_t*, int, long long*);
int mvnet_take_add_error(void*, long long, char*, int);
long long mvnet_adds_done(void*);
long long mvnet_adds_issued(void*);
int mvnet_wait_adds(void*, long long, double);
long long mvnet_get_send(void*, int, const void*, long long,
                         const int64_t*, long long, void*, long long);
int mvnet_get_wait(void*, long long, double);
void mvnet_get_cancel(void*, long long);
int mvnet_add_fanout(void**, int, int, long long, const void*, long long,
                     const int64_t*, long long, const void*, long long,
                     const char*, long long, long long*, long long*);
int mvnet_get_fanout(void**, int, int, long long, const void*, long long,
                     const int64_t*, long long, void*, long long,
                     long long*);
int mvnet_dead(void*);
void mvnet_last_error(void*, char*, int);
void mvnet_shutdown(void*);
void mvnet_free(void*);
}

namespace {

std::atomic<int> g_punts{0};
void* g_server = nullptr;

// minimal wire constants (must match mv_ps.cpp / wire.py)
#pragma pack(push, 1)
struct Hdr {
  char magic[4];
  uint16_t type, flags;
  int64_t msg_id;
  uint32_t metalen, narr;
  int64_t paylen;
};
#pragma pack(pop)

void punt_cb(uint64_t conn_id, const uint8_t* frame, int64_t len) {
  // reply ERR to whatever punted (exercises mvps_send_raw from a foreign
  // thread, the path Python's handler reply takes)
  assert(len >= (int64_t)sizeof(Hdr));
  Hdr h;
  memcpy(&h, frame, sizeof(h));
  ++g_punts;
  const char* meta = "{\"error\": \"smoke punt\"}";
  Hdr r;
  memcpy(r.magic, "MVPS", 4);
  r.type = 2;  // MSG_REPLY_ERR
  r.flags = 0;
  r.msg_id = h.msg_id;
  r.metalen = (uint32_t)strlen(meta);
  r.narr = 0;
  r.paylen = (int64_t)strlen(meta);
  std::vector<uint8_t> buf(sizeof(r) + strlen(meta));
  memcpy(buf.data(), &r, sizeof(r));
  memcpy(buf.data() + sizeof(r), meta, strlen(meta));
  mvps_send_raw(g_server, conn_id, buf.data(), (long long)buf.size());
}

int listen_and_adopt(void* srv, int* port_out) {
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  assert(lfd >= 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in a = {};
  a.sin_family = AF_INET;
  a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  a.sin_port = 0;
  assert(bind(lfd, (sockaddr*)&a, sizeof(a)) == 0);
  assert(listen(lfd, 16) == 0);
  socklen_t alen = sizeof(a);
  assert(getsockname(lfd, (sockaddr*)&a, &alen) == 0);
  *port_out = ntohs(a.sin_port);
  std::thread([srv, lfd] {
    for (;;) {
      int fd = accept(lfd, nullptr, nullptr);
      if (fd < 0) return;
      if (mvps_server_adopt(srv, fd) != 0) return;
    }
  }).detach();
  return lfd;
}

}  // namespace

int main() {
  const long long N = 64, C = 8;
  std::vector<float> shard_data((N + 1) * C, 0.f);
  std::vector<uint8_t> dirty(2 * N, 0);

  g_server = mvps_server_new(punt_cb, /*rank=*/0);
  void* pin = mvps_register_shard(g_server, "t", /*lo=*/0, N, C,
                                  /*itemsize=*/4, /*sign=*/1.0,
                                  shard_data.data(), dirty.data(),
                                  /*nworkers=*/2);
  assert(pin);
  int port = 0;
  int lfd = listen_and_adopt(g_server, &port);

  void* c1 = mvnet_connect("127.0.0.1", port, 5.0, 10.0);
  void* c2 = mvnet_connect("127.0.0.1", port, 5.0, 10.0);
  assert(c1 && c2);

  const char* meta = "{\"table\": \"t\"}";
  int64_t ids[4] = {1, 5, 9, 13};
  int64_t ids_mixed[4] = {1, 2, 5, 8};   // both mod-2 owners
  float vals[4 * C];
  for (int i = 0; i < 4 * C; ++i) vals[i] = 1.0f;
  int64_t vshape[2] = {4, C};

  // plain counted add + wait
  long long seq = 0;
  long long mid = mvnet_add(c1, 0x11, meta, strlen(meta), ids, 4, vals,
                            sizeof(vals), "<f4", vshape, 2, &seq);
  assert(mid >= 0 && seq == 1);
  assert(mvnet_wait_adds(c1, seq, 10.0) == 0);
  char ebuf[128];
  assert(mvnet_take_add_error(c1, mid, ebuf, sizeof(ebuf)) == 0);
  assert(mvnet_adds_done(c1) == 1 && mvnet_adds_issued(c1) == 1);

  // add fanout (world=2 routing: id % 2 -> two "ranks", both mapping to
  // the same server here via conns[])
  void* conns[2] = {c1, c2};
  long long oseq[2], omid[2];
  int nr = mvnet_add_fanout(conns, 2, /*mod_owner=*/1, /*rows_per=*/0,
                            meta, strlen(meta), ids_mixed, 4, vals,
                            C * sizeof(float), "<f4", C, oseq, omid);
  assert(nr == 2 && omid[0] >= 0 && omid[1] >= 0);
  assert(mvnet_wait_adds(c1, oseq[0], 10.0) == 0);
  assert(mvnet_wait_adds(c2, oseq[1], 10.0) == 0);

  // scatter get fanout: rows {1,5} saw both adds (2.0), {2,8} one (1.0)
  float out[4 * C] = {0};
  long long gmid[2];
  nr = mvnet_get_fanout(conns, 2, 1, 0, meta, strlen(meta), ids_mixed, 4,
                        out, C * sizeof(float), gmid);
  assert(nr == 2);
  for (int r = 0; r < 2; ++r)
    assert(mvnet_get_wait(conns[r], gmid[r], 10.0) == 0);
  const float want[4] = {2.0f, 1.0f, 2.0f, 1.0f};
  for (int i = 0; i < 4; ++i)
    for (int j = 0; j < C; ++j) assert(out[i * C + j] == want[i]);
  for (int i = 0; i < 4; ++i)
    assert(dirty[ids_mixed[i]] == 1 && dirty[N + ids_mixed[i]] == 1);

  // full get
  std::vector<float> full(N * C);
  long long fmid = mvnet_get_send(c1, 0x15, meta, strlen(meta), nullptr, 0,
                                  full.data(),
                                  (long long)(full.size() * 4));
  assert(fmid >= 0 && mvnet_get_wait(c1, fmid, 10.0) == 0);
  assert(full[1 * C] == 2.0f && full[9 * C] == 1.0f && full[0] == 0.0f);

  // error reply (out-of-shard id) keeps the connection usable
  int64_t bad = N + 7;
  float tiny[C];
  long long bmid = mvnet_get_send(c1, 0x12, meta, strlen(meta), &bad, 1,
                                  tiny, sizeof(tiny));
  assert(bmid >= 0 && mvnet_get_wait(c1, bmid, 10.0) == -2);
  char err[256];
  mvnet_last_error(c1, err, sizeof(err));
  assert(strstr(err, "outside shard"));

  // punted message (unknown table) -> ERR reply via mvps_send_raw
  const char* pmeta = "{\"table\": \"nope\", \"weird\": 1}";
  long long pmid = mvnet_get_send(c1, 0x12, pmeta, strlen(pmeta), ids, 1,
                                  tiny, sizeof(tiny));
  assert(pmid >= 0 && mvnet_get_wait(c1, pmid, 10.0) == -2);
  assert(g_punts.load() == 1);

  // concurrent adds on ONE conn: seq assignment and the wire write share
  // a wmu hold (mvnet_add), so replies arrive in seq order and the
  // counted fence is exact — TSan sees the locking, the asserts see the
  // accounting (4 threads x 8 adds, all acked, no errors recorded)
  {
    long long before = mvnet_adds_done(c1);
    std::vector<std::thread> adders;
    std::vector<long long> mids(4 * 8);
    for (int t = 0; t < 4; ++t)
      adders.emplace_back([&, t] {
        for (int i = 0; i < 8; ++i) {
          long long s = 0;
          mids[t * 8 + i] = mvnet_add(c1, 0x11, meta, strlen(meta), ids,
                                      4, vals, sizeof(vals), "<f4",
                                      vshape, 2, &s);
          assert(mids[t * 8 + i] >= 0 && s > 0);
        }
      });
    for (auto& th : adders) th.join();
    assert(mvnet_wait_adds(c1, mvnet_adds_issued(c1), 10.0) == 0);
    assert(mvnet_adds_done(c1) == before + 4 * 8);
    for (long long m : mids)
      assert(mvnet_take_add_error(c1, m, ebuf, sizeof(ebuf)) == 0);
  }

  // cancelled get: recv thread must never touch the buffer afterwards
  long long cmid = mvnet_get_send(c2, 0x15, meta, strlen(meta), nullptr, 0,
                                  full.data(),
                                  (long long)(full.size() * 4));
  mvnet_get_cancel(c2, cmid);

  // pin lock/stats from this thread while conn threads are live
  mvps_shard_pin_lock(pin);
  mvps_shard_pin_unlock(pin);
  unsigned long long adds = 0, applies = 0;
  mvps_shard_pin_stats(pin, &adds, &applies);
  // 1 single + 2 fanout legs + 32 hammer adds
  assert(adds == 35 && applies == 35);

  // hard drop with an add outstanding: futures must observe dead
  long long dseq = 0;
  mvnet_add(c2, 0x11, meta, strlen(meta), ids, 4, vals, sizeof(vals),
            "<f4", vshape, 2, &dseq);
  mvnet_shutdown(c2);
  assert(mvnet_dead(c2) == 1);
  int rc = mvnet_wait_adds(c2, dseq + 999, 1.0);
  assert(rc == -3 || rc == 0);  // dead, or acked before the shutdown won

  mvnet_free(c2);
  mvnet_shutdown(c1);
  mvnet_free(c1);
  close(lfd);
  mvps_server_free(g_server);
  mvps_shard_pin_free(pin);
  printf("mv_ps_smoke OK (punts=%d)\n", g_punts.load());
  return 0;
}
