"""ctypes loader for the native data pipeline (libmv_data.so).

The library is optional: if the .so is missing it is built on first use when
a toolchain is present, else callers fall back to the pure-Python/numpy
implementations (``available()`` reports which path is active). See
mv_data.cpp for what lives here and why.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libmv_data.so")
_lib = None
_lock = threading.Lock()
_build_failed = False


def build_and_load(so_name: str, src_name: str,
                   extra_flags: Tuple[str, ...] = (),
                   timeout: int = 180) -> Optional[ctypes.CDLL]:
    """Build ``native/<src_name>`` into ``native/<so_name>`` if missing
    (atomic rename so concurrent workers never load a half-written .so),
    then CDLL it. One implementation for every native helper's
    build-on-first-use path (this module and ps/native). Returns None when
    no toolchain produced a loadable library."""
    so = os.path.join(_DIR, so_name)
    if not os.path.exists(so):
        tmp = f"{so}.build.{os.getpid()}"
        try:
            subprocess.run(
                [os.environ.get("CXX", "g++"), "-O3", "-std=c++17",
                 "-fPIC", "-shared", "-march=native", *extra_flags,
                 "-o", tmp, os.path.join(_DIR, src_name)],
                check=True, capture_output=True, timeout=timeout)
            os.replace(tmp, so)
        except (subprocess.SubprocessError, OSError):
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
            if not os.path.exists(so):
                return None
    try:
        return ctypes.CDLL(so)
    except OSError:
        return None


def _try_load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        lib = build_and_load("libmv_data.so", "mv_data.cpp")
        if lib is None:
            _build_failed = True
            return None
        c_i64, c_i32, c_u64, c_dbl = (ctypes.c_int64, ctypes.c_int32,
                                      ctypes.c_uint64, ctypes.c_double)
        p = ctypes.POINTER
        lib.mv_corpus_load.restype = ctypes.c_void_p
        lib.mv_corpus_load.argtypes = [ctypes.c_char_p, c_i64, c_i64]
        lib.mv_corpus_free.argtypes = [ctypes.c_void_p]
        lib.mv_corpus_vocab_size.restype = c_i64
        lib.mv_corpus_vocab_size.argtypes = [ctypes.c_void_p]
        lib.mv_corpus_size.restype = c_i64
        lib.mv_corpus_size.argtypes = [ctypes.c_void_p]
        lib.mv_corpus_total_tokens.restype = c_i64
        lib.mv_corpus_total_tokens.argtypes = [ctypes.c_void_p]
        lib.mv_corpus_counts.argtypes = [ctypes.c_void_p, p(c_i64)]
        lib.mv_corpus_ids.argtypes = [ctypes.c_void_p, p(c_i32)]
        lib.mv_corpus_word.restype = ctypes.c_char_p
        lib.mv_corpus_word.argtypes = [ctypes.c_void_p, c_i64]
        lib.mv_subsample.restype = c_i64
        lib.mv_subsample.argtypes = [p(c_i32), c_i64, p(c_i64), c_i64,
                                     c_dbl, c_u64, p(c_i32)]
        lib.mv_generate_pairs.restype = c_i64
        lib.mv_generate_pairs.argtypes = [p(c_i32), c_i64, c_i32, c_u64,
                                          c_i32, p(c_i32), p(c_i32)]
        lib.mv_parse_libsvm_line.restype = c_i32
        lib.mv_parse_libsvm_line.argtypes = [ctypes.c_char_p, c_i64,
                                             p(ctypes.c_float), c_i64]
        _lib = lib
        return _lib


def available() -> bool:
    return _try_load() is not None


class NativeCorpus:
    """Opaque handle over mv_corpus_load: tokenized, pruned, encoded corpus."""

    def __init__(self, path: str, min_count: int = 5,
                 max_vocab: Optional[int] = None):
        lib = _try_load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.mv_corpus_load(path.encode(), min_count,
                                     max_vocab or 0)
        if not self._h:
            raise IOError(f"mv_corpus_load failed for {path!r}")

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.mv_corpus_free(self._h)
            self._h = None

    @property
    def vocab_size(self) -> int:
        return self._lib.mv_corpus_vocab_size(self._h)

    @property
    def total_tokens(self) -> int:
        return self._lib.mv_corpus_total_tokens(self._h)

    def counts(self) -> np.ndarray:
        out = np.zeros(self.vocab_size, dtype=np.int64)
        self._lib.mv_corpus_counts(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return out

    def ids(self) -> np.ndarray:
        out = np.zeros(self._lib.mv_corpus_size(self._h), dtype=np.int32)
        self._lib.mv_corpus_ids(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out

    def words(self) -> List[str]:
        return [self._lib.mv_corpus_word(self._h, i).decode()
                for i in range(self.vocab_size)]


def subsample(ids: np.ndarray, counts: np.ndarray, t: float = 1e-4,
              seed: int = 0) -> np.ndarray:
    lib = _try_load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    out = np.zeros(ids.size, dtype=np.int32)
    i32p, i64p = (ctypes.POINTER(ctypes.c_int32),
                  ctypes.POINTER(ctypes.c_int64))
    m = lib.mv_subsample(ids.ctypes.data_as(i32p), ids.size,
                         counts.ctypes.data_as(i64p), counts.size,
                         t, seed, out.ctypes.data_as(i32p))
    return out[:m].copy()


def generate_pairs(ids: np.ndarray, window: int, seed: int = 0,
                   dynamic: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    lib = _try_load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    cap = 2 * window * max(ids.size, 1)
    centers = np.zeros(cap, dtype=np.int32)
    contexts = np.zeros(cap, dtype=np.int32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    m = lib.mv_generate_pairs(ids.ctypes.data_as(i32p), ids.size, window,
                              seed, 1 if dynamic else 0,
                              centers.ctypes.data_as(i32p),
                              contexts.ctypes.data_as(i32p))
    return centers[:m].copy(), contexts[:m].copy()


def parse_libsvm_line(line: bytes, dim: int) -> Optional[Tuple[int, np.ndarray]]:
    lib = _try_load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    x = np.zeros(dim, dtype=np.float32)
    label = lib.mv_parse_libsvm_line(
        line, len(line), x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        dim)
    if label == -(1 << 31):
        return None
    return label, x
