// Sanitizer smoke test for the native data pipeline (mv_data.cpp).
//
// SURVEY §5 notes the reference ships no sanitizer coverage at all
// ("race detection: none in-tree"); this binary exercises every exported
// mv_* entry point so `make sanitize` can run the pipeline under
// ASan+UBSan (the single-threaded C++ here has no TSan surface).
// Build + run: make -C multiverso_tpu/native sanitize

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
void* mv_corpus_load(const char* path, int64_t min_count, int64_t max_vocab);
void mv_corpus_free(void* handle);
int64_t mv_corpus_vocab_size(void* handle);
int64_t mv_corpus_size(void* handle);
int64_t mv_corpus_total_tokens(void* handle);
void mv_corpus_counts(void* handle, int64_t* out);
void mv_corpus_ids(void* handle, int32_t* out);
const char* mv_corpus_word(void* handle, int64_t id);
int64_t mv_subsample(const int32_t* ids, int64_t n, const int64_t* counts,
                     int64_t vocab, double t, uint64_t seed, int32_t* out);
int64_t mv_generate_pairs(const int32_t* ids, int64_t n, int32_t window,
                          uint64_t seed, int32_t dynamic, int32_t* centers,
                          int32_t* contexts);
int32_t mv_parse_libsvm_line(const char* line, int64_t len, float* x,
                             int64_t input_size);
}

int main() {
    // write a small corpus
    const char* path = "/tmp/mv_smoke_corpus.txt";
    FILE* f = fopen(path, "w");
    assert(f);
    for (int i = 0; i < 500; ++i)
        fprintf(f, "the quick brown fox jumps over the lazy dog w%d ",
                i % 23);
    fclose(f);

    void* c = mv_corpus_load(path, 2, 1 << 20);
    assert(c);
    int64_t v = mv_corpus_vocab_size(c);
    int64_t n = mv_corpus_size(c);
    assert(v > 5 && n > 1000);
    assert(mv_corpus_total_tokens(c) >= n);
    std::vector<int64_t> counts(v);
    mv_corpus_counts(c, counts.data());
    std::vector<int32_t> ids(n);
    mv_corpus_ids(c, ids.data());
    for (int64_t i = 0; i < n; ++i) assert(ids[i] >= 0 && ids[i] < v);
    assert(mv_corpus_word(c, 0) != nullptr);

    std::vector<int32_t> sub(n);
    int64_t m = mv_subsample(ids.data(), n, counts.data(), v, 1e-3, 7,
                             sub.data());
    assert(m >= 0 && m <= n);

    std::vector<int32_t> centers(n * 10), contexts(n * 10);
    int64_t pairs = mv_generate_pairs(ids.data(), std::min<int64_t>(n, 2000),
                                      5, 11, /*dynamic=*/1,
                                      centers.data(), contexts.data());
    assert(pairs > 0);
    for (int64_t i = 0; i < pairs; ++i)
        assert(centers[i] >= 0 && centers[i] < v && contexts[i] >= 0 &&
               contexts[i] < v);

    std::string line = "1 0:0.5 3:-1.25 7:2.0";
    std::vector<float> x(8, 0.f);
    int32_t label = mv_parse_libsvm_line(line.c_str(),
                                         (int64_t)line.size(), x.data(), 8);
    assert(label == 1);
    assert(x[0] == 0.5f && x[3] == -1.25f && x[7] == 2.0f);

    mv_corpus_free(c);
    std::remove(path);
    std::puts("mv_data smoke: OK");
    return 0;
}
