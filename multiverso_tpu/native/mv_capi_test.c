/* mv_capi_test: end-to-end C driver over the full MV_* ABI.
 *
 * The reference ships a runnable binding test (ref: binding/lua/test.lua
 * :1-79 — array + matrix round-trips through the C API); this driver
 * covers the same surface from plain C, with ASSERTIONS, including the
 * async row ops the round-1 Lua shim missed. Built and run by
 * `make -C multiverso_tpu/native capi_test` (CI) and
 * tests/test_bindings.py.
 *
 * Requires PYTHONPATH to reach multiverso_tpu; set MV_CAPI_PLATFORM=cpu
 * to keep the embedded interpreter off the (single) TPU chip.
 */

#include <math.h>
#include <stdio.h>
#include <stdlib.h>

typedef void* TableHandler;

/* the ABI under test (mirrors ref include/multiverso/c_api.h:16-54) */
/* BEGIN generated ABI declarations (tools/gen_capi_surface.py) */
void MV_Init(int* argc, char** argv);
void MV_ShutDown(void);
void MV_Barrier(void);
int  MV_NumWorkers(void);
int  MV_WorkerId(void);
int  MV_ServerId(void);
void MV_NewArrayTable(int size, TableHandler* out);
void MV_GetArrayTable(TableHandler handler, float* data, int size);
void MV_AddArrayTable(TableHandler handler, float* data, int size);
void MV_AddAsyncArrayTable(TableHandler handler, float* data, int size);
void MV_NewAsyncArrayTable(int size, TableHandler* out);
void MV_NewAsyncMatrixTable(int num_row, int num_col, TableHandler* out);
void MV_NewMatrixTable(int num_row, int num_col, TableHandler* out);
void MV_GetMatrixTableAll(TableHandler handler, float* data, int size);
void MV_AddMatrixTableAll(TableHandler handler, float* data, int size);
void MV_AddAsyncMatrixTableAll(TableHandler handler, float* data, int size);
void MV_GetMatrixTableByRows(TableHandler handler, float* data, int size, int row_ids[], int row_ids_n);
void MV_AddMatrixTableByRows(TableHandler handler, float* data, int size, int row_ids[], int row_ids_n);
void MV_AddAsyncMatrixTableByRows(TableHandler handler, float* data, int size, int row_ids[], int row_ids_n);
/* END generated ABI declarations */

static int g_failures = 0;

static void expect(int cond, const char* what) {
  if (!cond) {
    fprintf(stderr, "FAIL: %s\n", what);
    g_failures++;
  }
}

static void expect_near(float got, float want, const char* what) {
  if (fabsf(got - want) > 1e-4f) {
    fprintf(stderr, "FAIL: %s (got %f want %f)\n", what, got, want);
    g_failures++;
  }
}

int main(void) {
  MV_Init(NULL, NULL);
  expect(MV_NumWorkers() >= 1, "MV_NumWorkers >= 1");
  expect(MV_WorkerId() >= 0, "MV_WorkerId >= 0");
  expect(MV_ServerId() >= 0, "MV_ServerId >= 0");
  MV_Barrier();

  /* ---- array table: sync + async adds, read-back ---- */
  enum { N = 16 };
  TableHandler at = NULL;
  MV_NewArrayTable(N, &at);
  expect(at != NULL, "MV_NewArrayTable handle");
  float delta[N], out[N];
  for (int i = 0; i < N; i++) delta[i] = (float)i;
  MV_AddArrayTable(at, delta, N);
  MV_AddAsyncArrayTable(at, delta, N);
  MV_Barrier(); /* fences the async add (ref test.lua barrier) */
  MV_GetArrayTable(at, out, N);
  for (int i = 0; i < N; i++) expect_near(out[i], 2.0f * i, "array sum");

  /* ---- matrix table: whole-table + row ops, sync + async ---- */
  enum { R = 8, C = 4, SZ = R * C };
  TableHandler mt = NULL;
  MV_NewMatrixTable(R, C, &mt);
  expect(mt != NULL, "MV_NewMatrixTable handle");
  float md[SZ], mo[SZ];
  for (int i = 0; i < SZ; i++) md[i] = 1.0f;
  MV_AddMatrixTableAll(mt, md, SZ);
  MV_AddAsyncMatrixTableAll(mt, md, SZ);
  MV_Barrier();
  MV_GetMatrixTableAll(mt, mo, SZ);
  for (int i = 0; i < SZ; i++) expect_near(mo[i], 2.0f, "matrix all sum");

  int rows[2] = {1, 6};
  float rvals[2 * C], rout[2 * C];
  for (int i = 0; i < 2 * C; i++) rvals[i] = 0.5f;
  MV_AddMatrixTableByRows(mt, rvals, 2 * C, rows, 2);
  MV_AddAsyncMatrixTableByRows(mt, rvals, 2 * C, rows, 2);
  MV_Barrier();
  MV_GetMatrixTableByRows(mt, rout, 2 * C, rows, 2);
  for (int i = 0; i < 2 * C; i++)
    expect_near(rout[i], 3.0f, "matrix row sum"); /* 2 + 0.5 + 0.5 */
  /* untouched row keeps the whole-table value */
  int row0[1] = {0};
  float r0[C];
  MV_GetMatrixTableByRows(mt, r0, C, row0, 1);
  for (int i = 0; i < C; i++) expect_near(r0[i], 2.0f, "untouched row");

  /* ---- async-PS-plane tables (beyond the reference C API): same
   * accessor surface, uncoordinated ownership; MV_Barrier flushes this
   * process's outstanding async ops before fencing. ---- */
  TableHandler aat = NULL;
  MV_NewAsyncArrayTable(N, &aat);
  expect(aat != NULL, "MV_NewAsyncArrayTable handle");
  MV_AddArrayTable(aat, delta, N);
  MV_AddAsyncArrayTable(aat, delta, N);
  MV_Barrier();
  MV_GetArrayTable(aat, out, N);
  for (int i = 0; i < N; i++)
    expect_near(out[i], 2.0f * i, "async array sum");

  TableHandler amt = NULL;
  MV_NewAsyncMatrixTable(R, C, &amt);
  expect(amt != NULL, "MV_NewAsyncMatrixTable handle");
  MV_AddMatrixTableAll(amt, md, SZ);
  MV_AddAsyncMatrixTableAll(amt, md, SZ);
  MV_Barrier();
  MV_GetMatrixTableAll(amt, mo, SZ);
  for (int i = 0; i < SZ; i++)
    expect_near(mo[i], 2.0f, "async matrix all sum");
  MV_AddMatrixTableByRows(amt, rvals, 2 * C, rows, 2);
  MV_AddAsyncMatrixTableByRows(amt, rvals, 2 * C, rows, 2);
  MV_Barrier();
  MV_GetMatrixTableByRows(amt, rout, 2 * C, rows, 2);
  for (int i = 0; i < 2 * C; i++)
    expect_near(rout[i], 3.0f, "async matrix row sum");

  MV_ShutDown();
  if (g_failures == 0) {
    printf("MV_CAPI_TEST PASS\n");
    return 0;
  }
  fprintf(stderr, "MV_CAPI_TEST: %d failures\n", g_failures);
  return 1;
}
