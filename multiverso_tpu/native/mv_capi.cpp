// mv_capi: C ABI for multiverso_tpu (libmultiverso.so).
//
// Parity surface for the reference C API (ref: include/multiverso/c_api.h
// MV_Init/MV_Barrier/MV_NewArrayTable/... and src/c_api.cpp) so that FFI
// clients — the Lua/Torch binding pattern, or any C program — can drive the
// framework. The reference's C API wraps a C++ library; here the runtime is
// Python/JAX, so this shim embeds (or attaches to) a CPython interpreter and
// forwards through multiverso_tpu/c_api_support.py, passing raw buffers as
// integer addresses for zero-copy numpy views.
//
// Build: make -f Makefile.capi -C multiverso_tpu/native
// When loaded from inside a running Python process (e.g. the test suite),
// the shim attaches to the existing interpreter instead of starting one.

#include <Python.h>

#include <cstdint>
#include <cstdio>

namespace {

PyObject* g_support = nullptr;  // multiverso_tpu.c_api_support module
bool g_owns_interpreter = false;

struct Gil {
  PyGILState_STATE state;
  Gil() : state(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state); }
};

bool ensure_support() {
  if (g_support != nullptr) return true;
  g_support = PyImport_ImportModule("multiverso_tpu.c_api_support");
  if (g_support == nullptr) {
    PyErr_Print();
    return false;
  }
  return true;
}

// Call a support function with a printf-style arg format; prints + clears
// Python errors (the C ABI has no error channel, matching the reference).
PyObject* call(const char* name, const char* fmt, ...) {
  Gil gil;
  if (!ensure_support()) return nullptr;
  va_list args;
  va_start(args, fmt);
  PyObject* callable = PyObject_GetAttrString(g_support, name);
  if (callable == nullptr) {
    va_end(args);
    PyErr_Print();
    return nullptr;
  }
  PyObject* py_args = Py_VaBuildValue(fmt, args);
  va_end(args);
  PyObject* result =
      py_args ? PyObject_CallObject(callable, py_args) : nullptr;
  Py_XDECREF(py_args);
  Py_DECREF(callable);
  if (result == nullptr) PyErr_Print();
  return result;
}

int64_t call_i64(const char* name, const char* fmt, ...) {
  Gil gil;
  if (!ensure_support()) return -1;
  va_list args;
  va_start(args, fmt);
  PyObject* callable = PyObject_GetAttrString(g_support, name);
  if (callable == nullptr) {
    va_end(args);
    PyErr_Print();
    return -1;
  }
  PyObject* py_args = Py_VaBuildValue(fmt, args);
  va_end(args);
  PyObject* result =
      py_args ? PyObject_CallObject(callable, py_args) : nullptr;
  Py_XDECREF(py_args);
  Py_DECREF(callable);
  if (result == nullptr) {
    PyErr_Print();
    return -1;
  }
  int64_t out = PyLong_AsLongLong(result);
  Py_DECREF(result);
  return out;
}

}  // namespace

extern "C" {

typedef void* TableHandler;

void MV_Init(int* /*argc*/, char** /*argv*/) {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_owns_interpreter = true;
  }
  Py_XDECREF(call("init", "()"));
}

void MV_ShutDown() {
  Py_XDECREF(call("shutdown", "()"));
  if (g_owns_interpreter) {
    Gil gil;
    Py_XDECREF(g_support);
    g_support = nullptr;
  }
}

void MV_Barrier() { Py_XDECREF(call("barrier", "()")); }

int MV_NumWorkers() {
  return static_cast<int>(call_i64("num_workers", "()"));
}

int MV_WorkerId() { return static_cast<int>(call_i64("worker_id", "()")); }

int MV_ServerId() { return static_cast<int>(call_i64("server_id", "()")); }

// ---- Array table --------------------------------------------------------

void MV_NewArrayTable(int size, TableHandler* out) {
  *out = reinterpret_cast<TableHandler>(
      call_i64("new_array_table", "(i)", size));
}

void MV_GetArrayTable(TableHandler handler, float* data, int size) {
  Py_XDECREF(call("array_get", "(LLi)",
                  reinterpret_cast<int64_t>(handler),
                  reinterpret_cast<int64_t>(data), size));
}

void MV_AddArrayTable(TableHandler handler, float* data, int size) {
  Py_XDECREF(call("array_add", "(LLii)",
                  reinterpret_cast<int64_t>(handler),
                  reinterpret_cast<int64_t>(data), size, 1));
}

void MV_AddAsyncArrayTable(TableHandler handler, float* data, int size) {
  Py_XDECREF(call("array_add", "(LLii)",
                  reinterpret_cast<int64_t>(handler),
                  reinterpret_cast<int64_t>(data), size, 0));
}

// Uncoordinated (async-PS plane) tables — BEYOND the reference C API,
// which reached only the sync tables: every process owns a row shard
// served by its PSService; Adds/Gets are uncoordinated and ride the
// native C++ transport where libmv_ps builds. The row/whole-table
// accessors below work on these handles unchanged (same op surface).

void MV_NewAsyncArrayTable(int size, TableHandler* out) {
  *out = reinterpret_cast<TableHandler>(
      call_i64("new_async_array_table", "(i)", size));
}

void MV_NewAsyncMatrixTable(int num_row, int num_col, TableHandler* out) {
  *out = reinterpret_cast<TableHandler>(
      call_i64("new_async_matrix_table", "(ii)", num_row, num_col));
}

// ---- Matrix table -------------------------------------------------------

void MV_NewMatrixTable(int num_row, int num_col, TableHandler* out) {
  *out = reinterpret_cast<TableHandler>(
      call_i64("new_matrix_table", "(ii)", num_row, num_col));
}

void MV_GetMatrixTableAll(TableHandler handler, float* data, int size) {
  Py_XDECREF(call("matrix_get_all", "(LLi)",
                  reinterpret_cast<int64_t>(handler),
                  reinterpret_cast<int64_t>(data), size));
}

void MV_AddMatrixTableAll(TableHandler handler, float* data, int size) {
  Py_XDECREF(call("matrix_add_all", "(LLii)",
                  reinterpret_cast<int64_t>(handler),
                  reinterpret_cast<int64_t>(data), size, 1));
}

void MV_AddAsyncMatrixTableAll(TableHandler handler, float* data, int size) {
  Py_XDECREF(call("matrix_add_all", "(LLii)",
                  reinterpret_cast<int64_t>(handler),
                  reinterpret_cast<int64_t>(data), size, 0));
}

void MV_GetMatrixTableByRows(TableHandler handler, float* data, int size,
                             int row_ids[], int row_ids_n) {
  Py_XDECREF(call("matrix_get_rows", "(LLiLi)",
                  reinterpret_cast<int64_t>(handler),
                  reinterpret_cast<int64_t>(data), size,
                  reinterpret_cast<int64_t>(row_ids), row_ids_n));
}

void MV_AddMatrixTableByRows(TableHandler handler, float* data, int size,
                             int row_ids[], int row_ids_n) {
  Py_XDECREF(call("matrix_add_rows", "(LLiLii)",
                  reinterpret_cast<int64_t>(handler),
                  reinterpret_cast<int64_t>(data), size,
                  reinterpret_cast<int64_t>(row_ids), row_ids_n, 1));
}

void MV_AddAsyncMatrixTableByRows(TableHandler handler, float* data,
                                  int size, int row_ids[], int row_ids_n) {
  Py_XDECREF(call("matrix_add_rows", "(LLiLii)",
                  reinterpret_cast<int64_t>(handler),
                  reinterpret_cast<int64_t>(data), size,
                  reinterpret_cast<int64_t>(row_ids), row_ids_n, 0));
}

}  // extern "C"
