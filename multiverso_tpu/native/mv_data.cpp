// mv_data: native host-side data pipeline for multiverso_tpu.
//
// TPU-native equivalent of the reference's C++ data machinery — the
// WordEmbedding reader/dictionary (ref: Applications/WordEmbedding/src/
// reader.cpp, dictionary.cpp, data_block.cpp) and the LR sample reader's
// parsing core (ref: Applications/LogisticRegression/src/reader.cpp). The
// device side of the framework is JAX/XLA; this library owns the CPU-bound
// text work that feeds it: tokenization, vocabulary counting, id encoding,
// frequent-word subsampling, and training-pair generation. Exposed as a C ABI
// for ctypes (no pybind11 in the image).
//
// Build: make -C multiverso_tpu/native      (produces libmv_data.so)

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// splitmix64: small deterministic RNG (seed-stable across platforms).
struct Rng {
  uint64_t state;
  explicit Rng(uint64_t seed) : state(seed + 0x9E3779B97F4A7C15ULL) {}
  uint64_t next() {
    uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  double uniform() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
  // unbiased-enough bounded draw for window shrink
  uint64_t below(uint64_t n) { return n ? next() % n : 0; }
};

struct Corpus {
  std::vector<std::string> words;           // id -> word, count-desc order
  std::vector<int64_t> counts;              // id -> corpus count
  std::vector<int32_t> ids;                 // encoded corpus stream
  int64_t total_tokens = 0;                 // pre-pruning token count
};

bool is_space(char c) {
  return c == ' ' || c == '\n' || c == '\t' || c == '\r' || c == '\v' ||
         c == '\f';
}

}  // namespace

extern "C" {

// Load + tokenize + count + prune(min_count) + encode. Returns an opaque
// handle, or nullptr on IO failure. (ref dictionary.cpp build + reader.cpp
// tokenize, fused into one pass over the mmap-sized buffer.)
void* mv_corpus_load(const char* path, int64_t min_count, int64_t max_vocab) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::string buf(static_cast<size_t>(size), '\0');
  if (size > 0 && std::fread(&buf[0], 1, static_cast<size_t>(size), f) !=
                      static_cast<size_t>(size)) {
    std::fclose(f);
    return nullptr;
  }
  std::fclose(f);

  // pass 1: count tokens. Only (offset, len) spans are kept — materializing
  // every token as a std::string would multiply peak memory several-fold on
  // GB-scale corpora.
  std::unordered_map<std::string, int64_t> counter;
  std::vector<std::pair<uint32_t, uint32_t>> spans;
  spans.reserve(static_cast<size_t>(size / 6 + 16));
  size_t i = 0, n = buf.size();
  auto corpus = new Corpus();
  std::string scratch;
  while (i < n) {
    while (i < n && is_space(buf[i])) ++i;
    size_t start = i;
    while (i < n && !is_space(buf[i])) ++i;
    if (i > start) {
      spans.emplace_back(static_cast<uint32_t>(start),
                         static_cast<uint32_t>(i - start));
      scratch.assign(buf.data() + start, i - start);
      ++counter[scratch];
    }
  }
  corpus->total_tokens = static_cast<int64_t>(spans.size());

  // vocab: count-desc, then lexicographic for determinism (matches the
  // python Dictionary.build ordering)
  std::vector<std::pair<std::string, int64_t>> vocab;
  vocab.reserve(counter.size());
  for (auto& kv : counter) {
    if (kv.second >= min_count) vocab.emplace_back(kv.first, kv.second);
  }
  std::sort(vocab.begin(), vocab.end(), [](const auto& a, const auto& b) {
    return a.second != b.second ? a.second > b.second : a.first < b.first;
  });
  if (max_vocab > 0 && static_cast<int64_t>(vocab.size()) > max_vocab) {
    vocab.resize(static_cast<size_t>(max_vocab));
  }
  std::unordered_map<std::string, int32_t> word2id;
  word2id.reserve(vocab.size() * 2);
  for (size_t w = 0; w < vocab.size(); ++w) {
    corpus->words.push_back(vocab[w].first);
    corpus->counts.push_back(vocab[w].second);
    word2id.emplace(vocab[w].first, static_cast<int32_t>(w));
  }

  // pass 2: encode spans, dropping OOV (ref reader behavior)
  corpus->ids.reserve(spans.size());
  for (auto& sp : spans) {
    scratch.assign(buf.data() + sp.first, sp.second);
    auto it = word2id.find(scratch);
    if (it != word2id.end()) corpus->ids.push_back(it->second);
  }
  return corpus;
}

void mv_corpus_free(void* handle) { delete static_cast<Corpus*>(handle); }

int64_t mv_corpus_vocab_size(void* handle) {
  return static_cast<int64_t>(static_cast<Corpus*>(handle)->words.size());
}

int64_t mv_corpus_size(void* handle) {
  return static_cast<int64_t>(static_cast<Corpus*>(handle)->ids.size());
}

int64_t mv_corpus_total_tokens(void* handle) {
  return static_cast<Corpus*>(handle)->total_tokens;
}

void mv_corpus_counts(void* handle, int64_t* out) {
  auto* c = static_cast<Corpus*>(handle);
  std::memcpy(out, c->counts.data(), c->counts.size() * sizeof(int64_t));
}

void mv_corpus_ids(void* handle, int32_t* out) {
  auto* c = static_cast<Corpus*>(handle);
  std::memcpy(out, c->ids.data(), c->ids.size() * sizeof(int32_t));
}

const char* mv_corpus_word(void* handle, int64_t id) {
  auto* c = static_cast<Corpus*>(handle);
  if (id < 0 || id >= static_cast<int64_t>(c->words.size())) return "";
  return c->words[static_cast<size_t>(id)].c_str();
}

// Frequent-word subsampling (ref reader.cpp sample_value): keep word w with
// prob min(1, (sqrt(f/t)+1) * t/f). Writes surviving ids to out; returns the
// new length. counts/vocab describe the id space; total = sum(counts).
int64_t mv_subsample(const int32_t* ids, int64_t n, const int64_t* counts,
                     int64_t vocab, double t, uint64_t seed, int32_t* out) {
  double total = 0;
  for (int64_t w = 0; w < vocab; ++w) total += static_cast<double>(counts[w]);
  std::vector<double> keep(static_cast<size_t>(vocab), 1.0);
  for (int64_t w = 0; w < vocab; ++w) {
    double f = counts[w] / (total > 0 ? total : 1.0);
    if (f > 1e-12) {
      double p = (std::sqrt(f / t) + 1.0) * t / f;
      keep[static_cast<size_t>(w)] = p < 1.0 ? p : 1.0;
    }
  }
  Rng rng(seed);
  int64_t m = 0;
  for (int64_t i = 0; i < n; ++i) {
    int32_t w = ids[i];
    if (w >= 0 && w < vocab && rng.uniform() < keep[static_cast<size_t>(w)]) {
      out[m++] = w;
    }
  }
  return m;
}

// Sliding-window skipgram pair generation with dynamic window shrink
// (word2vec 'b = rand % window'; ref trainer consumption of data blocks).
// Caller allocates out_centers/out_contexts with capacity 2*window*n.
// Returns the pair count.
int64_t mv_generate_pairs(const int32_t* ids, int64_t n, int32_t window,
                          uint64_t seed, int32_t dynamic,
                          int32_t* out_centers, int32_t* out_contexts) {
  Rng rng(seed);
  int64_t m = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t w = dynamic ? 1 + static_cast<int64_t>(
                                  rng.below(static_cast<uint64_t>(window)))
                        : window;
    int64_t lo = i - w > 0 ? i - w : 0;
    int64_t hi = i + w + 1 < n ? i + w + 1 : n;
    for (int64_t j = lo; j < hi; ++j) {
      if (j == i) continue;
      out_centers[m] = ids[i];
      out_contexts[m] = ids[j];
      ++m;
    }
  }
  // Fisher-Yates shuffle so minibatches mix offsets/positions
  for (int64_t i = m - 1; i > 0; --i) {
    int64_t j = static_cast<int64_t>(rng.below(static_cast<uint64_t>(i + 1)));
    std::swap(out_centers[i], out_centers[j]);
    std::swap(out_contexts[i], out_contexts[j]);
  }
  return m;
}

// libsvm line parsing: "label idx:val ..." -> dense row (ref LR reader.cpp
// text parser). Fills x (pre-zeroed by caller) of width dim; returns label,
// or INT32_MIN on empty/comment line.
int32_t mv_parse_libsvm_line(const char* line, int64_t len, float* x,
                             int64_t dim) {
  int64_t i = 0;
  while (i < len && is_space(line[i])) ++i;
  if (i >= len || line[i] == '#') return INT32_MIN;
  char* end = nullptr;
  long label = std::strtol(line + i, &end, 10);
  i = end - line;
  while (i < len) {
    while (i < len && is_space(line[i])) ++i;
    if (i >= len) break;
    char* colon = nullptr;
    long idx = std::strtol(line + i, &colon, 10);
    if (!colon || *colon != ':') break;
    char* vend = nullptr;
    double val = std::strtod(colon + 1, &vend);
    if (idx >= 0 && idx < dim) x[idx] = static_cast<float>(val);
    i = vend - line;
  }
  return static_cast<int32_t>(label);
}

}  // extern "C"
