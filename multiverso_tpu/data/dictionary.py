"""Vocabulary dictionary + Huffman encoding for hierarchical softmax.

TPU-native equivalent of the reference WordEmbedding vocab machinery
(ref: Applications/WordEmbedding/src/dictionary.cpp — word->id map with
min_count pruning; src/huffman_encoder.cpp — Huffman tree over word counts
producing per-word (codes, points) paths). The host-side logic is the same
job; the output here is *padded numpy arrays* (codes/points/lengths) ready to
ship to the device once, because the TPU consumes fixed-shape tensors, not
per-word C structs.
"""

from __future__ import annotations

import collections
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np


class Dictionary:
    """Word <-> id with count-based pruning (ref dictionary.cpp)."""

    def __init__(self, min_count: int = 5):
        self.min_count = min_count
        self.word2id: Dict[str, int] = {}
        self.words: List[str] = []
        self.counts: np.ndarray = np.zeros(0, dtype=np.int64)

    @classmethod
    def from_counts(cls, words: List[str], counts: np.ndarray,
                    min_count: int = 5) -> "Dictionary":
        """Adopt a pre-counted vocabulary (e.g. from the native corpus
        loader), which is already pruned and count-desc sorted."""
        d = cls(min_count)
        d.words = list(words)
        d.word2id = {w: i for i, w in enumerate(d.words)}
        d.counts = np.asarray(counts, dtype=np.int64)
        return d

    @classmethod
    def build(cls, tokens: Iterable[str], min_count: int = 5,
              max_vocab: Optional[int] = None) -> "Dictionary":
        d = cls(min_count)
        counter = collections.Counter(tokens)
        items = [(w, c) for w, c in counter.items() if c >= min_count]
        items.sort(key=lambda wc: (-wc[1], wc[0]))
        if max_vocab is not None:
            items = items[:max_vocab]
        d.words = [w for w, _ in items]
        d.word2id = {w: i for i, w in enumerate(d.words)}
        d.counts = np.array([c for _, c in items], dtype=np.int64)
        return d

    def __len__(self) -> int:
        return len(self.words)

    def encode(self, tokens: Iterable[str]) -> np.ndarray:
        """Token stream -> id stream, dropping OOV (ref reader behavior)."""
        w2i = self.word2id
        return np.fromiter((w2i[t] for t in tokens if t in w2i),
                           dtype=np.int64)

    def subsample(self, ids: np.ndarray, t: float = 1e-4,
                  seed: int = 0) -> np.ndarray:
        """Frequent-word subsampling (ref reader.cpp sample_value): keep word w
        with prob (sqrt(f/t)+1)*t/f where f is w's corpus frequency."""
        total = self.counts.sum()
        freq = self.counts / max(total, 1)
        keep = np.minimum(1.0, (np.sqrt(freq / t) + 1) * t / np.maximum(freq, 1e-12))
        rng = np.random.default_rng(seed)
        return ids[rng.random(ids.size) < keep[ids]]

    def unigram_table(self, power: float = 0.75) -> np.ndarray:
        """Negative-sampling distribution (counts^0.75, normalized)."""
        p = self.counts.astype(np.float64) ** power
        return (p / p.sum()).astype(np.float32)


def build_huffman(counts: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Huffman tree over word counts (ref huffman_encoder.cpp:BuildTree).

    Returns (codes, points, lengths):
    * codes  [V, L] int32 in {0,1}, the left/right decisions, padded with 0
    * points [V, L] int32, inner-node ids (< V-1), padded with V-2 safe ids
      (masked out by lengths)
    * lengths [V] int32, true path length per word

    L = max path length. Inner nodes are numbered 0..V-2 (the output table for
    HS has V-1 rows).
    """
    vocab = int(counts.size)
    if vocab < 2:
        raise ValueError("huffman needs >= 2 words")
    # Standard two-queue O(V log V) build via heap for clarity.
    import heapq
    heap = [(int(c), i) for i, c in enumerate(counts)]
    heapq.heapify(heap)
    parent = np.zeros(2 * vocab - 1, dtype=np.int64)
    binary = np.zeros(2 * vocab - 1, dtype=np.int8)
    next_id = vocab
    while len(heap) > 1:
        c1, i1 = heapq.heappop(heap)
        c2, i2 = heapq.heappop(heap)
        parent[i1] = next_id
        parent[i2] = next_id
        binary[i2] = 1
        heapq.heappush(heap, (c1 + c2, next_id))
        next_id += 1
    root = next_id - 1

    codes_list, points_list = [], []
    max_len = 0
    for w in range(vocab):
        code, point = [], []
        node = w
        while node != root:
            code.append(int(binary[node]))
            node = int(parent[node])
            point.append(node - vocab)  # inner-node id in [0, V-2]
        code.reverse()
        point.reverse()
        codes_list.append(code)
        points_list.append(point)
        max_len = max(max_len, len(code))

    codes = np.zeros((vocab, max_len), dtype=np.int32)
    points = np.full((vocab, max_len), max(vocab - 2, 0), dtype=np.int32)
    lengths = np.zeros(vocab, dtype=np.int32)
    for w in range(vocab):
        l = len(codes_list[w])
        lengths[w] = l
        codes[w, :l] = codes_list[w]
        points[w, :l] = points_list[w]
    return codes, points, lengths
