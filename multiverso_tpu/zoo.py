"""Zoo: system orchestrator — topology, lifecycle, table registry.

TPU-native re-design of the reference Zoo/Controller bootstrap
(ref: include/multiverso/zoo.h:19, src/zoo.cpp:41-177, src/controller.cpp).
The reference spins up an actor system per MPI/ZMQ process and runs a rank-0
Controller that assigns worker/server ids and implements barriers. On TPU all
of that is subsumed by the JAX runtime:

* node membership / rank assignment  -> ``jax.process_index()/process_count()``
  (multi-controller runtime discovers the pod; no Control_Register handshake)
* worker/server roles                -> every process is a worker, every
  *device* holds a server shard (the reference's ``ps_role=default`` collapse).
  ``num_workers`` = processes, ``num_servers`` = devices in the mesh.
* Controller barrier round-trip      -> a global device sync over ICI
* Communicator/net actors            -> XLA collectives inside jitted table ops

The Zoo owns the global ``jax.sharding.Mesh`` that tables shard over, and the
table registry (table_id -> table) used by checkpointing and the C ABI.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from multiverso_tpu.utils import config, log
from multiverso_tpu.utils.dashboard import Dashboard


class Zoo:
    """Singleton orchestrator (ref zoo.h Zoo). Use module helpers or Zoo.get()."""

    _instance: Optional["Zoo"] = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._started = False
        self._mesh: Optional[jax.sharding.Mesh] = None
        self._tables: Dict[int, Any] = {}
        self._next_table_id = 0
        self._barrier_count = 0
        self._dirty: set = set()   # table_ids with ops since last barrier

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    @classmethod
    def get(cls) -> "Zoo":
        with cls._lock:
            if cls._instance is None:
                cls._instance = Zoo()
            return cls._instance

    def start(self, argv: Optional[List[str]] = None,
              mesh: Optional[jax.sharding.Mesh] = None) -> None:
        """ref Zoo::Start (src/zoo.cpp:41): parse flags, init net, start actors.

        Here: parse flags, configure logging, adopt/build the device mesh.
        Idempotent; re-entrant start is a no-op (matching MV_Init usage).
        """
        if self._started:
            return
        config.parse_cmd_flags(argv)
        log.configure_from_flags()
        self._mesh = mesh if mesh is not None else self._default_mesh()
        # telemetry plane: adopt the trace_ids flag and start the
        # flag-gated metrics exporter (both no-ops unless configured; a
        # PSService starting later upgrades the exporter's payload with
        # its shard registry)
        from multiverso_tpu.telemetry import devstats as _devstats
        from multiverso_tpu.telemetry import exporter as _exporter
        from multiverso_tpu.telemetry import flightrec as _flightrec
        from multiverso_tpu.telemetry import profiler as _profiler
        from multiverso_tpu.telemetry import trace as _trace
        _trace.configure(self.rank())
        _profiler.configure(self.rank())
        # device plane: adopt the devstats flag and key compiles with
        # no explicit scope to THIS mesh's shape (the default label a
        # recompile is attributed to when nothing narrower is active)
        _devstats.configure(self.rank())
        _devstats.set_default_mesh(self._mesh)
        _exporter.ensure_started(self.rank())
        # flight-recorder plane: pin the rank, give the structured log
        # sink the same rank, and dump the black box if a fault signal
        # lands (a later handler — e.g. bench.py's SIGTERM salvage —
        # replaces this one and dumps on its own)
        _flightrec.configure(self.rank())
        log.set_rank(self.rank())
        _flightrec.install_signal_handlers()
        self._started = True
        log.info(
            "multiverso_tpu started: process %d/%d, %d devices in mesh %s, "
            "platform=%s",
            self.rank(), self.size(), self._mesh.size,
            dict(zip(self._mesh.axis_names, self._mesh.devices.shape)),
            jax.devices()[0].platform,
        )
        self.barrier()

    def _default_mesh(self) -> jax.sharding.Mesh:
        axis = config.get_flag("mesh_axis")
        devices = np.asarray(jax.devices())
        return jax.sharding.Mesh(devices, (axis,))

    def stop(self, finalize: bool = True) -> None:
        """ref Zoo::Stop (src/zoo.cpp:103): drain, display dashboard, stop
        (including the async-PS service, ref StopPS stopping the actors)."""
        if not self._started:
            return
        self.barrier()
        if config.get_flag("dashboard"):
            # natively-served async ops never cross the Python monitor
            # (that's the point of them), so surface the C++ counters in
            # the shutdown report alongside the monitored paths — BEFORE
            # the final exporter snapshot, so the last metrics record
            # carries them too
            for table in list(self._tables.values()):
                shard = getattr(table, "_shard", None)
                if shard is None or getattr(shard, "_native_ref",
                                            None) is None:
                    continue
                adds, applies = shard._native_stats()
                if adds:
                    Dashboard.note(
                        f"ps[{table.name}].native_served",
                        f"adds = {adds}, applies = {applies}")
        # final telemetry flush while the monitors still hold this run's
        # numbers (the exporter's stop() writes a last snapshot; buffered
        # trace spans drain to metrics_dir)
        from multiverso_tpu.telemetry import aggregator as _aggregator
        from multiverso_tpu.telemetry import exporter as _exporter
        from multiverso_tpu.telemetry import flightrec as _flightrec
        from multiverso_tpu.telemetry import trace as _trace
        # cluster aggregator first (final poll needs the PS service,
        # which reset_default_context below tears down), then the
        # per-rank exporter; the failover checkpointer writes one final
        # committed save while the shards are still intact
        _aggregator.stop_global()
        from multiverso_tpu.ps import failover as _failover
        _failover.stop_global(final=True)
        _exporter.stop_global()
        # final black-box dump (no-op unless a dump directory resolves):
        # a run that hung AFTER stop began still leaves its last tape.
        # routine=True: if a FAULT dump (watchdog trip, peer death,
        # fatal) was already written this process, keep it — the healthy
        # shutdown tape must never overwrite the fault evidence
        _flightrec.dump_global("Zoo.stop", routine=True)
        d = config.get_flag("metrics_dir")
        if d:
            try:
                _trace.dump_to(d)
            except OSError as e:
                log.error("trace dump at shutdown failed: %s", e)
            try:
                from multiverso_tpu.telemetry import profiler as _profiler
                _profiler.dump_to(d)
            except OSError as e:
                log.error("profile dump at shutdown failed: %s", e)
        if config.get_flag("dashboard"):
            Dashboard.display(log.info)
            # a second init/stop cycle must not reprint this run's
            # counters as its own
            Dashboard.reset()
        try:
            from multiverso_tpu.ps import service as _ps_service
            _ps_service.reset_default_context()
        except ImportError:  # pragma: no cover
            pass
        self._tables.clear()
        self._next_table_id = 0
        self._mesh = None
        self._started = False

    @property
    def started(self) -> bool:
        return self._started

    # ------------------------------------------------------------------ #
    # topology (ref zoo.h rank/size/worker_rank/server_rank accessors)
    # ------------------------------------------------------------------ #
    def rank(self) -> int:
        return jax.process_index()

    def size(self) -> int:
        return jax.process_count()

    def mesh(self) -> jax.sharding.Mesh:
        if self._mesh is None:
            raise RuntimeError("multiverso_tpu not initialized; call mv.init()")
        return self._mesh

    def shard_axis(self) -> str:
        """Mesh axis tables shard over (the last axis of the mesh)."""
        return self.mesh().axis_names[-1]

    def num_workers(self) -> int:
        n = config.get_flag("num_workers")
        return n if n > 0 else self.size()

    def num_servers(self) -> int:
        n = config.get_flag("num_servers")
        return n if n > 0 else self.mesh().size

    def worker_id(self) -> int:
        return self.rank()

    def server_id(self) -> int:
        return self.rank()

    def worker_id_to_rank(self, worker_id: int) -> int:
        return worker_id

    def server_id_to_rank(self, server_id: int) -> int:
        return server_id

    # ------------------------------------------------------------------ #
    # barrier (ref Zoo::Barrier, src/zoo.cpp:165-177 — controller round trip)
    # ------------------------------------------------------------------ #
    def mark_dirty(self, table_id: int) -> None:
        """Table ops call this; the next single-process barrier fences only
        tables with activity since the last one (a battery that barriers
        per block with many tables would otherwise pay O(tables) blocking
        syncs per barrier)."""
        self._dirty.add(table_id)

    def barrier(self) -> None:
        self._barrier_count += 1
        # black-box edges: a rank that dies INSIDE the barrier leaves
        # "enter without exit" as the last record of its tape
        from multiverso_tpu.telemetry import flightrec as _flightrec
        _flightrec.record(_flightrec.EV_BARRIER_ENTER,
                          msg_id=self._barrier_count, note="zoo.barrier")
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(
                f"multiverso_tpu_barrier_{self._barrier_count}")
        else:
            # Single controller: block on the live arrays of every table
            # TOUCHED since the last barrier, giving the reference's "all
            # prior Adds are visible" fence without fencing idle tables.
            dirty, self._dirty = self._dirty, set()
            for table_id in dirty:
                table = self._tables.get(table_id)
                raw = getattr(table, "raw", None)
                if callable(raw):
                    value = raw()
                    jax.tree.map(
                        lambda a: a.block_until_ready()
                        if isinstance(a, jax.Array) else a, value)
        _flightrec.record(_flightrec.EV_BARRIER_EXIT,
                          msg_id=self._barrier_count, note="zoo.barrier")

    # ------------------------------------------------------------------ #
    # table registry (ref zoo.h RegisterTable / table_factory ownership)
    # ------------------------------------------------------------------ #
    def register_table(self, table: Any) -> int:
        with self._lock:
            table_id = self._next_table_id
            self._next_table_id += 1
            self._tables[table_id] = table
            return table_id

    def table(self, table_id: int) -> Any:
        return self._tables[table_id]

    def tables(self) -> Dict[int, Any]:
        return dict(self._tables)
