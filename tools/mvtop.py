#!/usr/bin/env python
"""mvtop — one pane of glass over a live async-PS cluster.

    python tools/mvtop.py --rdv RDV_DIR [--world N] --once [--json]
    python tools/mvtop.py --rdv RDV_DIR --watch [SECONDS]

Reads rank addresses from the file-rendezvous directory (``<rank>.addr``,
the same files the PS plane itself rendezvouses through), probes each
rank's MSG_HEALTH + MSG_STATS over **one-shot connections** (the PR-4
probe path: answers even when a rank's data plane is wedged, bounded by
``ps_health_timeout``-scale waits), merges the payloads through
``telemetry/aggregator.py`` (exact histogram merge, shard skew, hot-key
top-K), and renders:

* per-rank health verdicts (ok/slow/stuck/unreachable, queue depth,
  oldest in-flight op age);
* per-table cluster totals and — in ``--watch`` mode, from consecutive
  polls — rates (adds/s, gets/s, wire MB/s), queue-depth deltas, and
  the windowed shard skew;
* merged p50/p99 latency percentiles for the serve/apply planes;
* the cluster hot-key table with the estimated
  cache-hit-rate-if-cached curve.

* the SLO panel (when a rank carries an armed ``telemetry/slo.py``
  sentinel): per-objective burn rates + firing state, recent episodes,
  the named straggler, and the typed autoscaling signal bus.

``--once`` prints a single snapshot and exits 0 when at least one rank
answered (scripts/tests); ``--watch`` refreshes in place until ^C.
``--json`` emits the raw merged cluster record instead of the table.
``--assert-slo`` (with ``--once``) exits 3 iff any SLO objective is
firing — the one-line CI gate on the sentinel's verdict.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def read_addrs(rdv_dir: str,
               world: Optional[int] = None) -> Dict[int, str]:
    """rank -> published address from a file-rendezvous directory
    (``world`` limits the scan; default: every ``<rank>.addr`` found)."""
    out: Dict[int, str] = {}
    try:
        names = os.listdir(rdv_dir)
    except OSError:
        return out
    for n in names:
        if not n.endswith(".addr") or n.startswith("."):
            continue
        stem = n[: -len(".addr")]
        if not stem.isdigit():
            continue
        rank = int(stem)
        if world is not None and rank >= world:
            continue
        try:
            with open(os.path.join(rdv_dir, n)) as f:
                addr = f.read().strip()
        except OSError:
            continue
        if addr:
            out[rank] = addr
    return out


def poll(addrs: Dict[int, str], timeout: float = 2.0) -> Dict:
    """Probe every rank once (one-shot conns, CONCURRENT — failures and
    deadline overruns become per-rank entries) and return the merged
    cluster record. One poll is bounded by ~2 probe timeouts total, not
    per dead rank: a --watch refresh against a half-down cluster must
    not stall world x 2 timeouts."""
    from multiverso_tpu.ps import service as svc
    from multiverso_tpu.telemetry import aggregator

    def probe_one(r, stats, health):
        addr = addrs[r]
        try:
            health[r] = svc.oneshot_probe(addr, svc.MSG_HEALTH, timeout)
        except Exception as e:  # noqa: BLE001 — per-rank entry
            health[r] = e
        try:
            stats[r] = svc.oneshot_probe(addr, svc.MSG_STATS, timeout)
        except Exception as e:  # noqa: BLE001
            stats[r] = e

    stats, health = aggregator.probe_all(sorted(addrs), probe_one,
                                         deadline_s=2.0 * timeout + 1.0)
    return aggregator.merge_cluster(stats, health, world=len(addrs))


def _fmt(v, nd: int = 3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


# objective kind -> the unit its SLI value renders in (the SLO panel's
# value column; check_obs_surface lint 7 requires every slo.py kind to
# appear here or in dump_metrics — a kind no pane can show is a verdict
# into the void)
_SLO_KIND_UNITS = {
    "serve_latency_p99": "ms", "add_latency_p99": "ms",
    "staleness": "s", "shed_rate": "frac", "availability": "frac",
    "stall_fraction": "frac", "steady_recompiles": "n",
    "recovery_s": "s", "scale_efficiency": "E",
}

# signal name -> cell formatter for the SLO panel's signal-bus line
# (telemetry/signals.py; same lint-7 rule — every bus signal renders)
_SIGNAL_FMT = {
    "shed_rate": lambda v: f"{v * 100:.1f}%",
    "hot_key_mass": lambda v: f"{v * 100:.0f}%",
    "replica_lag_epochs": lambda v: f"{v:.0f}ep",
    "replica_lag_s": lambda v: f"{v:.2f}s",
    "queue_depth": lambda v: f"{v:.0f}",
    "burn_rate": lambda v: f"{v:.1f}x",
    "spares_left": lambda v: f"{v:.0f}",
    "active_replicas": lambda v: f"{v:.0f}",
    "stall_fraction": lambda v: f"{v * 100:.1f}%",
}


def _signal_cells(rec: Dict) -> list:
    """The typed signal bus derived from THIS record (pure — the same
    signals.from_record the aggregator publishes each poll), rendered
    as "name[table]=value" cells in the bus's declared name order."""
    from multiverso_tpu.telemetry import signals as _signals
    cells = []
    by_name: Dict[str, list] = {}
    for s in _signals.from_record(rec):
        by_name.setdefault(s.name, []).append(s)
    for name in _signals.SIGNAL_NAMES:
        fmt = _SIGNAL_FMT.get(name, _fmt)
        for s in by_name.get(name, []):
            scope = f"[{s.table}]" if s.table else ""
            cells.append(f"{name}{scope}={fmt(s.value)}")
    return cells


def _mb(v) -> str:
    return f"{(v or 0) / 1e6:.2f} MB/s"


def render(rec: Dict, prev: Optional[Dict] = None,
           topk: int = 8) -> str:
    """Cluster record -> the operator screen (pure; tested directly).
    ``prev`` (the previous poll) turns counters into rates."""
    from multiverso_tpu.telemetry import aggregator
    if prev is not None and "rates" not in rec:
        aggregator.derive_rates(prev, rec)
    up = sum(1 for e in rec.get("ranks", {}).values()
             if e.get("status") not in (None, "unreachable"))
    lines = [f"mvtop  {time.strftime('%H:%M:%S', time.localtime(rec.get('ts', 0)))}"
             f"  ranks {up}/{rec.get('world', '?')} up"
             f"  (stats from {rec.get('polled', 0)})"]
    lines.append(f"{'rank':<5} {'status':<12} {'gen':>4} "
                 f"{'addr':<22} {'queue':>6} "
                 f"{'infl':>5} {'oldest_s':>9} {'serve_age':>10} "
                 f"{'stall%':>7} {'recomp':>6}")
    for r in sorted(rec.get("ranks", {}), key=int):
        e = rec["ranks"][r]
        status = e.get("status", "?")
        if e.get("stats_error"):
            status += "*"       # health answered, stats did not
        # incarnation generation: gen>0 = this rank was respawned by
        # the failover plane (the at-a-glance restarted-shard signal).
        # stall% / recomp come from the step profiler's MSG_STATS block
        # (flag step_profile): wall time no phase claimed, and
        # steady-state recompiles past step 1 — "-" when not profiling
        lines.append(
            f"{r:<5} {status:<12} {_fmt(e.get('gen')):>4} "
            f"{_fmt(e.get('addr')):<22} "
            f"{_fmt(e.get('queue_depth')):>6} {_fmt(e.get('inflight')):>5} "
            f"{_fmt(e.get('oldest_inflight_s')):>9} "
            f"{_fmt(e.get('serve_age_s')):>10} "
            f"{_fmt(e.get('stall_pct'), 1):>7} "
            f"{_fmt(e.get('recompiles')):>6}")
        if e.get("error"):
            lines.append(f"      {e['error']}")
    # memory panel (telemetry/memstats.py, MSG_STATS "memory" block):
    # per-rank RSS / device bytes / live table bytes / replay-retained
    # bytes / pinned read epochs, plus the (host, pid)-deduped cluster
    # totals. "-" = the rank's payload carried no memory block (an
    # older peer) or the figure is unavailable (no /proc, no sampler).
    mem = rec.get("memory")
    if mem:

        def _mmb(v):
            return "-" if not isinstance(v, (int, float)) \
                else f"{v / 1e6:.2f}"

        t = mem.get("totals", {})
        lines.append("")
        lines.append(
            f"memory: rss {_fmt(t.get('rss_mb'), 1)} MB"
            f"  device {_mmb(t.get('device_bytes'))} MB"
            f"  tables {_mmb(t.get('table_bytes'))} MB"
            f"  retained {_mmb(t.get('retained_bytes'))} MB"
            f"  pinned epochs {t.get('pinned_epochs', 0)}")
        lines.append(f"  {'rank':<5} {'rss_mb':>8} {'device_mb':>10} "
                     f"{'table_mb':>9} {'retained_mb':>12} {'pins':>5} "
                     f"{'verdicts':<20}")
        for r in sorted(mem.get("ranks", {}), key=str):
            e = mem["ranks"][r]
            vd = ",".join(e.get("verdicts") or []) or "-"
            lines.append(
                f"  {r:<5} {_fmt(e.get('rss_mb'), 1):>8} "
                f"{_mmb(e.get('device_bytes')):>10} "
                f"{_mmb(e.get('table_bytes')):>9} "
                f"{_mmb(e.get('retained_bytes')):>12} "
                f"{_fmt(e.get('pinned_epochs')):>5} {vd:<20}")
    # device panel (telemetry/devstats.py, MSG_STATS "devices" block):
    # per-rank host<->device transfer bytes, collective calls/bytes,
    # mesh-keyed compiles, per-device live bytes, and SPMD hygiene
    # findings. The block is ADDITIVE — a rank whose payload lacks it
    # (an older peer in a mixed-version cluster, or no device activity)
    # renders "-", never a KeyError.
    dev = rec.get("devices")
    if dev:

        def _dmb(v):
            return "-" if not isinstance(v, (int, float)) \
                else f"{v / 1e6:.2f}"

        t = dev.get("totals", {})
        lines.append("")
        lines.append(
            f"devices: h2d {_dmb(t.get('h2d_bytes'))} MB"
            f"  d2h {_dmb(t.get('d2h_bytes'))} MB"
            f"  coll {t.get('coll_calls', 0)} calls"
            f"/{_dmb(t.get('coll_bytes'))} MB"
            f"  compiles {t.get('compiles', 0)}"
            f" ({_fmt(t.get('compile_s'), 2)} s)"
            f"  live {_dmb(t.get('device_bytes'))} MB"
            + (f"  HYGIENE FINDINGS {t['hygiene_findings']}"
               if t.get("hygiene_findings") else ""))
        lines.append(f"  {'rank':<5} {'h2d_mb':>8} {'d2h_mb':>8} "
                     f"{'coll':>6} {'coll_mb':>8} {'compiles':>8} "
                     f"{'mesh shapes':<28}")
        for r in sorted(dev.get("ranks", {}), key=str):
            d = dev["ranks"][r]
            tr = d.get("transfers") or {}
            colls = d.get("collectives") or {}
            comp = d.get("compiles_by_mesh") or {}
            lines.append(
                f"  {r:<5} "
                f"{_dmb((tr.get('h2d') or {}).get('bytes')):>8} "
                f"{_dmb((tr.get('d2h') or {}).get('bytes')):>8} "
                f"{sum(int(c.get('calls') or 0) for c in colls.values() if isinstance(c, dict)):>6} "
                f"{_dmb(sum(int(c.get('bytes') or 0) for c in colls.values() if isinstance(c, dict))):>8} "
                f"{sum(int(c.get('compiles') or 0) for c in comp.values() if isinstance(c, dict)):>8} "
                f"{','.join(sorted(comp)) or '-':<28}")
            ops = {op: c.get("calls") for op, c in sorted(colls.items())
                   if isinstance(c, dict)}
            if ops:
                lines.append("        coll ops: " + "  ".join(
                    f"{op}:{n}" for op, n in ops.items()))
    # tenant panel (telemetry/tenants.py, MSG_STATS "tenants" block):
    # per-(table, tenant) served/shed/deferred + latency percentiles,
    # interval traffic shares, per-tenant budget decisions, and the
    # noisy-neighbor verdict state. ADDITIVE like the device block — a
    # cluster with no tenant traffic renders nothing.
    ten = rec.get("tenants")
    if ten:
        lines.append("")
        head = (f"tenants: episodes {ten.get('episodes', 0)}"
                + ("  NOISY-NEIGHBOR ACTIVE" if ten.get("active")
                   else ""))
        shares = ten.get("shares") or {}
        if shares:
            head += "  share " + "  ".join(
                f"{tn}:{sh * 100:.0f}%" for tn, sh in
                sorted(shares.items(), key=lambda kv: -kv[1])[:topk])
        lines.append(head)
        v = ten.get("verdict")
        if v:
            lines.append(
                f"  verdict: {v.get('kind')} tenant={v.get('tenant')}"
                f" share={_fmt(v.get('share'))}"
                f" victims={','.join(v.get('victims') or [])}"
                f" why={','.join(v.get('why') or [])}")
        lines.append(f"  {'table/tenant':<28} {'served':>8} {'shed':>7} "
                     f"{'shed%':>6} {'defer':>6} {'qps':>8} "
                     f"{'p99_ms':>8} {'age_s':>7}")
        for tname in sorted(ten.get("tables") or {}):
            tt = ten["tables"][tname]
            for tn in sorted(tt):
                e = tt[tn]
                h = e.get("infer") or {}
                er = e.get("rates") or {}
                sr = e.get("shed_rate")
                lines.append(
                    f"  {tname + '/' + tn:<28} {e.get('served', 0):>8} "
                    f"{e.get('shed', 0):>7} "
                    f"{('-' if sr is None else f'{sr * 100:.1f}'):>6} "
                    f"{e.get('deferred', 0):>6} "
                    f"{_fmt(er.get('served_per_s'), 1):>8} "
                    f"{_fmt(h.get('p99_ms')):>8} "
                    f"{_fmt(e.get('max_age_s')):>7}")
        adm = ten.get("admission") or {}
        if adm:
            cells = [
                f"{k} {a.get('admitted', 0)}/{a.get('shed', 0)}"
                + (f"@{a['qps_limit']}qps" if a.get("qps_limit")
                   else "")
                for k, a in sorted(adm.items())]
            lines.append("  budgets (admitted/shed): "
                         + "  ".join(cells[:topk]))
        wire = ten.get("wire") or {}
        if wire:
            cells = [
                f"{tn}:{w.get('ops', 0)}op/"
                f"{(w.get('add_bytes', 0) + w.get('get_bytes', 0)) / 1e6:.2f}MB"
                for tn, w in sorted(wire.items())]
            lines.append("  wire ops: " + "  ".join(cells[:topk]))
    # SLO panel (telemetry/slo.py, MSG_STATS "slo" block): per-objective
    # burn-rate verdicts (fast/slow window), firing state, episode
    # counts, the named straggler, and the typed signal bus — the
    # objective-first line an operator reads before any raw gauge.
    # ADDITIVE like the device block: a cluster with no slo_spec
    # renders nothing.
    slo = rec.get("slo")
    if slo:
        firing = slo.get("firing") or []
        lines.append("")
        lines.append(
            f"slo: objectives {len(slo.get('objectives') or {})}"
            f"  episodes {slo.get('episodes', 0)}"
            f"  evals {slo.get('evals', 0)}"
            + (f"  FIRING {','.join(firing)}" if firing else "  ok"))
        objs = slo.get("objectives") or {}
        if objs:
            lines.append(f"  {'objective':<26} {'kind':<19} {'state':<7} "
                         f"{'value':>10} {'burn_f':>7} {'burn_s':>7} "
                         f"{'eps':>4}")
            for name in sorted(objs):
                o = objs[name]
                kind = o.get("kind") or "?"
                unit = _SLO_KIND_UNITS.get(kind, "")
                val = o.get("value")
                cell = ("-" if val is None
                        else f"{_fmt(val)}{unit and ' ' + unit}")
                lines.append(
                    f"  {name:<26} {kind:<19} "
                    f"{'FIRING' if o.get('firing') else 'ok':<7} "
                    f"{cell:>10} {_fmt(o.get('burn_fast'), 1):>7} "
                    f"{_fmt(o.get('burn_slow'), 1):>7} "
                    f"{o.get('episodes', 0):>4}")
        s = slo.get("straggler")
        if s:
            lines.append(
                f"  straggler: rank {s.get('rank')} "
                f"({s.get('attribution')}"
                + (f", top phase {s['top_phase']}"
                   if s.get("top_phase") else "")
                + f")  score {_fmt(s.get('score'), 2)}")
        for ev in (slo.get("recent") or [])[-4:]:
            lines.append(
                f"  {ev.get('kind')}: {ev.get('objective')} "
                f"ep{ev.get('episode')} value={_fmt(ev.get('value'))} "
                f"burn={_fmt(ev.get('burn_fast'), 1)}"
                f"/{_fmt(ev.get('burn_slow'), 1)}")
        cells = _signal_cells(rec)
        if cells:
            lines.append("  signals: " + "  ".join(cells[:topk]))
    mons = rec.get("monitors", {})
    rates = rec.get("rates", {})
    serving = rec.get("serving", {})

    def _serving_lines(tname: str) -> list:
        """Serving panel for one table: per-replica lag (epochs +
        seconds vs the advertised bound), cache hit rate, shed rate,
        and served QPS when consecutive polls derived rates."""
        s = serving.get(tname)
        if not s:
            return []
        sr = s.get("rates") or {}
        head = (f"  serving: replicas={len(s.get('replicas', {}))}"
                f"  served {s.get('served', 0)}"
                + (f" ({_fmt(sr.get('served_per_s'), 1)}/s)"
                   if sr else "")
                + f"  shed {s.get('shed', 0)}"
                + (f" ({_fmt(sr.get('shed_per_s'), 1)}/s)" if sr else "")
                + (f"  shed_rate {s['shed_rate'] * 100:.1f}%"
                   if s.get("shed_rate") is not None else "")
                + (f"  cache_hit {s['cache_hit_rate'] * 100:.1f}%"
                   if s.get("cache_hit_rate") is not None else ""))
        out = [head]
        for r in sorted(s.get("replicas", {}), key=str):
            e = s["replicas"][r]
            out.append(
                f"    replica@rank{r}: epoch {_fmt(e.get('epoch'))}"
                f"  lag {_fmt(e.get('age_s'))}s"
                f"/{_fmt(e.get('bound_s'))}s bound"
                f"  refresh {_fmt(e.get('refresh_ms'), 1)} ms"
                f"  cache {_fmt(e.get('cache_rows'))} rows"
                + (f" ({e['cache_hit_rate'] * 100:.1f}% hit)"
                   if e.get("cache_hit_rate") is not None else ""))
        # pool panel (serving/pool.py via the aggregator's serving
        # merge): per-member route share, staleness lag, degraded flag
        for r in sorted(s.get("pools", {}), key=str):
            p = s["pools"][r]
            out.append(
                f"    pool@rank{r}: active {_fmt(p.get('active'))}"
                f"  degraded {_fmt(p.get('degraded'))}"
                f"  spares {_fmt(p.get('spares_left'))}"
                f"  failovers {_fmt(p.get('failovers'))}"
                f"  demotions {_fmt(p.get('demotions'))}")
            for m in p.get("members", []):
                share = m.get("share")
                state = ("DEGRADED" if m.get("degraded")
                         else "active" if m.get("active") else "spare")
                out.append(
                    f"      member {m.get('idx')}: {state}"
                    + ("  share -" if share is None
                       else f"  share {share * 100:.1f}%")
                    + f"  lag {_fmt(m.get('age_s'))}s"
                    + f"  routed {_fmt(m.get('routed'))}"
                    + f"  pull_fail {_fmt(m.get('pull_failures'))}")
        return out

    for tname in sorted(rec.get("tables", {})):
        t = rec["tables"][tname]
        lines.append("")
        lines.append(f"table[{tname}]  shards={len(t.get('shards', {}))}"
                     f"  skew={_fmt(t.get('skew'))}"
                     f"  queue={t.get('queue_depth', 0)}")
        tr = rates.get(tname)
        if tr:
            lines.append(
                f"  rates: adds {tr['adds_per_s']}/s  gets "
                f"{tr['gets_per_s']}/s  applies {tr['applies_per_s']}/s  "
                f"wire {_mb(tr['wire_bytes_per_s'])}  "
                f"queue Δ{tr['queue_depth_delta']}"
                + (f"  skew(window) {tr['skew_window']}"
                   if "skew_window" in tr else ""))
        lines.append(f"  totals: adds {t.get('adds', 0)}  gets "
                     f"{t.get('gets', 0)}  applies {t.get('applies', 0)}  "
                     f"wire {((t.get('add_bytes', 0) or 0) + (t.get('get_bytes', 0) or 0)) / 1e6:.2f} MB")
        # merged latency percentiles: shard apply + the serve monitor
        a = t.get("apply") or {}
        parts = []
        if a.get("timed"):
            parts.append(f"apply p50 {_fmt(a.get('p50_ms'))} "
                         f"p99 {_fmt(a.get('p99_ms'))} ms")
        srv = mons.get(f"ps[{tname}].serve")
        if srv and srv.get("timed"):
            parts.append(f"serve p50 {_fmt(srv.get('p50_ms'))} "
                         f"p99 {_fmt(srv.get('p99_ms'))} ms")
        if parts:
            lines.append("  " + "  |  ".join(parts))
        # shard-placement panel (mesh data plane, ps/spmd.py): shard ->
        # rank / row range / device + each shard's share of the table's
        # applies, so skew from bad placement is visible live. The
        # "spmd" block (stacked groups) names the slot's device and its
        # share of grouped SPMD dispatches; classic shards render their
        # apply share from the plain per-shard counters.
        shards = t.get("shards") or {}
        srows = [(r, s) for r, s in shards.items()
                 if isinstance(s, dict) and s.get("kind") == "row"]
        if len(srows) > 1:
            tot = sum(int(s.get("applies") or 0) for _r, s in srows)
            cells = []
            for r, s in sorted(srows, key=lambda kv: str(kv[0])):
                sp = s.get("spmd") or {}
                lo = s.get("lo", 0)
                hi = lo + (s.get("rows") or 0)
                ap = int(s.get("applies") or 0)
                share = f"{ap / tot * 100:.0f}%" if tot else "-"
                dev = sp.get("device") or "classic"
                slot = (f" slot{sp.get('slot')}"
                        if sp.get("slot") is not None else "")
                cells.append(f"r{r}[{lo}-{hi}]@{dev}{slot} {share}")
            lines.append("  placement: " + "  ".join(cells))
            sp0 = next((s.get("spmd") for _r, s in srows
                        if s.get("spmd")), None)
            if sp0:
                lines.append(
                    f"  spmd group: {sp0.get('members')} shards stacked"
                    f"  dispatches {sp0.get('dispatches')}"
                    f"  stack {(sp0.get('stack_bytes') or 0) / 1e6:.2f}"
                    " MB")
        hk = rec.get("hotkeys", {}).get(tname)
        if hk and hk.get("top"):
            head = "  ".join(f"{k}:{c}" for k, c, _ in hk["top"][:topk])
            lines.append(f"  hot rows (of {hk.get('total', 0)} sketched): "
                         f"{head}")
            curve = hk.get("hit_rate_curve") or []
            if curve:
                lines.append("  cache-hit-if-cached: " + "  ".join(
                    f"top{k}={r * 100:.0f}%" for k, r in curve))
        lines.extend(_serving_lines(tname))
    # replicas of tables with no shard visible in this poll (a serving
    # sidecar whose owners did not answer) still render
    for tname in sorted(set(serving) - set(rec.get("tables", {}))):
        lines.append("")
        lines.append(f"table[{tname}]  (serving only)")
        lines.extend(_serving_lines(tname))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mvtop", description="live async-PS cluster view")
    ap.add_argument("--rdv", required=True,
                    help="file-rendezvous directory (<rank>.addr files)")
    ap.add_argument("--world", type=int, default=None,
                    help="rank count (default: every published addr)")
    ap.add_argument("--once", action="store_true",
                    help="one snapshot, then exit (scripts/tests)")
    ap.add_argument("--watch", type=float, nargs="?", const=2.0,
                    default=None, metavar="SECONDS",
                    help="refresh every SECONDS (default 2) until ^C")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw merged cluster record")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-rank probe timeout seconds")
    ap.add_argument("--topk", type=int, default=8,
                    help="hot keys shown per table")
    ap.add_argument("--assert-slo", action="store_true",
                    help="with --once: exit 3 iff any SLO objective is "
                         "firing (CI gate on the sentinel verdict)")
    args = ap.parse_args(argv)

    addrs = read_addrs(args.rdv, args.world)
    if not addrs:
        print(f"mvtop: no <rank>.addr files under {args.rdv}",
              file=sys.stderr)
        return 2
    if args.once or args.watch is None:
        rec = poll(addrs, args.timeout)
        print(json.dumps(rec) if args.json
              else render(rec, topk=args.topk))
        up = sum(1 for e in rec.get("ranks", {}).values()
                 if e.get("status") not in (None, "unreachable"))
        if args.assert_slo:
            firing = (rec.get("slo") or {}).get("firing") or []
            if firing:
                print("mvtop: SLO firing: " + ",".join(firing),
                      file=sys.stderr)
                return 3
        return 0 if up else 1
    prev = None
    try:
        while True:
            addrs = read_addrs(args.rdv, args.world) or addrs
            rec = poll(addrs, args.timeout)
            # rates belong to the RECORD, not the renderer: --json
            # consumers get the same consecutive-poll rates block the
            # table view shows
            if prev is not None:
                from multiverso_tpu.telemetry import aggregator
                aggregator.derive_rates(prev, rec)
            if args.json:
                # machine-readable stream: one record per line, no
                # screen-clear escapes corrupting the JSON
                out = json.dumps(rec)
                sys.stdout.write(out + "\n")
            else:
                sys.stdout.write("\x1b[2J\x1b[H"
                                 + render(rec, topk=args.topk) + "\n")
            sys.stdout.flush()
            prev = rec
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
