#!/usr/bin/env python
"""Static lint of the observability surface (tier-1 wraps this).

PR 8 shipped a new wire opcode (MSG_SNAPSHOT) with no flight-recorder
or trace coverage, and earlier PRs have shipped flags with no
docs/TUNING.md row — both slipped because nothing asked the question at
review time. This tool asks it mechanically:

1. **Every ``MSG_*`` opcode** defined in ``ps/service.py`` /
   ``ps/wire.py`` must have an entry in
   ``telemetry/flightrec.MSG_EV_COVERAGE`` naming the ring events that
   mark its lifecycle (an explicit EMPTY tuple is allowed — probe
   traffic is deliberately off the tape — but it must be stated, not
   forgotten), and every event named must exist in ``EV_NAMES``.
2. **Every flag** registered via ``config.define_*`` anywhere under
   ``multiverso_tpu/`` must appear in ``docs/TUNING.md`` — a knob an
   operator cannot discover is a knob that does not exist.

    python tools/check_obs_surface.py        # exit 0 clean, 1 findings

Run by ``tests/test_profiler.py`` in tier-1, so a PR adding an opcode
or flag without its observability/doc surface fails CI, not review.
"""

from __future__ import annotations

import os
import re
import sys
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# trailing comments allowed: this codebase styles constants as
# "MSG_X = 0x1E  # what it is", and a commented definition escaping the
# scan would re-open the exact crack this tool closes
_MSG_RE = re.compile(
    r"^(MSG_[A-Z_0-9]+)\s*=\s*(?:0x[0-9a-fA-F]+|\d+)\s*(?:#.*)?$", re.M)
_FLAG_RE = re.compile(
    r"""define_(?:bool|int|float|string)\(\s*['"]([^'"]+)['"]""")


def wire_opcodes() -> List[str]:
    """MSG_* names defined in the wire/service layer (source scan — the
    lint must see an opcode the moment it is committed, imported
    anywhere or not)."""
    names: List[str] = []
    for rel in ("multiverso_tpu/ps/service.py", "multiverso_tpu/ps/wire.py"):
        with open(os.path.join(_REPO, rel)) as f:
            names += _MSG_RE.findall(f.read())
    return sorted(set(names))


def defined_flags() -> List[str]:
    """Every config.define_* flag name under multiverso_tpu/."""
    names: List[str] = []
    for root, _dirs, files in os.walk(os.path.join(_REPO, "multiverso_tpu")):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(root, fn)) as f:
                names += _FLAG_RE.findall(f.read())
    return sorted(set(names))


def check() -> List[str]:
    """All findings as human-readable strings ([] = clean)."""
    findings: List[str] = []
    from multiverso_tpu.telemetry import flightrec

    cov = flightrec.MSG_EV_COVERAGE
    for op in wire_opcodes():
        if op not in cov:
            findings.append(
                f"{op}: no flightrec.MSG_EV_COVERAGE entry — name the "
                "ring events marking its lifecycle (or an explicit () "
                "with the probe-exclusion reason)")
            continue
        for ev in cov[op]:
            if ev not in flightrec.EV_NAMES:
                findings.append(
                    f"{op}: coverage names unknown event id {ev!r} "
                    "(not in flightrec.EV_NAMES)")
    stale = sorted(set(cov) - set(wire_opcodes()))
    for op in stale:
        findings.append(
            f"{op}: MSG_EV_COVERAGE entry for an opcode that no longer "
            "exists in ps/service.py or ps/wire.py")

    with open(os.path.join(_REPO, "docs", "TUNING.md")) as f:
        tuning = f.read()
    for flag in defined_flags():
        # a flag is "documented" when its name appears anywhere in
        # TUNING.md (knob row, prose, or the wiring-flag table)
        if flag not in tuning:
            findings.append(
                f"flag {flag!r}: not mentioned in docs/TUNING.md — add "
                "a knob row (or a wiring-flags table entry)")
    return findings


def main(argv=None) -> int:
    findings = check()
    for f in findings:
        print(f"OBS-SURFACE: {f}")
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    print(f"observability surface clean: "
          f"{len(wire_opcodes())} opcodes covered, "
          f"{len(defined_flags())} flags documented")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
