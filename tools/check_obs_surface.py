#!/usr/bin/env python
"""Static lint of the observability surface (tier-1 wraps this).

PR 8 shipped a new wire opcode (MSG_SNAPSHOT) with no flight-recorder
or trace coverage, and earlier PRs have shipped flags with no
docs/TUNING.md row — both slipped because nothing asked the question at
review time. This tool asks it mechanically:

1. **Every ``MSG_*`` opcode** defined in ``ps/service.py`` /
   ``ps/wire.py`` must have an entry in
   ``telemetry/flightrec.MSG_EV_COVERAGE`` naming the ring events that
   mark its lifecycle (an explicit EMPTY tuple is allowed — probe
   traffic is deliberately off the tape — but it must be stated, not
   forgotten), and every event named must exist in ``EV_NAMES``.
2. **Every flag** registered via ``config.define_*`` anywhere under
   ``multiverso_tpu/`` must appear in ``docs/TUNING.md`` — a knob an
   operator cannot discover is a knob that does not exist.
3. **Every top-level key** the stats surface emits — the shard
   ``stats()`` methods, ``PSService.stats_payload``, the exporter's
   ``default_stats_fn``, and the memstats ``"memory"`` block — must be
   RENDERED by at least one of ``tools/mvtop.py`` /
   ``tools/dump_metrics.py`` (its quoted name appears in their
   source), or sit on the explicit raw-key allowlist. This is the
   exact crack that would let a new stats block ship and go dark: the
   payload grows a key, no pane of glass ever shows it, and the next
   leak's evidence is emitted into the void.

    python tools/check_obs_surface.py        # exit 0 clean, 1 findings

Run by ``tests/test_profiler.py`` in tier-1, so a PR adding an opcode,
flag, or stats key without its observability/doc surface fails CI, not
review.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# trailing comments allowed: this codebase styles constants as
# "MSG_X = 0x1E  # what it is", and a commented definition escaping the
# scan would re-open the exact crack this tool closes
_MSG_RE = re.compile(
    r"^(MSG_[A-Z_0-9]+)\s*=\s*(?:0x[0-9a-fA-F]+|\d+)\s*(?:#.*)?$", re.M)
_FLAG_RE = re.compile(
    r"""define_(?:bool|int|float|string)\(\s*['"]([^'"]+)['"]""")


def wire_opcodes() -> List[str]:
    """MSG_* names defined in the wire/service layer (source scan — the
    lint must see an opcode the moment it is committed, imported
    anywhere or not)."""
    names: List[str] = []
    for rel in ("multiverso_tpu/ps/service.py", "multiverso_tpu/ps/wire.py"):
        with open(os.path.join(_REPO, rel)) as f:
            names += _MSG_RE.findall(f.read())
    return sorted(set(names))


def defined_flags() -> List[str]:
    """Every config.define_* flag name under multiverso_tpu/."""
    names: List[str] = []
    for root, _dirs, files in os.walk(os.path.join(_REPO, "multiverso_tpu")):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(root, fn)) as f:
                names += _FLAG_RE.findall(f.read())
    return sorted(set(names))


# ---------------------------------------------------------------------- #
# stats-surface rule (lint 3): emitted keys vs the rendering tools
# ---------------------------------------------------------------------- #
# (file, function) pairs whose emitted top-level keys ARE the stats
# surface — jax-free ast scans, so the lint runs on a bare host
_STATS_SOURCES = (
    ("multiverso_tpu/ps/shard.py", "stats"),
    ("multiverso_tpu/ps/service.py", "stats_payload"),
    ("multiverso_tpu/telemetry/exporter.py", "default_stats_fn"),
    ("multiverso_tpu/telemetry/memstats.py", "stats_snapshot"),
)
_RENDERERS = ("tools/mvtop.py", "tools/dump_metrics.py")

# intentionally raw keys: shard-stat SCALARS whose only rendering is
# dump_metrics' generic "k=v" shard join (format_record prints every
# shard key, so a first-class column would add nothing), plus process
# identity plumbing. New BLOCK keys (serving/profile/memory-style)
# never belong here — blocks are structured, not generically joined,
# and an unrendered block is exactly what this lint exists to catch.
_STATS_RAW_KEYS = frozenset({
    "kind", "lo", "rows", "cols", "bytes", "version", "wave_ops",
    "wave_max_ops", "get_chunks", "cow_applies", "read_pins",
    "dup_frames", "replay_clients", "snapshots", "snapshots_unchanged",
    "dirty_rows", "keys", "pending_bytes",
    "pid",   # the aggregator's (host, pid) process-dedupe token
})


def stats_keys(rel_path: str, func: str,
               repo: str = _REPO) -> List[str]:
    """Top-level string keys emitted by every function named ``func``
    in ``rel_path``: dict-literal keys, ``.update(k=...)`` keyword
    args, ``.setdefault("k", ...)``, and ``x["k"] = ...`` subscript
    assigns. Over-approximates (nested literals count too) — a spare
    entry costs one allowlist line, a missed one costs a dark key."""
    with open(os.path.join(repo, rel_path)) as f:
        tree = ast.parse(f.read())
    keys = set()
    for node in ast.walk(tree):
        if not (isinstance(node, (ast.FunctionDef,
                                  ast.AsyncFunctionDef))
                and node.name == func):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict):
                for k in sub.keys:
                    if (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        keys.add(k.value)
            elif isinstance(sub, ast.Call):
                fn = sub.func
                if isinstance(fn, ast.Attribute) \
                        and fn.attr in ("update", "setdefault"):
                    for kw in sub.keywords:
                        if kw.arg:
                            keys.add(kw.arg)
                    if (fn.attr == "setdefault" and sub.args
                            and isinstance(sub.args[0], ast.Constant)
                            and isinstance(sub.args[0].value, str)):
                        keys.add(sub.args[0].value)
            elif isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if (isinstance(tgt, ast.Subscript)
                            and isinstance(tgt.slice, ast.Constant)
                            and isinstance(tgt.slice.value, str)):
                        keys.add(tgt.slice.value)
    return sorted(keys)


def stats_surface_findings(
        keys_by_src: Dict[str, List[str]] = None,
        renderer_text: str = None,
        allow: frozenset = _STATS_RAW_KEYS) -> List[str]:
    """Lint 3 proper: every emitted key must appear quoted in a
    renderer's source or on the allowlist. Parameters are injectable
    so tests can prove the rule CATCHES a fabricated dark key."""
    if keys_by_src is None:
        keys_by_src = {f"{path}:{func}()": stats_keys(path, func)
                       for path, func in _STATS_SOURCES}
    if renderer_text is None:
        renderer_text = ""
        for rel in _RENDERERS:
            with open(os.path.join(_REPO, rel)) as f:
                renderer_text += f.read()
    findings = []
    for src, keys in sorted(keys_by_src.items()):
        for key in keys:
            if key in allow:
                continue
            if f'"{key}"' in renderer_text or f"'{key}'" in renderer_text:
                continue
            findings.append(
                f"stats key {key!r} (emitted by {src}): rendered by "
                "neither tools/mvtop.py nor tools/dump_metrics.py — "
                "add a panel/row (or an explicit raw-key allowlist "
                "entry) so the block cannot go dark")
    return findings


# ---------------------------------------------------------------------- #
# collective-coverage rule (lint 4): device-plane ops must record
# ---------------------------------------------------------------------- #
# (file, mode): "all" = every public top-level function is a collective
# entry point and must record; "shard_map" = only public functions that
# dispatch through the mesh (call _shard_map/shard_map) must. moe.py /
# pipeline.py / worker_map.py join this table when they grow spans.
_COLLECTIVE_SOURCES = (
    ("multiverso_tpu/parallel/collectives.py", "all"),
    ("multiverso_tpu/parallel/ring.py", "shard_map"),
    ("multiverso_tpu/parallel/tp.py", "shard_map"),
)
# a function "records" when its body calls one of these devstats sites
_RECORDING_CALLS = frozenset({"collective_span", "note_transfer"})


def _called_names(node: ast.AST) -> set:
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = sub.func
            if isinstance(fn, ast.Attribute):
                out.add(fn.attr)
            elif isinstance(fn, ast.Name):
                out.add(fn.id)
    return out


def collective_coverage_findings(
        sources=_COLLECTIVE_SOURCES,
        source_text: Dict[str, str] = None) -> List[str]:
    """Lint 4: every collective entry point in ``parallel/`` must wrap
    its dispatch in ``devstats.collective_span`` (or count through
    ``note_transfer``) — the exact MSG_SNAPSHOT crack for the device
    plane: a new collective op shipping with no span is invisible to
    mvtop/flightrec/the scale harness. ``source_text`` injects
    {rel_path: source} so tests can prove the rule catches a dark op."""
    findings = []
    for rel, mode in sources:
        if source_text is not None and rel in source_text:
            src = source_text[rel]
        else:
            with open(os.path.join(_REPO, rel)) as f:
                src = f.read()
        for node in ast.parse(src).body:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                    or node.name.startswith("_"):
                continue
            calls = _called_names(node)
            if mode == "shard_map" \
                    and not calls & {"_shard_map", "shard_map"}:
                continue   # host-side helper, not a mesh dispatch
            if not calls & _RECORDING_CALLS:
                findings.append(
                    f"collective {rel}:{node.name}(): dispatches on the "
                    "mesh with no devstats recording site — wrap the "
                    "dispatch in devstats.collective_span (or count it "
                    "via note_transfer) so the op cannot ship dark")
    return findings


# ---------------------------------------------------------------------- #
# tenant-surface rule (lint 6): the MSG_STATS "tenants" block renders
# ---------------------------------------------------------------------- #
# (file, function) pairs whose emitted keys ARE the tenant block: the
# ledger's stats_snapshot (block structure + per-(table, tenant)
# counters), the shard meter's counter shape (note builds the entry
# dicts, to_dict adds the sketch key), and the admission controller's
# per-tenant budget entries that ride the block's "admission" map.
_TENANT_SOURCES = (
    ("multiverso_tpu/telemetry/tenants.py", "stats_snapshot"),
    ("multiverso_tpu/telemetry/tenants.py", "note"),
    ("multiverso_tpu/telemetry/tenants.py", "to_dict"),
    ("multiverso_tpu/serving/admission.py", "tenant_stats"),
)


def tenant_surface_findings(keys_by_src: Dict[str, List[str]] = None,
                            renderer_text: str = None) -> List[str]:
    """Lint 6: every key the tenants block emits must appear quoted in
    ``tools/mvtop.py`` or ``tools/dump_metrics.py`` — the lint-3 rule
    applied to the tenant plane with NO allowlist: per-tenant evidence
    that no pane of glass shows is exactly how a noisy-neighbor episode
    goes dark. Injectable so tests can prove the rule catches a
    fabricated dark key."""
    if keys_by_src is None:
        keys_by_src = {f"{path}:{func}()": stats_keys(path, func)
                       for path, func in _TENANT_SOURCES}
    if renderer_text is None:
        renderer_text = ""
        for rel in _RENDERERS:
            with open(os.path.join(_REPO, rel)) as f:
                renderer_text += f.read()
    findings = []
    for src, keys in sorted(keys_by_src.items()):
        for key in keys:
            if f'"{key}"' in renderer_text or f"'{key}'" in renderer_text:
                continue
            findings.append(
                f"tenant stats key {key!r} (emitted by {src}): rendered "
                "by neither tools/mvtop.py nor tools/dump_metrics.py — "
                "add it to the tenant panel/table so per-tenant "
                "evidence cannot go dark")
    return findings


# ---------------------------------------------------------------------- #
# slo-surface rule (lint 7): every objective kind and bus signal renders
# ---------------------------------------------------------------------- #
# module-level string-tuple registries that ARE the SLO surface: the
# sentinel's objective kinds and the signal bus's published names. Read
# by ast (no import — slo.py pulls in the config plane, and this lint
# must run on a bare host).
_SLO_REGISTRIES = (
    ("multiverso_tpu/telemetry/slo.py", "OBJECTIVE_KINDS",
     "SLO objective kind"),
    ("multiverso_tpu/telemetry/signals.py", "SIGNAL_NAMES",
     "signal-bus name"),
)


def module_tuple(rel_path: str, name: str,
                 repo: str = _REPO) -> List[str]:
    """The strings of a module-level ``NAME = ("a", "b", ...)`` tuple
    assignment, read by ast so the lint sees the registry the moment
    it is committed, importable or not."""
    with open(os.path.join(repo, rel_path)) as f:
        tree = ast.parse(f.read())
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if any(isinstance(t, ast.Name) and t.id == name
               for t in node.targets):
            return [str(v) for v in ast.literal_eval(node.value)]
    return []


def slo_surface_findings(kinds: List[str] = None,
                         signal_names: List[str] = None,
                         renderer_text: str = None) -> List[str]:
    """Lint 7: every objective kind ``telemetry/slo.py`` can judge and
    every signal name ``telemetry/signals.py`` can publish must appear
    quoted in ``tools/mvtop.py`` or ``tools/dump_metrics.py`` — the
    lint-3 rule applied to the SLO plane with NO allowlist: an
    objective kind no pane can show is a verdict into the void, and a
    bus signal nothing renders is an autoscaling input no operator can
    audit. Injectable so tests can prove the rule catches a fabricated
    dark kind."""
    if kinds is None:
        kinds = module_tuple(*_SLO_REGISTRIES[0][:2])
    if signal_names is None:
        signal_names = module_tuple(*_SLO_REGISTRIES[1][:2])
    if renderer_text is None:
        renderer_text = ""
        for rel in _RENDERERS:
            with open(os.path.join(_REPO, rel)) as f:
                renderer_text += f.read()
    findings = []
    for label, (rel, _reg, what) in (("kind", _SLO_REGISTRIES[0]),
                                     ("signal", _SLO_REGISTRIES[1])):
        names = kinds if label == "kind" else signal_names
        for key in names:
            if f'"{key}"' in renderer_text or f"'{key}'" in renderer_text:
                continue
            findings.append(
                f"{what} {key!r} (declared in {rel}): rendered by "
                "neither tools/mvtop.py nor tools/dump_metrics.py — "
                "add it to the SLO panel / _slo_lines table so the "
                "sentinel's verdicts cannot go dark")
    return findings


# ---------------------------------------------------------------------- #
# regression-key rule (lint 5): every tracked bench key has a producer
# ---------------------------------------------------------------------- #
def regression_paths(repo: str = _REPO) -> List[tuple]:
    """The extra.* paths ``tools/run_bench.py`` compares run-over-run,
    read from its ``_REGRESSION_KEYS`` / ``_REGRESSION_KEYS_HIGHER``
    tables by ast (no import: run_bench pulls in bench.py, which this
    jax-free lint must not)."""
    with open(os.path.join(repo, "tools", "run_bench.py")) as f:
        tree = ast.parse(f.read())
    paths: List[tuple] = []
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        names = {t.id for t in node.targets
                 if isinstance(t, ast.Name)}
        if not names & {"_REGRESSION_KEYS", "_REGRESSION_KEYS_HIGHER"}:
            continue
        for path, _label in ast.literal_eval(node.value):
            paths.append(tuple(path))
    return paths


def regression_key_findings(paths=None,
                            producer_text: str = None) -> List[str]:
    """Lint 5: every component of every run_bench regression path must
    appear QUOTED in a producer source (bench.py or a tools/bench_*.py)
    — a bench key renamed without updating run_bench leaves the old
    path in the comparison tables matching nothing, silently disarming
    its regression flag forever. Injectable for the catches-a-disarmed-
    key test."""
    if paths is None:
        paths = regression_paths()
    if producer_text is None:
        producer_text = ""
        # bench.py + every tools/bench_*.py worker, plus the library
        # modules bench.py delegates whole extra blocks to (memstats.
        # bench_extra writes extra.memory's keys)
        with open(os.path.join(_REPO, "bench.py")) as f:
            producer_text += f.read()
        with open(os.path.join(
                _REPO, "multiverso_tpu", "telemetry",
                "memstats.py")) as f:
            producer_text += f.read()
        tdir = os.path.join(_REPO, "tools")
        for fn in sorted(os.listdir(tdir)):
            if fn.startswith("bench_") and fn.endswith(".py"):
                with open(os.path.join(tdir, fn)) as f:
                    producer_text += f.read()
    findings = []
    for path in paths:
        missing = [k for k in path
                   if f'"{k}"' not in producer_text
                   and f"'{k}'" not in producer_text]
        if missing:
            findings.append(
                f"regression key extra.{'.'.join(path)} "
                f"(tools/run_bench.py): component(s) {missing} never "
                "produced by bench.py or any tools/bench_*.py — the "
                "run-over-run flag is disarmed; rename the table entry "
                "or restore the producer")
    return findings


def check() -> List[str]:
    """All findings as human-readable strings ([] = clean)."""
    findings: List[str] = []
    from multiverso_tpu.telemetry import flightrec

    cov = flightrec.MSG_EV_COVERAGE
    for op in wire_opcodes():
        if op not in cov:
            findings.append(
                f"{op}: no flightrec.MSG_EV_COVERAGE entry — name the "
                "ring events marking its lifecycle (or an explicit () "
                "with the probe-exclusion reason)")
            continue
        for ev in cov[op]:
            if ev not in flightrec.EV_NAMES:
                findings.append(
                    f"{op}: coverage names unknown event id {ev!r} "
                    "(not in flightrec.EV_NAMES)")
    stale = sorted(set(cov) - set(wire_opcodes()))
    for op in stale:
        findings.append(
            f"{op}: MSG_EV_COVERAGE entry for an opcode that no longer "
            "exists in ps/service.py or ps/wire.py")

    with open(os.path.join(_REPO, "docs", "TUNING.md")) as f:
        tuning = f.read()
    for flag in defined_flags():
        # a flag is "documented" when its name appears anywhere in
        # TUNING.md (knob row, prose, or the wiring-flag table)
        if flag not in tuning:
            findings.append(
                f"flag {flag!r}: not mentioned in docs/TUNING.md — add "
                "a knob row (or a wiring-flags table entry)")
    findings.extend(stats_surface_findings())
    findings.extend(collective_coverage_findings())
    findings.extend(regression_key_findings())
    findings.extend(tenant_surface_findings())
    findings.extend(slo_surface_findings())
    return findings


def main(argv=None) -> int:
    findings = check()
    for f in findings:
        print(f"OBS-SURFACE: {f}")
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    nkeys = sum(len(stats_keys(p, fn)) for p, fn in _STATS_SOURCES)
    print(f"observability surface clean: "
          f"{len(wire_opcodes())} opcodes covered, "
          f"{len(defined_flags())} flags documented, "
          f"{nkeys} stats keys rendered/allowlisted")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
