#!/usr/bin/env python
"""Run bench.py and record its headline with truncation status.

The bench prints ONE JSON line and exits 0 when complete; a driver-side
timeout SIGTERM triggers the salvage handler, which still prints the
headline but exits ``bench.TRUNCATED_EXIT`` (75). This wrapper is the
recording side of that contract: it re-runs the bench unchanged,
captures the last JSON line, and writes it (default ``BENCH_RUN.json``)
with an explicit ``truncated`` key derived from the exit status — so a
timeout-truncated record can never masquerade as a complete run.

    python tools/run_bench.py [-o BENCH_RUN.json] [-- extra bench args]

Exit status mirrors the bench's own (0 complete, 75 truncated-but-
salvaged, anything else = failed).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from bench import TRUNCATED_EXIT  # noqa: E402


def last_json_line(text: str):
    """The bench contract: the headline is the last parseable JSON line."""
    for line in reversed(text.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def record(returncode: int, stdout: str, flightrec_dumps=()) -> dict:
    """Shape a bench run into the recorded artifact (pure: testable
    without spawning the real 20-minute bench). ``flightrec_dumps`` is
    the dump-file listing produced during the run — the postmortem entry
    point: each dump's header names its reason (routine Zoo.stop tape
    vs. a watchdog trip / peer death / SIGTERM salvage), so a truncated
    or faulted run is diagnosable from the recorded artifact alone."""
    headline = last_json_line(stdout)
    # truncated iff the salvage path exited, OR the headline itself
    # carries the salvage marker (belt: a wrapper that lost the exit
    # status must still never record a truncated run as complete)
    truncated = (returncode == TRUNCATED_EXIT
                 or bool((headline or {}).get("extra", {})
                         .get("truncated")))
    return {
        "returncode": returncode,
        "truncated": truncated,
        # never both: the belt case (exit status lost, salvage marker
        # present) must read as truncated, not complete
        "complete": returncode == 0 and not truncated,
        "flightrec_dumps": sorted(flightrec_dumps),
        "headline": headline,
    }


# bench-extra latency keys compared run-over-run: (path into headline
# "extra", human label). Lower is better for all of them.
_REGRESSION_KEYS = (
    (("get_rows_plane", "small_get_on_p50_ms"), "coalesced small-get p50"),
    (("get_rows_plane", "small_get_off_p50_ms"), "plain small-get p50"),
    (("get_rows_plane", "big_get_chunked_ms"), "chunked big-get"),
    (("small_add_send_window", "window_on_p50_ms"), "windowed small-add p50"),
    # elastic failover: recovery-time-to-90%-throughput after a
    # SIGKILLed shard (tools/bench_chaos.py) — flagged like the skew
    # growth, never failed: box weather moves it, but a silent 2x
    # slide in how long a shard stays dark must reach the next session
    (("chaos", "recovery_s"), "chaos failover recovery time"),
    # online-serving plane (tools/bench_serving.py): inference tail
    # latency against the bounded-staleness replica
    (("serving", "infer_p99_ms"), "serving inference p99"),
    # tenant attribution plane (ISSUE 18): the VICTIM tenant's tail
    # latency and shed rate out of extra.serving.tenants — growth here
    # with the aggregate p99 holding is exactly the noisy-neighbor
    # signature the tenant plane exists to surface. Flagged, never
    # failed, like every band; the shed rate compares against a
    # floored baseline (see _REGRESSION_BASELINE_FLOORS)
    (("serving", "tenants", "victim", "infer_p99_ms"),
     "victim-tenant serving p99"),
    (("serving", "tenants", "victim", "shed_rate"),
     "victim-tenant shed rate"),
    # memory plane (ISSUE 10): peak process RSS over the whole bench
    # (VmHWM — kernel-tracked, no sampling cadence can under-read it).
    # Growth is a regression like latency growth: higher is worse, so
    # it rides the standard lower-is-better table
    (("memory", "peak_rss_mb"), "bench peak RSS"),
)

# healthy fully-attributed runs record stall_fraction ~0.0 — the
# `old <= 0` guard in the ratio loop (written for impossible-zero
# latencies) would then suppress stall-growth flags forever, so the
# stall comparison floors the baseline at this value instead (a new
# stall above 2 x 5% flags even against a perfect-zero prior)
_STALL_BASELINE_FLOOR = 0.05

# per-path baseline floors for the lower-is-better table: a healthy
# run records ~0 victim-tenant sheds (steady is paced inside the
# budget; overload sheds mostly land on the storm workers), and the
# `old <= 0` guard below would then suppress shed-growth flags forever
# — so these paths compare against max(prev, floor) instead, the same
# directionality fix as the stall fraction
_REGRESSION_BASELINE_FLOORS = {
    ("serving", "tenants", "victim", "shed_rate"): 0.05,
}

# replay retained-frame bytes: a healthy run with a live failover
# checkpointer records ~0 here (frames prune at the durable floor), so
# the `old <= 0` ratio guard would suppress retention-growth flags
# forever — same directionality fix as the stall floor: the baseline
# floors at 1 MB and any new peak over 2 x max(prev, floor) flags
_RETAINED_BASELINE_FLOOR_BYTES = 1 << 20

# bench-extra keys where HIGHER is better: flagged when the new run
# DROPPED by more than the factor (the served-QPS mirror of the
# latency-growth flags above)
_REGRESSION_KEYS_HIGHER = (
    (("serving", "served_qps"), "serving served QPS"),
    # WE async-plane throughput (ISSUE 11): the ROADMAP item-2 scale
    # metric — a >2x words/s drop is the pipeline silently falling back
    # to serial prepare (or the training cache going cold), exactly the
    # regression the pipelined path was built to close
    (("we", "words_per_s"), "WE async words/s"),
    # mesh scale curve (ISSUE 12, tools/bench_scale.py): the weakest
    # E_n = T_n/(n*T_1) point of the 1->2->4->8 shard curve, and the
    # single-shard baseline itself. A drop in efficiency_min with t1
    # holding is a SCALING regression — per-shard cost growing with the
    # shard count — invisible to every single-rank latency key above
    (("scale", "efficiency_min"), "mesh scaling efficiency (min E_n)"),
    (("scale", "t1_rows_per_s"), "mesh scale single-shard baseline"),
    # per-shard-count efficiency points (ISSUE 15): the 2- and 4-shard
    # E_n recorded as first-class scalars by tools/bench_scale.py — a
    # drop at one point with the min holding (e.g. E_2 regressing
    # while E_8 stays the min) must still flag
    (("scale", "e2"), "mesh scaling efficiency E_2"),
    (("scale", "e4"), "mesh scaling efficiency E_4"),
)


def _extra_value(headline, path):
    node = (headline or {}).get("extra", {})
    for key in path:
        if not isinstance(node, dict):
            return None
        node = node.get(key)
    return node if isinstance(node, (int, float)) else None


def _cluster_skews(headline):
    """Per-table shard-skew values out of a headline's ``extra.cluster``
    record (written when the stats aggregator ran during the bench;
    both the bench main record and worker-reported ``cluster`` blocks
    use the same compact shape)."""
    out = {}
    nodes = [(headline or {}).get("extra", {}).get("cluster")]
    # worker-level cluster blocks (e.g. small_add_send_window.cluster)
    for sub in (headline or {}).get("extra", {}).values():
        if isinstance(sub, dict) and isinstance(sub.get("cluster"), dict):
            nodes.append(sub["cluster"])
    for node in nodes:
        if not isinstance(node, dict):
            continue
        for t, d in (node.get("tables") or {}).items():
            s = d.get("skew") if isinstance(d, dict) else None
            if isinstance(s, (int, float)) and not isinstance(s, bool):
                out[t] = s
    return out


def flag_regressions(prev_headline, new_headline, factor: float = 2.0):
    """Compare this run's recorded get/small-add latencies — and, when
    the cluster aggregator ran, per-table shard skew — against the
    PREVIOUS recorded bench file: anything more than ``factor``x worse
    is FLAGGED (returned as human-readable strings), never failed — the
    box's weather varies, and the flag exists so the next session sees
    the band moved, not to veto a run. Keys missing on either side
    (older record, errored sub-bench, no aggregator) are skipped."""
    out = []
    for path, label in _REGRESSION_KEYS:
        old = _extra_value(prev_headline, path)
        new = _extra_value(new_headline, path)
        if old is None or new is None:
            continue
        base = max(old, _REGRESSION_BASELINE_FLOORS.get(path, 0.0))
        if base <= 0:
            continue
        if new > factor * base:
            out.append(f"{label}: {new} vs {old} previously "
                       f"({new / base:.1f}x, flag threshold {factor}x)")
    # chaos scenario matrix (ISSUE 14, tools/bench_chaos.py): per-
    # scenario recovery_s growth, keyed by scenario name so a new
    # scenario joining the matrix starts its own trend — never fails,
    # like every flag; scenarios missing on either side are skipped
    def _scenarios(headline):
        node = ((headline or {}).get("extra", {}) or {}).get("chaos")
        sc = node.get("scenarios") if isinstance(node, dict) else None
        return sc if isinstance(sc, dict) else {}

    old_sc, new_sc = _scenarios(prev_headline), _scenarios(new_headline)
    if old_sc and new_sc:
        for name in sorted(set(old_sc) & set(new_sc)):
            o, n = old_sc[name], new_sc[name]
            if not (isinstance(o, dict) and isinstance(n, dict)):
                continue
            old_r, new_r = o.get("recovery_s"), n.get("recovery_s")
            if not isinstance(old_r, (int, float)) \
                    or not isinstance(new_r, (int, float)) \
                    or isinstance(old_r, bool) or isinstance(new_r, bool):
                continue
            # floored baseline (0.25 s = one rate bucket): a healthy
            # instant-recovery prior must not suppress the flag the
            # first time a scenario starts taking seconds
            base = max(old_r, 0.25)
            if new_r > factor * base:
                out.append(
                    f"chaos scenario '{name}' recovery: {new_r}s vs "
                    f"{old_r}s previously (flag threshold {factor}x "
                    "over max(prev, 0.25))")
    # higher-is-better keys (served QPS): a >factor DROP is the flag
    for path, label in _REGRESSION_KEYS_HIGHER:
        old = _extra_value(prev_headline, path)
        new = _extra_value(new_headline, path)
        if old is None or new is None or new <= 0:
            continue
        if old > factor * new:
            out.append(f"{label}: {new} vs {old} previously "
                       f"({old / new:.1f}x drop, flag threshold "
                       f"{factor}x)")
    # step-profiler stall fraction (ISSUE 9): wall time NO phase/span
    # claimed in the WE async measured epoch — the number that rises
    # when every latency monitor holds. Floored baseline (see
    # _STALL_BASELINE_FLOOR): 0.0 is the HEALTHY prior here, not a
    # skip-worthy missing measurement
    old_sf = _extra_value(prev_headline, ("profile", "stall_fraction"))
    new_sf = _extra_value(new_headline, ("profile", "stall_fraction"))
    if old_sf is not None and new_sf is not None:
        base = max(old_sf, _STALL_BASELINE_FLOOR)
        if new_sf > factor * base:
            out.append(f"WE step stall fraction: {new_sf} vs {old_sf} "
                       f"previously (flag threshold {factor}x over "
                       f"max(prev, {_STALL_BASELINE_FLOOR}))")
    # steady-state recompiles (step profiler): ANY nonzero count past
    # step 1 is flagged outright — not run-over-run — because a healthy
    # steady state compiles exactly zero times and a silent mid-run
    # retrace re-traces every step it touches (the worker also asserts
    # this in-run; the flag catches records produced by older workers)
    sr = _extra_value(new_headline, ("profile", "steady_recompiles"))
    if sr:
        out.append(f"steady-state recompiles: {sr} jit compiles "
                   "attributed past step 1 (expected 0; see "
                   "extra.profile and tools/mvprof.py)")
    # replay retained-frame bytes peak (memory plane): floored baseline
    # like the stall fraction — a healthy 0-byte prior must not
    # suppress the flag the first time a run starts hoarding frames
    old_rb = _extra_value(prev_headline, ("memory", "peak_retained_bytes"))
    new_rb = _extra_value(new_headline, ("memory", "peak_retained_bytes"))
    if old_rb is not None and new_rb is not None:
        base = max(old_rb, _RETAINED_BASELINE_FLOOR_BYTES)
        if new_rb > factor * base:
            out.append(
                f"replay retained-frame bytes peak: {new_rb} vs {old_rb} "
                f"previously (flag threshold {factor}x over max(prev, "
                f"{_RETAINED_BASELINE_FLOOR_BYTES}))")
    # shard-skew growth: a scale-out run whose row traffic collapsed
    # onto one shard is a regression even when every latency held
    old_skews, new_skews = (_cluster_skews(prev_headline),
                            _cluster_skews(new_headline))
    for t in sorted(set(old_skews) & set(new_skews)):
        old, new = old_skews[t], new_skews[t]
        if old > 0 and new > factor * old:
            out.append(f"table[{t}] shard skew: {new} vs {old} "
                       f"previously ({new / old:.1f}x, flag threshold "
                       f"{factor}x)")
    # SLO sentinel episodes (ISSUE 19, telemetry/slo.py): an objective
    # that FIRED this run but not in the previous recorded run is a new
    # burn — flagged by objective name so the next session reads which
    # promise broke, never failed (chaos scenarios fire objectives on
    # purpose; the comparison is run-over-run drift, not a veto). Both
    # sides need an extra.slo block (older records are skipped).
    def _slo_episodes(headline):
        node = ((headline or {}).get("extra", {}) or {}).get("slo")
        eps = node.get("episodes") if isinstance(node, dict) else None
        return eps if isinstance(eps, dict) else None

    old_eps, new_eps = (_slo_episodes(prev_headline),
                        _slo_episodes(new_headline))
    if old_eps is not None and new_eps is not None:
        for name in sorted(new_eps):
            n = new_eps[name]
            if not isinstance(n, (int, float)) or isinstance(n, bool) \
                    or n <= 0:
                continue
            if not old_eps.get(name):
                out.append(
                    f"SLO objective '{name}': {int(n)} episode(s) fired "
                    "this run, none in the previous recorded run (see "
                    "extra.slo and metrics alerts.jsonl)")
    return out


def history_entry(rec, out_path: str, ts: Optional[float] = None):
    """One compact BENCH_HISTORY.jsonl line from a recorded run (pure;
    tested without spawning the bench). The trajectory index exists
    because the bench trajectory was otherwise unreconstructable
    without globbing BENCH_r*.json by mtime: each run appends its
    headline value, verdicts, and every run_bench-tracked metric that
    was present, so `dump_metrics show BENCH_HISTORY.jsonl` renders the
    whole arc in one table."""
    headline = rec.get("headline") or {}
    metrics = {}
    for path, _label in (*_REGRESSION_KEYS, *_REGRESSION_KEYS_HIGHER):
        v = _extra_value(headline, path)
        if v is not None:
            metrics[".".join(path)] = v
    return {
        "ts": round(time.time() if ts is None else ts, 3),
        "record": os.path.basename(out_path),
        "complete": bool(rec.get("complete")),
        "truncated": bool(rec.get("truncated")),
        "value": headline.get("value"),
        "unit": headline.get("unit"),
        "vs_baseline": headline.get("vs_baseline"),
        "regressions": list(rec.get("regressions") or []),
        "metrics": metrics,
    }


def append_history(entry, history_path: str) -> None:
    """Append one entry to the trajectory index (one JSON object per
    line; the file is append-only — history is never rewritten)."""
    with open(history_path, "a") as f:
        f.write(json.dumps(entry) + "\n")


def collect_flightrec_dumps(directory: str, since: float = 0.0):
    """Dump files under a run's flight-recorder directory (basenames;
    [] when the directory never materialized — no dump was written).
    ``since`` (epoch seconds) excludes files older than the run being
    recorded: the directory is reused across runs, and a stale dump
    from run N-1 must not be attributed to run N."""
    if not os.path.isdir(directory):
        return []
    out = []
    for n in os.listdir(directory):
        if not (n.startswith("flightrec-") and n.endswith(".jsonl")):
            continue
        try:
            if os.path.getmtime(os.path.join(directory, n)) < since:
                continue
        except OSError:
            continue
        out.append(n)
    return sorted(out)


def main(argv) -> int:
    out_path = os.path.join(_REPO, "BENCH_RUN.json")
    if argv[:1] == ["-o"]:
        out_path, argv = argv[1], argv[2:]
    if argv[:1] == ["--"]:
        argv = argv[1:]
    # give the bench a dump directory so fault-time black boxes (SIGTERM
    # salvage, watchdog trips, peer deaths) land somewhere recordable; an
    # operator override via the env wins. Absolute: the bench child runs
    # with cwd=_REPO, and a relative -o path would make it dump where
    # the collector below never looks
    frdir = os.path.abspath(out_path) + ".flightrec"
    env = dict(os.environ)
    env.setdefault("MV_FLIGHTREC_DIR", frdir)
    # absolute EITHER way: a relative operator-supplied dir would
    # resolve against the bench child's cwd (_REPO) while the collector
    # below resolves it against THIS process's cwd — dumps written
    # where the listing never looks. An EMPTY value stays empty: that is
    # the documented "no dump files" setting, and abspath("") would
    # silently re-enable dumps into the collector's cwd
    if env["MV_FLIGHTREC_DIR"]:
        env["MV_FLIGHTREC_DIR"] = os.path.abspath(env["MV_FLIGHTREC_DIR"])
    start = time.time()
    proc = subprocess.run([sys.executable, os.path.join(_REPO, "bench.py"),
                           *argv], cwd=_REPO, capture_output=True,
                          text=True, env=env)
    # 2s slack: coarse-mtime filesystems floor a dump written just
    # after start below time.time()'s sub-second reading, and a real
    # fault dump filtered as "stale" is the diagnosability this exists
    # to provide
    rec = record(proc.returncode, proc.stdout,
                 collect_flightrec_dumps(env["MV_FLIGHTREC_DIR"],
                                         since=start - 2.0))
    if rec["headline"] is None:
        sys.stderr.write(proc.stderr[-2000:])
    # run-over-run latency regression band: compare against the PREVIOUS
    # record at this path (when one exists) and FLAG — never fail — a
    # >2x slowdown of the get/small-add planes, so the next session
    # inherits an explicit signal instead of silently re-baselining
    prev = None
    try:
        with open(out_path) as f:
            prev = json.load(f)
    except (OSError, json.JSONDecodeError):
        pass
    regressions = flag_regressions((prev or {}).get("headline"),
                                   rec["headline"])
    rec["regressions"] = regressions
    for r in regressions:
        sys.stderr.write(f"REGRESSION FLAG: {r}\n")
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    # trajectory index: one append-only line per run beside the record,
    # so the bench arc is reconstructable without globbing BENCH_r*.json
    # by mtime (dump_metrics show/diff render it)
    try:
        append_history(history_entry(rec, out_path),
                       os.path.join(os.path.dirname(out_path) or ".",
                                    "BENCH_HISTORY.jsonl"))
    except OSError as e:
        sys.stderr.write(f"BENCH_HISTORY append failed: {e}\n")
    print(json.dumps({"recorded": os.path.relpath(out_path, _REPO),
                      "truncated": rec["truncated"],
                      "complete": rec["complete"],
                      "regressions": regressions,
                      "flightrec_dumps": rec["flightrec_dumps"]}))
    return proc.returncode


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
