#!/usr/bin/env python
"""Run bench.py and record its headline with truncation status.

The bench prints ONE JSON line and exits 0 when complete; a driver-side
timeout SIGTERM triggers the salvage handler, which still prints the
headline but exits ``bench.TRUNCATED_EXIT`` (75). This wrapper is the
recording side of that contract: it re-runs the bench unchanged,
captures the last JSON line, and writes it (default ``BENCH_RUN.json``)
with an explicit ``truncated`` key derived from the exit status — so a
timeout-truncated record can never masquerade as a complete run.

    python tools/run_bench.py [-o BENCH_RUN.json] [-- extra bench args]

Exit status mirrors the bench's own (0 complete, 75 truncated-but-
salvaged, anything else = failed).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from bench import TRUNCATED_EXIT  # noqa: E402


def last_json_line(text: str):
    """The bench contract: the headline is the last parseable JSON line."""
    for line in reversed(text.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def record(returncode: int, stdout: str) -> dict:
    """Shape a bench run into the recorded artifact (pure: testable
    without spawning the real 20-minute bench)."""
    headline = last_json_line(stdout)
    # truncated iff the salvage path exited, OR the headline itself
    # carries the salvage marker (belt: a wrapper that lost the exit
    # status must still never record a truncated run as complete)
    truncated = (returncode == TRUNCATED_EXIT
                 or bool((headline or {}).get("extra", {})
                         .get("truncated")))
    return {
        "returncode": returncode,
        "truncated": truncated,
        # never both: the belt case (exit status lost, salvage marker
        # present) must read as truncated, not complete
        "complete": returncode == 0 and not truncated,
        "headline": headline,
    }


def main(argv) -> int:
    out_path = os.path.join(_REPO, "BENCH_RUN.json")
    if argv[:1] == ["-o"]:
        out_path, argv = argv[1], argv[2:]
    if argv[:1] == ["--"]:
        argv = argv[1:]
    proc = subprocess.run([sys.executable, os.path.join(_REPO, "bench.py"),
                           *argv], cwd=_REPO, capture_output=True, text=True)
    rec = record(proc.returncode, proc.stdout)
    if rec["headline"] is None:
        sys.stderr.write(proc.stderr[-2000:])
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps({"recorded": os.path.relpath(out_path, _REPO),
                      "truncated": rec["truncated"],
                      "complete": rec["complete"]}))
    return proc.returncode


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
