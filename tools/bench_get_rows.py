"""Bench worker: the PS read path (ISSUE 5) — small-get per-call latency
with the client get coalescer on vs off, a concurrent fan-in phase
showing the single-flight dedupe, and one large get plain vs
chunk-streamed. Mirrors tools/bench_small_add.py: two PSContexts in one
process (2-rank world over real localhost sockets), identical request
streams to both arms, and latency is only reported when the returned
values match bit-for-bit.

  off — every get_rows ships its own frame immediately (rides the
        native C++ transport where built, i.e. the FASTEST window-off
        baseline available)
  on  — get_window_ms=2: single-flight per-owner fetches; serial gets
        dispatch immediately (no added latency), concurrent gets dedupe
        into one frame per owner

Every get targets the REMOTE rank's rows, so the off arm's cost is a
real socket round-trip, not the local short-circuit.

Invoked as: python tools/bench_get_rows.py [iters] [big_rows]
(``big_rows`` shrinks the chunk-streamed phase for tier-1 smoke runs.)
Prints "RESULT <json>".
"""

import json
import sys
import tempfile
import threading
import time


def main():
    iters = int(sys.argv[1]) if len(sys.argv) > 1 else 300
    big_rows = int(sys.argv[2]) if len(sys.argv) > 2 else 120_000
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                           PSService)
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    from multiverso_tpu.utils import config
    from multiverso_tpu.utils.dashboard import Dashboard

    rows, cols = 4096, 32
    rng = np.random.default_rng(7)
    init = rng.normal(size=(rows, cols)).astype(np.float32)
    with tempfile.TemporaryDirectory(prefix="mv_get_rows_") as rdv_dir:
        rdv = FileRendezvous(rdv_dir)
        ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
        t_off = AsyncMatrixTable(rows, cols, name="gr_off", init=init,
                                 ctx=ctxs[0])
        AsyncMatrixTable(rows, cols, name="gr_off", init=init, ctx=ctxs[1])
        t_on = AsyncMatrixTable(rows, cols, name="gr_on", init=init,
                                get_window_ms=2.0, ctx=ctxs[0])
        AsyncMatrixTable(rows, cols, name="gr_on", init=init, ctx=ctxs[1])

        # remote-owned single rows: rank 1 owns [2048, 4096)
        ids = rng.integers(rows // 2, rows, iters)
        for i in rng.integers(rows // 2, rows, 32):   # warm conns + jit
            t_off.get_rows([i])
            t_on.get_rows([i])

        def serial_arm(table):
            samples, got = [], None
            for i in range(iters):
                t0 = time.perf_counter()
                got = table.get_rows([ids[i]])
                samples.append(time.perf_counter() - t0)
            return samples, got

        on_s, on_last = serial_arm(t_on)
        off_s, off_last = serial_arm(t_off)
        parity = bool(np.array_equal(on_last, off_last) and np.array_equal(
            t_on.get_rows(np.arange(rows)), t_off.get_rows(np.arange(rows))))
        if not parity:
            raise AssertionError(
                "get-coalescer parity broke: window-on table returned "
                "different bytes than window-off for the identical reads")

        def pct(s, q):
            return round(float(np.percentile(np.asarray(s) * 1e3, q)), 5)

        # concurrent fan-in: N threads pulling overlapping remote rows at
        # once — the single-flight shape the coalescer exists for. The
        # dedupe is read off the fetch counters (frames actually sent vs
        # logical gets), not wall time: in-process thread scheduling is
        # too noisy for a latency claim here.
        fan_threads, fan_iters = 4, max(iters // 4, 25)
        fetch_mon = Dashboard.get("table[gr_on].get_rows.fetches")
        win_mon = Dashboard.get("table[gr_on].get_rows.windowed")
        f0, w0 = fetch_mon.count, win_mon.count

        def fan(table):
            errs = []

            def run(seed):
                r = np.random.default_rng(seed)
                try:
                    for _ in range(fan_iters):
                        table.get_rows(r.integers(rows // 2, rows, 4))
                except Exception as e:  # noqa: BLE001 — join surfaces it
                    errs.append(e)
            ths = [threading.Thread(target=run, args=(s,))
                   for s in range(fan_threads)]
            t0 = time.perf_counter()
            for th in ths:
                th.start()
            for th in ths:
                th.join()
            if errs:
                raise errs[0]
            return time.perf_counter() - t0

        fan_on_wall = fan(t_on)
        fan_off_wall = fan(t_off)
        fan_gets = win_mon.count - w0
        fan_frames = fetch_mon.count - f0

        # large get, plain vs chunk-streamed (bf16 wire keeps the serve
        # on the python plane either way, so the comparison isolates the
        # chunking — and exercises the codec-per-chunk path)
        big_cols = 8
        t_big = AsyncMatrixTable(big_rows, big_cols, name="gr_big",
                                 wire="bf16", ctx=ctxs[0])
        AsyncMatrixTable(big_rows, big_cols, name="gr_big", wire="bf16",
                         ctx=ctxs[1])
        t_big.set_rows(np.arange(big_rows),
                       rng.normal(size=(big_rows, big_cols))
                       .astype(np.float32))
        all_ids = np.arange(big_rows)

        def timed_big():
            t0 = time.perf_counter()
            got = t_big.get_rows(all_ids)
            return time.perf_counter() - t0, got

        timed_big()   # warm
        plain_s, plain_got = min(timed_big() for _ in range(3))
        config.set_flag("get_chunk_rows", max(big_rows // 8, 256))
        try:
            chunk_s, chunk_got = min(timed_big() for _ in range(3))
        finally:
            config.set_flag("get_chunk_rows", 0)
        chunk_parity = bool(np.array_equal(plain_got, chunk_got))
        if not chunk_parity:
            raise AssertionError(
                "chunked-get parity broke: streamed reply differs from "
                "the one-frame reply for the identical read")

        for c in ctxs:
            c.close()

    print("RESULT " + json.dumps({
        "small_get_off_p50_ms": pct(off_s, 50),
        "small_get_on_p50_ms": pct(on_s, 50),
        "small_get_off_p99_ms": pct(off_s, 99),
        "small_get_on_p99_ms": pct(on_s, 99),
        "fanout_gets": int(fan_gets),
        "fanout_frames": int(fan_frames),
        "fanout_dedupe": (round(fan_gets / fan_frames, 2)
                          if fan_frames else None),
        "fanout_on_wall_s": round(fan_on_wall, 3),
        "fanout_off_wall_s": round(fan_off_wall, 3),
        "big_get_rows": big_rows,
        "big_get_plain_ms": round(plain_s * 1e3, 3),
        "big_get_chunked_ms": round(chunk_s * 1e3, 3),
        "chunk_parity_bit_for_bit": chunk_parity,
        "parity_bit_for_bit": parity,
        "iters": iters,
    }), flush=True)


if __name__ == "__main__":
    main()
