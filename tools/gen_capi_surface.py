#!/usr/bin/env python
"""Regenerate the FFI surface mirrors of the C ABI from its one source of
truth, native/mv_capi.cpp.

Round 4 shipped with a red pin test because two new C-ABI entry points
were added without extending the Lua cdef / C driver declarations by
hand. This tool makes the mirrors *generated*: it parses the extern "C"
definitions in mv_capi.cpp and rewrites

  * the ``ffi.cdef[[...]]`` block in examples/lua/multiverso.lua, and
  * the declaration block in native/mv_capi_test.c (between the
    ``/* BEGIN/END generated ABI declarations */`` markers),

so the surface cannot drift: ``--check`` (run by
tests/test_lua_cdef.py::test_generated_mirrors_are_current) fails CI
whenever a regeneration is pending, and the fix is mechanical:

    python tools/gen_capi_surface.py

(ref parallel: binding/lua/init.lua hand-copies c_api.h — the reference
has exactly the drift hazard this removes.)
"""

from __future__ import annotations

import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CAPI = os.path.join(_REPO, "multiverso_tpu", "native", "mv_capi.cpp")
_LUA = os.path.join(_REPO, "examples", "lua", "multiverso.lua")
_CTEST = os.path.join(_REPO, "multiverso_tpu", "native", "mv_capi_test.c")

_BEGIN = "/* BEGIN generated ABI declarations (tools/gen_capi_surface.py) */"
_END = "/* END generated ABI declarations */"


def parse_capi(path: str = _CAPI):
    """Yield (ret, name, [param, ...]) for every extern "C" MV_* definition,
    in source order. Commented-out parameter names (``int* /*argc*/``) are
    resurrected so the generated declarations stay self-documenting.

    The return type admits pointers and multi-word scalars
    (``void*``, ``const char*``, ``unsigned long long``) — and a looser
    scan cross-checks the strict pattern: an ``MV_`` definition the strict
    regex missed fails LOUDLY here instead of silently vanishing from the
    generated cdef (the exact drift this tool exists to prevent)."""
    src = open(path).read()
    out = []
    for m in re.finditer(
            r"^((?:const\s+)?(?:unsigned\s+|signed\s+)?\w+(?:\s+\w+)?"
            r"(?:\s*\*+)?)\s*(MV_\w+)\s*\(([^)]*)\)\s*\{",
            src, re.MULTILINE | re.DOTALL):
        ret, name, raw = " ".join(m.group(1).split()), m.group(2), m.group(3)
        params = []
        for p in raw.split(",") if raw.strip() else []:
            p = re.sub(r"/\*\s*(\w+)\s*\*/", r"\1", p)  # /*argc*/ -> argc
            params.append(" ".join(p.split()))
        out.append((ret, name, params))
    # cross-check: ANY line-anchored MV_* function definition, however
    # exotic its return type
    loose = set(re.findall(r"^[ \t]*[\w\*&: \t]+?\b(MV_\w+)\s*\([^)]*\)\s*\{",
                           src, re.MULTILINE | re.DOTALL))
    strict = {name for _, name, _ in out}
    missed = sorted(loose - strict)
    if missed:
        raise SystemExit(
            f"{path}: MV_ exports {missed} match the loose definition scan "
            "but not the strict return-type pattern — extend parse_capi's "
            "regex (refusing to silently drop them from the generated "
            "cdef)")
    if not out:
        raise SystemExit(f"no extern-C MV_* definitions found in {path}")
    return out


def _decl(ret, name, params, empty="") -> str:
    args = ", ".join(params) if params else empty
    pad = " " if ret == "void" else "  "  # align like the hand-written file
    return f"{ret}{pad}{name}({args});"


def lua_cdef_block(surface) -> str:
    lines = ["typedef void* TableHandler;"]
    lines += [_decl(*f) for f in surface]
    return "\n" + "\n".join(lines) + "\n"


def c_decl_block(surface) -> str:
    # C (unlike C++) needs (void) to declare a no-arg prototype.
    lines = [_decl(r, n, p, empty="void") for r, n, p in surface]
    return "\n".join(lines)


def render(path: str, surface) -> str:
    src = open(path).read()
    if path.endswith(".lua"):
        return re.sub(r"(ffi\.cdef\[\[).*?(\]\])",
                      lambda m: m.group(1) + lua_cdef_block(surface)
                      + m.group(2),
                      src, count=1, flags=re.DOTALL)
    begin, end = src.index(_BEGIN), src.index(_END)
    return (src[:begin + len(_BEGIN)] + "\n" + c_decl_block(surface)
            + "\n" + src[end:])


def main(argv) -> int:
    check = "--check" in argv
    surface = parse_capi()
    stale = []
    for path in (_LUA, _CTEST):
        want = render(path, surface)
        if open(path).read() != want:
            if check:
                stale.append(path)
            else:
                open(path, "w").write(want)
                print(f"regenerated: {os.path.relpath(path, _REPO)}")
    if stale:
        print("stale generated ABI mirrors (run tools/gen_capi_surface.py):"
              f" {[os.path.relpath(p, _REPO) for p in stale]}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
