#!/usr/bin/env python
"""mvprof — per-step critical-path report over step-profiler records.

The step profiler (``multiverso_tpu/telemetry/profiler.py``, flag
``step_profile``) writes one JSON record per training step to
``profile-rank<r>.jsonl`` under ``metrics_dir``; PR-3 tracing writes
request spans to ``trace-rank<r>.jsonl`` beside them. This tool is the
read side — point it at the metrics directory (or explicit files):

    python tools/mvprof.py DIR_OR_FILES... [--report] [--json]
    python tools/mvprof.py DIR_OR_FILES... --to-perfetto OUT.json

``--report`` (the default) prints, per rank:

* the per-step table — wall, top (critical-path) phase, stall %,
  overlap credit, compile count — and which phase won the critical
  path across steps (the "prepare dominates block" headline, measured
  instead of inferred);
* a stall-fraction histogram (how much wall time NO instrument
  claimed, bucketed across steps);
* the recompile table: every step whose boundary sampling attributed
  a jit compile, with per-function retrace counts where ``watch_jit``
  was registered — a silent mid-run recompile names its step.

``--to-perfetto`` writes a Chrome/Perfetto ``traceEvents`` envelope
with **one track per phase per rank** (pid = rank, named tids): step
spans, phase marks, and async PS spans from the profile records, plus
every PR-3 trace span found alongside — the wire's serve/apply spans
land on the same wall-clock timeline as the steps that issued them.

Exit status: 0 with output, 1 when no step records were found.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _load_jsonl(path: str) -> List[Dict]:
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        return []
    return out


def collect(paths: List[str]) -> Tuple[List[Dict], List[Dict]]:
    """(step records, trace events) from directories and/or explicit
    files. A directory contributes every ``profile-rank*.jsonl`` and
    ``trace-rank*.jsonl`` under it."""
    steps: List[Dict] = []
    spans: List[Dict] = []
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files += sorted(glob.glob(os.path.join(p, "profile-rank*.jsonl")))
            files += sorted(glob.glob(os.path.join(p, "trace-rank*.jsonl")))
        else:
            files.append(p)
    for f in files:
        for rec in _load_jsonl(f):
            if rec.get("kind") == "step":
                steps.append(rec)
            elif "ph" in rec and "ts" in rec:
                spans.append(rec)
    steps.sort(key=lambda r: (r.get("rank", 0), r.get("ts", 0.0)))
    return steps, spans


def collect_hygiene(paths: List[str]) -> List[Dict]:
    """SPMD compile-hygiene reports (``compile-hygiene-rank<r>.json``,
    written by ``devstats.dump_hygiene`` — tools/bench_scale.py dumps
    one per run) from directories and/or explicit files."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files += sorted(glob.glob(
                os.path.join(p, "compile-hygiene-rank*.json")))
        elif "compile-hygiene" in os.path.basename(p):
            files.append(p)
    out: List[Dict] = []
    for f in files:
        try:
            with open(f) as fh:
                rep = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(rep, dict) and "findings" in rep:
            rep.setdefault("_file", os.path.basename(f))
            out.append(rep)
    return out


def render_hygiene(reports: List[Dict]) -> str:
    """Compile-hygiene section: per rank the checked-scope log and
    every classified SPMD finding (clean reports say so explicitly —
    a silent section reads as 'not checked', which is the opposite)."""
    lines = []
    for rep in reports:
        head = (f"compile hygiene rank {rep.get('rank', '?')}: "
                + ("CLEAN" if rep.get("clean") else
                   f"{len(rep.get('findings') or [])} FINDING(S)")
                + f"  ({len(rep.get('checked') or [])} scoped compiles)")
        lines.append(head)
        for c in rep.get("checked") or []:
            lines.append(f"  checked {c.get('fn')} @ {c.get('mesh')}: "
                         f"{c.get('captured', 0)} captured, "
                         f"{c.get('findings', 0)} classified")
        for e in rep.get("findings") or []:
            lines.append(f"  FINDING [{e.get('category')}] "
                         f"{e.get('fn')} @ {e.get('mesh')}: "
                         f"{e.get('message')}")
    return "\n".join(lines)


# ---------------------------------------------------------------------- #
# report
# ---------------------------------------------------------------------- #
def _stall_histogram(steps: List[Dict], buckets=(5, 10, 20, 40, 100)
                     ) -> List[Tuple[str, int]]:
    """Stall-fraction distribution across steps, percent buckets."""
    out = []
    lo = 0
    for hi in buckets:
        n = sum(1 for r in steps
                if lo <= 100.0 * r.get("stall_fraction", 0.0) < hi)
        out.append((f"{lo:>3}-{hi:<3}%", n))
        lo = hi
    return out


def report_data(steps: List[Dict]) -> Dict:
    """The report as data (--json; the text renderer consumes this).
    Per-rank aggregation is ``profiler.aggregate_step_records`` — the
    ONE definition dump_metrics' step renderers share."""
    from multiverso_tpu.telemetry.profiler import aggregate_step_records
    by_rank: Dict[int, List[Dict]] = {}
    for r in steps:
        by_rank.setdefault(int(r.get("rank", 0)), []).append(r)
    out: Dict = {"ranks": {}}
    for rank, recs in sorted(by_rank.items()):
        agg = aggregate_step_records(recs)
        wall = agg["wall_ms"]
        out["ranks"][str(rank)] = {
            "steps": agg["steps"],
            "wall_ms": round(wall, 2),
            "attributed_fraction": (round(agg["attributed_ms"] / wall, 4)
                                    if wall else 0.0),
            "stall_fraction": (round(agg["stall_ms"] / wall, 4)
                               if wall else 0.0),
            "overlap_ms": round(agg["overlap_ms"], 2),
            "phases_ms": {n: round(v, 2)
                          for n, v in agg["phases_ms"].items()},
            "critical_path_wins": agg["critical_path_wins"],
            "stall_histogram": _stall_histogram(recs),
            "recompile_steps": agg["recompile_steps"],
            "retraces_by_fn": agg["retraces_by_fn"],
        }
    return out


def render_report(steps: List[Dict], max_steps: int = 20) -> str:
    data = report_data(steps)
    lines: List[str] = []
    for rank, d in sorted(data["ranks"].items(), key=lambda kv: int(kv[0])):
        lines.append(f"== rank {rank}: {d['steps']} steps, "
                     f"{d['wall_ms']:.1f} ms wall, "
                     f"attributed {100 * d['attributed_fraction']:.1f}%, "
                     f"stall {100 * d['stall_fraction']:.1f}%, "
                     f"overlap credit {d['overlap_ms']:.1f} ms ==")
        wins = d["critical_path_wins"]
        if wins:
            total = sum(wins.values())
            lines.append("critical path: " + "  ".join(
                f"{n} {c}/{total}" for n, c in wins.items()))
        lines.append("phase totals (exclusive ms): " + "  ".join(
            f"{n}={v}" for n, v in d["phases_ms"].items()))
        lines.append("stall histogram: " + "  ".join(
            f"{b}:{n}" for b, n in d["stall_histogram"]))
        if d["recompile_steps"]:
            lines.append("recompiles (step: compiles / by fn):")
            for e in d["recompile_steps"][:16]:
                by = ("  " + ", ".join(f"{f}+{k}"
                                       for f, k in e["by_fn"].items())
                      if e["by_fn"] else "")
                lines.append(f"  step {e['step']} [{e['name']}]: "
                             f"{e['compiles']}{by}")
        else:
            lines.append("recompiles: none")
        recs = [r for r in steps if str(r.get("rank", 0)) == rank]
        lines.append("")
        lines.append(f"{'step':>5} {'name':<18} {'wall_ms':>9} "
                     f"{'top phase':<24} {'stall%':>7} {'overlap':>8}")
        from multiverso_tpu.telemetry.profiler import step_top_phase
        for r in recs[:max_steps]:
            top_n, top_ms = step_top_phase(r)
            top_s = f"{top_n} ({top_ms:.1f} ms)" if top_n else "-"
            lines.append(
                f"{r.get('step', '?'):>5} {r.get('name', '?'):<18} "
                f"{r.get('wall_ms', 0):>9.2f} {top_s:<24} "
                f"{100 * r.get('stall_fraction', 0):>6.1f}% "
                f"{r.get('overlap_ms', 0):>8.2f}")
        if len(recs) > max_steps:
            lines.append(f"  ... {len(recs) - max_steps} more steps "
                         "(--steps N to widen)")
        lines.append("")
    return "\n".join(lines).rstrip()


# ---------------------------------------------------------------------- #
# perfetto timeline
# ---------------------------------------------------------------------- #
def to_perfetto(steps: List[Dict], spans: List[Dict],
                out_path: Optional[str]) -> Dict:
    """Profile records + trace spans -> one traceEvents envelope. One
    track per phase per rank: pid = rank, tid = a small stable index
    per track name with thread_name metadata, so Perfetto renders
    "step", each phase, and each async-span name as parallel lanes.
    PR-3 trace spans keep their own (pid=rank, tid=thread) tracks —
    same wall-clock microsecond timebase, one timeline."""
    events: List[Dict] = []
    tids: Dict[Tuple[int, str], int] = {}

    def tid_for(rank: int, track: str) -> int:
        key = (rank, track)
        t = tids.get(key)
        if t is None:
            t = tids[key] = len([k for k in tids if k[0] == rank]) + 1
            events.append({"ph": "M", "name": "thread_name", "pid": rank,
                           "tid": t, "args": {"name": track}})
        return t

    for r in steps:
        rank = int(r.get("rank", 0))
        t0_us = int(float(r.get("ts", 0.0)) * 1e6)
        events.append({
            "name": f"{r.get('name', 'step')}#{r.get('step')}",
            "cat": "profile", "ph": "X", "ts": t0_us,
            "dur": int(float(r.get("wall_ms", 0.0)) * 1e3),
            "pid": rank, "tid": tid_for(rank, "step"),
            "args": {"stall_fraction": r.get("stall_fraction"),
                     "attributed_fraction": r.get("attributed_fraction"),
                     "compiles": r.get("jax", {}).get("compiles", 0)}})
        for span in r.get("spans", []):
            kind, name, a_us, b_us = span[0], span[1], span[2], span[3]
            track = name if kind == "phase" else f"async:{name}"
            ev = {"name": name, "cat": kind, "ph": "X",
                  "ts": t0_us + int(a_us),
                  "dur": max(int(b_us) - int(a_us), 1),
                  "pid": rank, "tid": tid_for(rank, track)}
            if len(span) > 4 and span[4] == "open":
                ev["args"] = {"open_at_step_end": True}
            events.append(ev)
    events.extend(spans)   # PR-3 trace spans: already trace_event shaped
    envelope = {"traceEvents": events, "displayTimeUnit": "ms"}
    if out_path:
        with open(out_path, "w") as f:
            json.dump(envelope, f)
    return envelope


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mvprof",
        description="per-step critical-path report / Perfetto timeline")
    ap.add_argument("paths", nargs="+",
                    help="metrics dir(s) and/or profile/trace JSONL files")
    ap.add_argument("--report", action="store_true",
                    help="print the critical-path report (default)")
    ap.add_argument("--to-perfetto", metavar="OUT.json", default=None,
                    help="write a Perfetto/chrome traceEvents envelope")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of tables")
    ap.add_argument("--steps", type=int, default=20,
                    help="per-rank step rows shown in the report")
    args = ap.parse_args(argv)

    steps, spans = collect(args.paths)
    hygiene = collect_hygiene(args.paths)
    if not steps and not hygiene:
        print("mvprof: no step records found (is step_profile on and "
              "metrics_dir set?)", file=sys.stderr)
        return 1
    did = False
    if args.to_perfetto:
        if not steps:
            # an explicitly requested export must fail loudly, not
            # exit 0 with the output file silently never written
            print("mvprof: --to-perfetto needs step records; the "
                  "given paths hold only compile-hygiene reports",
                  file=sys.stderr)
            return 1
        env = to_perfetto(steps, spans, args.to_perfetto)
        print(f"wrote {len(env['traceEvents'])} events "
              f"({len(steps)} steps, {len(spans)} trace spans) to "
              f"{args.to_perfetto}")
        did = True
    if args.report or args.json or not did:
        if args.json:
            data = report_data(steps) if steps else {}
            if hygiene:
                data["hygiene"] = hygiene
            print(json.dumps(data))
        else:
            parts = []
            if steps:
                parts.append(render_report(steps, args.steps))
            if hygiene:
                parts.append(render_hygiene(hygiene))
            print("\n\n".join(parts))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
