#!/usr/bin/env python
"""Merge per-rank flight-recorder dumps into one timeline and answer
"who was stuck on whom".

Every rank's flight recorder (multiverso_tpu/telemetry/flightrec.py)
dumps ``flightrec-rank<r>.jsonl`` at fault time: a header (with the
rank's monotonic->wall anchor), the event ring, the in-flight request
table, and — on watchdog trips/signals — per-thread Python stacks. This
tool is the read side: point it at the dump directory (or explicit
files) and it

* merges every rank's events onto ONE wall-clock timeline (each rank's
  monotonic stamps shifted by its own header anchor), interleaving any
  structured JSONL log files (``utils/log.py`` ``jsonl=True`` sink —
  records carrying a ``level`` field) found alongside;
* reports the oldest unacked (src, dst, msg id) per rank pair from the
  in-flight tables — the "rank 0 has been waiting 12 s on rank 3's
  msg 41" line that localizes a hang without a repro;
* names suspect ranks: peers that appear as the dst of unacked traffic
  or in peer-death events but produced no dump of their own (a rank
  that died hard never got to write one — its absence IS the finding).

    python tools/postmortem.py DIR_OR_FILES... [--json] [--tail N]

Exit status: 0 with a report, 1 when no dumps were found.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _msg_names() -> Dict[int, str]:
    """MSG_* code -> name map off ps/service.py (jax-free import); falls
    back to raw codes if the package is unimportable (e.g. the tool is
    run against dumps on a bare host)."""
    try:
        from multiverso_tpu.ps import service as svc
        return {v: k for k, v in vars(svc).items()
                if k.startswith("MSG_") and isinstance(v, int)}
    except Exception:   # noqa: BLE001
        return {}


def load_dump(path: str) -> Optional[Dict]:
    """One dump file -> {"header", "events", "inflight", "stacks",
    "memory", "memsamples"}; None for an unreadable/foreign file. The
    memory records are the memstats dump provider's ledger snapshot +
    bounded sample history (telemetry/memstats.py) — the memory
    timeline rendered next to the wire timeline."""
    header, events, inflight, stacks = None, [], [], []
    memory, memsamples = [], []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                kind = rec.get("kind")
                if kind == "header":
                    header = rec
                elif kind == "event":
                    events.append(rec)
                elif kind == "inflight":
                    inflight.append(rec)
                elif kind == "stack":
                    stacks.append(rec)
                elif kind == "memory":
                    memory.append(rec)
                elif kind == "memsample":
                    memsamples.append(rec)
    except (OSError, json.JSONDecodeError):
        return None
    if header is None:
        return None
    return {"header": header, "events": events, "inflight": inflight,
            "stacks": stacks, "memory": memory,
            "memsamples": memsamples, "path": path}


def _expand(args: List[str]) -> (List[str], List[str]):
    """Paths/dirs -> (dump files, jsonl log files). A directory
    contributes its flightrec-rank*.jsonl dumps plus any other *.jsonl
    whose first record carries a ``level`` field (the structured log
    sink); trace/metrics JSONL files are skipped by that probe."""
    dumps, logs = [], []
    for a in args:
        if os.path.isdir(a):
            dumps.extend(sorted(glob.glob(
                os.path.join(a, "flightrec-rank*.jsonl"))))
            for p in sorted(glob.glob(os.path.join(a, "*.jsonl"))):
                if os.path.basename(p).startswith(
                        ("flightrec-rank", "trace-rank", "metrics-rank")):
                    continue
                if _is_log_file(p):
                    logs.append(p)
        elif os.path.basename(a).startswith("flightrec-"):
            dumps.append(a)
        elif _is_log_file(a):
            logs.append(a)
        else:
            dumps.append(a)   # explicit file: trust the caller
    return dumps, logs


def _is_log_file(path: str) -> bool:
    try:
        with open(path) as f:
            first = f.readline().strip()
        return bool(first) and "level" in json.loads(first)
    except (OSError, json.JSONDecodeError):
        return False


def load_dumps(args) -> List[Dict]:
    if isinstance(args, str):
        args = [args]
    paths, _ = _expand(list(args))
    return [d for d in (load_dump(p) for p in paths) if d is not None]


def load_log_lines(path: str) -> List[Dict]:
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "level" in rec and "ts" in rec:
                    out.append(rec)
    except OSError:
        pass
    return out


def timeline(dumps: List[Dict], log_lines: List[Dict] = ()
             ) -> List[Dict]:
    """All ranks' events + log lines, one list sorted by wall time.
    Events gain ``ts`` (wall seconds) via their dump's monotonic
    anchor and ``rank``; log lines pass through (they already carry
    wall ``ts`` and ``rank``)."""
    rows: List[Dict] = []
    for d in dumps:
        anchor = float(d["header"].get("mono_to_wall", 0.0))
        rank = d["header"].get("rank", -1)
        for e in d["events"]:
            r = dict(e)
            r["ts"] = round(float(e.get("mono", 0.0)) + anchor, 6)
            r["rank"] = rank
            rows.append(r)
    for rec in log_lines:
        r = dict(rec)
        r.setdefault("ev", f"log.{rec.get('level', '?').lower()}")
        rows.append(r)
    rows.sort(key=lambda r: r.get("ts", 0.0))
    return rows


def stuck_pairs(dumps: List[Dict]) -> List[Dict]:
    """Oldest unacked request per (src, dst) rank pair, oldest first."""
    best: Dict[tuple, Dict] = {}
    for d in dumps:
        src = d["header"].get("rank", -1)
        for e in d["inflight"]:
            key = (src, e.get("peer", -1))
            if key not in best or e.get("age_s", 0) > best[key]["age_s"]:
                best[key] = {"src": src, "dst": e.get("peer", -1),
                             "msg_id": e.get("msg_id", -1),
                             "type": e.get("type", 0),
                             "age_s": float(e.get("age_s", 0.0)),
                             "nbytes": e.get("nbytes", 0)}
    return sorted(best.values(), key=lambda p: -p["age_s"])


def dead_suspects(dumps: List[Dict]) -> List[Dict]:
    """Ranks implicated without a dump of their own: the dst of unacked
    traffic, or named in a peer-death event. A hard-killed rank never
    writes a dump — its absence plus a survivor's pointer is the
    verdict."""
    have = {d["header"].get("rank", -1) for d in dumps}
    why: Dict[int, set] = {}
    for p in stuck_pairs(dumps):
        if p["dst"] not in have:
            why.setdefault(p["dst"], set()).add(
                f"rank {p['src']} has unacked traffic to it "
                f"(oldest msg {p['msg_id']}, {p['age_s']:.1f}s)")
    for d in dumps:
        src = d["header"].get("rank", -1)
        for e in d["events"]:
            if e.get("ev") == "peer.dead" and e.get("peer", -1) not in have:
                why.setdefault(e["peer"], set()).add(
                    f"rank {src} observed its connection die")
    return [{"rank": r, "evidence": sorted(v)}
            for r, v in sorted(why.items())]


def memory_report(dumps: List[Dict]) -> Dict:
    """The memory forensics view across every rank's dump: each rank's
    LAST ledger snapshot (RSS, device census total, component bytes,
    verdicts) plus the merged sample timeline — RSS/device readings on
    one wall clock (memstats samples carry wall ``ts`` directly, no
    monotonic anchor needed). ``{"ranks": {}, "timeline": []}`` when no
    dump carried memory records (pre-memstats artifacts)."""
    ranks: Dict[str, Dict] = {}
    timeline: List[Dict] = []
    for d in dumps:
        rank = d["header"].get("rank", -1)
        mems = d.get("memory") or []
        if mems:
            m = mems[-1]
            census = m.get("census") or {}
            ranks[str(rank)] = {
                "ts": m.get("ts"), "rss_mb": m.get("rss_mb"),
                "hwm_mb": m.get("hwm_mb"),
                "device_bytes": census.get("bytes"),
                "totals": m.get("totals", {}),
                "components": m.get("components", {}),
                "verdicts": m.get("verdicts", []),
            }
        for s in d.get("memsamples") or []:
            r = dict(s)
            r["rank"] = rank
            timeline.append(r)
    timeline.sort(key=lambda r: r.get("ts") or 0.0)
    return {"ranks": ranks, "timeline": timeline}


_RECOVERY_EVS = ("failover.detect", "failover.respawn",
                 "failover.restore", "failover.replay",
                 "failover.rejoin")


def recovery_timeline(dumps: List[Dict], log_lines: List[Dict] = ()
                      ) -> List[Dict]:
    """The failover lifecycle (detect → respawn → restore → replay →
    rejoin) across every rank's dump, on one wall clock, each phase
    stamped with its delay since the episode's first detect — the
    "how long was the shard dark, and where did the time go" view."""
    rows = [r for r in timeline(dumps, log_lines)
            if r.get("ev") in _RECOVERY_EVS]
    t0: Optional[float] = None
    out = []
    for r in rows:
        phase = r["ev"].split(".", 1)[1]
        if phase == "detect":
            t0 = r.get("ts", 0.0)
        entry = {"ts": r.get("ts", 0.0), "phase": phase,
                 "rank": r.get("rank", -1)}
        if r.get("peer", -1) != -1:
            entry["about_rank"] = r["peer"]
        if r.get("note"):
            entry["note"] = r["note"]
        if t0 is not None:
            entry["t_plus_s"] = round(entry["ts"] - t0, 3)
        out.append(entry)
    return out


def injected_faults(dumps: List[Dict]) -> Dict:
    """Chaos-plane evidence (ps/faults.py): every ``fault.inject`` /
    ``fault.plane`` event across the merged dumps, plus per-kind
    counts — the view that separates INJECTED faults from organic
    ones, so a chaos run's peer deaths and timeouts read as scenario,
    not incident. The kind is the note's first token
    ("drop"/"delay:…"/"duplicate"/…)."""
    events = [r for r in timeline(dumps)
              if r.get("ev") in ("fault.inject", "fault.plane")]
    counts: Dict[str, int] = {}
    for r in events:
        if r["ev"] != "fault.inject":
            continue
        kind = str(r.get("note") or "?").split()[0].split(":")[0]
        counts[kind] = counts.get(kind, 0) + 1
    return {"injected": sum(counts.values()), "by_kind": counts,
            "events": events}


def tenant_timeline(dumps: List[Dict]) -> List[Dict]:
    """The tenant attribution plane's events (telemetry/tenants.py):
    every ``tenant.shed`` (a per-tenant budget refusing a read) and
    ``tenant.verdict`` (a noisy-neighbor episode opening) across the
    merged dumps, on one wall clock — rendered beside the injected
    faults so a chaos run's storm reads as scenario. The note carries
    ``table:tenant`` for sheds and the storm tenant + share for
    verdicts."""
    return [r for r in timeline(dumps)
            if r.get("ev") in ("tenant.shed", "tenant.verdict")]


def slo_timeline(dumps: List[Dict]) -> List[Dict]:
    """The SLO sentinel's episodes (telemetry/slo.py): every
    ``slo.fired`` / ``slo.cleared`` event across the merged dumps, on
    one wall clock — rendered beside the injected faults and tenant
    verdicts so an objective's burn reads against the scenario that
    caused it. The note carries the objective name, episode number,
    and the burn rates at the transition."""
    return [r for r in timeline(dumps)
            if r.get("ev") in ("slo.fired", "slo.cleared")]


def render_report(dumps: List[Dict], log_lines: List[Dict] = (),
                  tail: int = 40) -> str:
    names = _msg_names()

    def mname(t):
        return names.get(t, f"0x{t:X}" if isinstance(t, int) else str(t))

    lines = []
    ranks = sorted(d["header"].get("rank", -1) for d in dumps)
    lines.append(f"postmortem over {len(dumps)} dump(s): ranks {ranks}")
    for d in dumps:
        h = d["header"]
        lines.append(
            f"  rank {h.get('rank')}: reason={h.get('reason')!r} "
            f"events={len(d['events'])} inflight={len(d['inflight'])} "
            f"stacks={len(d['stacks'])} ({d['path']})")
    suspects = dead_suspects(dumps)
    if suspects:
        lines.append("suspect dead/stuck ranks (no dump of their own):")
        for s in suspects:
            lines.append(f"  rank {s['rank']}:")
            for ev in s["evidence"]:
                lines.append(f"    - {ev}")
    inj = injected_faults(dumps)
    if inj["injected"] or inj["events"]:
        # chaos plane armed: say so FIRST — every organic-looking
        # fault below (peer deaths, timeouts, stuck ops) must be read
        # against the scenario that provoked it
        lines.append(
            "INJECTED faults (chaos plane, ps/faults.py): "
            + (", ".join(f"{k}={n}" for k, n
                         in sorted(inj["by_kind"].items()))
               or "plane armed, none fired"))
        for e in inj["events"][-8:]:
            lines.append(
                f"  {e.get('ts', 0.0):.6f} rank{e.get('rank', -1)} "
                f"{e['ev']} peer={e.get('peer', -1)} "
                f"{e.get('note') or ''}")
    tten = tenant_timeline(dumps)
    if tten:
        # tenant plane: verdicts print whole, sheds summarize by
        # table:tenant — one budget's refusals are one line, not a
        # page, and the verdict stays beside the faults it co-occurred
        # with
        shed_counts: Dict[str, int] = {}
        for e in tten:
            if e["ev"] == "tenant.shed":
                key = str(e.get("note") or "?")
                shed_counts[key] = shed_counts.get(key, 0) + 1
        lines.append(
            "tenant plane (telemetry/tenants.py): sheds "
            + (", ".join(f"{k}={n}" for k, n
                         in sorted(shed_counts.items())) or "none"))
        for e in tten:
            if e["ev"] != "tenant.verdict":
                continue
            lines.append(
                f"  {e.get('ts', 0.0):.6f} rank{e.get('rank', -1)} "
                f"VERDICT {e.get('note') or ''}")
    tslo = slo_timeline(dumps)
    if tslo:
        fired = sum(1 for e in tslo if e["ev"] == "slo.fired")
        lines.append(
            f"SLO episodes (telemetry/slo.py): {fired} fired, "
            f"{len(tslo) - fired} cleared")
        for e in tslo:
            lines.append(
                f"  {e.get('ts', 0.0):.6f} rank{e.get('rank', -1)} "
                f"{'FIRED' if e['ev'] == 'slo.fired' else 'cleared'} "
                f"{e.get('note') or ''}")
    rec = recovery_timeline(dumps, log_lines)
    if rec:
        lines.append("recovery timeline (failover plane):")
        for e in rec:
            about = (f" rank {e['about_rank']}"
                     if "about_rank" in e else "")
            note = f"  {e['note']!r}" if e.get("note") else ""
            tplus = (f"  (+{e['t_plus_s']:.3f}s)"
                     if "t_plus_s" in e else "")
            lines.append(f"  {e['ts']:.6f} rank{e['rank']} "
                         f"{e['phase']}{about}{note}{tplus}")
    mem = memory_report(dumps)
    if mem["ranks"]:
        lines.append("memory at dump time (byte ledger):")
        for r in sorted(mem["ranks"], key=str):
            e = mem["ranks"][r]
            dev = e.get("device_bytes")
            lines.append(
                f"  rank {r}: rss {e.get('rss_mb', '-')} MB "
                f"(hwm {e.get('hwm_mb', '-')})  device "
                + ("-" if not isinstance(dev, (int, float))
                   else f"{dev / 1e6:.1f} MB"))
            comps = e.get("components") or {}
            for name in sorted(comps):
                g = comps[name]
                if not isinstance(g, dict):
                    continue
                nb = sum(v for k, v in g.items()
                         if k.endswith("_bytes")
                         and isinstance(v, (int, float))
                         and not isinstance(v, bool))
                lines.append(f"    {name}: {int(nb)} bytes")
            for v in (e.get("verdicts") or [])[-4:]:
                if isinstance(v, dict):
                    lines.append(f"    VERDICT {v.get('kind')} "
                                 f"({v.get('component')})")
    if mem["timeline"]:
        tl = mem["timeline"]
        lines.append(f"memory timeline (last {min(tail, len(tl))} of "
                     f"{len(tl)} samples):")
        for s in tl[-tail:]:
            dev = s.get("device_bytes")
            lines.append(
                f"  {s.get('ts', 0):.3f} rank{s.get('rank', '?')} "
                f"rss {s.get('rss_mb', '-')} MB  device "
                + ("-" if not isinstance(dev, (int, float))
                   else f"{dev / 1e6:.1f} MB")
                + "  " + " ".join(
                    f"{k}={v}" for k, v in sorted(
                        (s.get("totals") or {}).items()) if v))
    pairs = stuck_pairs(dumps)
    if pairs:
        lines.append("oldest unacked request per (src, dst):")
        for p in pairs:
            lines.append(
                f"  rank {p['src']} -> rank {p['dst']}: "
                f"msg {p['msg_id']} ({mname(p['type'])}, "
                f"{p['age_s']:.1f}s unacked, {p['nbytes']} bytes)")
    else:
        lines.append("no unacked requests at dump time")
    tl = timeline(dumps, log_lines)
    if tl:
        lines.append(f"timeline (last {min(tail, len(tl))} of "
                     f"{len(tl)} records):")
        for r in tl[-tail:]:
            what = r.get("ev", "?")
            detail = ""
            if r.get("msg_id", -1) != -1:
                detail += f" msg={r['msg_id']}"
            if r.get("peer", -1) != -1:
                detail += f" peer={r['peer']}"
            if r.get("type"):
                detail += f" {mname(r['type'])}"
            if r.get("note"):
                detail += f" note={r['note']!r}"
            if r.get("msg"):
                detail += f" {r['msg']}"
            lines.append(f"  {r.get('ts', 0):.6f} rank{r.get('rank', '?')}"
                         f" {what}{detail}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="dump directory or flightrec/log JSONL files")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    ap.add_argument("--tail", type=int, default=40,
                    help="timeline records to print")
    args = ap.parse_args(argv)
    dump_paths, log_paths = _expand(args.paths)
    dumps = [d for d in (load_dump(p) for p in dump_paths)
             if d is not None]
    if not dumps:
        print("no flight-recorder dumps found", file=sys.stderr)
        return 1
    log_lines = [rec for p in log_paths for rec in load_log_lines(p)]
    if args.json:
        print(json.dumps({
            "ranks": sorted(d["header"].get("rank", -1) for d in dumps),
            "suspects": dead_suspects(dumps),
            "stuck_pairs": stuck_pairs(dumps),
            "recovery": recovery_timeline(dumps, log_lines),
            "injected_faults": injected_faults(dumps),
            "tenant_timeline": tenant_timeline(dumps),
            "slo_timeline": slo_timeline(dumps),
            "memory": memory_report(dumps),
            "timeline": timeline(dumps, log_lines)[-args.tail:],
        }, indent=1))
    else:
        print(render_report(dumps, log_lines, tail=args.tail))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
