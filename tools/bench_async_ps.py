"""Async-PS plane throughput: two real processes hammering row traffic.

Worker body for bench.bench_async_ps(): rank 0 and rank 1 each own half of
a (rows, dim) table and push/pull batches of their OWN row sets for a
fixed duration — uncoordinated, so the measured rate is the plane's
(serialization + TCP + shard update) throughput, not a collective's.

Invoked as: python tools/bench_async_ps.py <rdv> <world> <rank> <seconds>
           [wire]
Prints "RESULT {...}" with ops, rows moved, and get-latency percentiles.
"""

import json
import os
import sys
import time


def main():
    rdv_dir, world, rank, seconds = (sys.argv[1], int(sys.argv[2]),
                                     int(sys.argv[3]), float(sys.argv[4]))
    wire = sys.argv[5] if len(sys.argv) > 5 else "none"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                           PSService)
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    from multiverso_tpu.utils import config
    from multiverso_tpu.utils.filesync import file_barrier

    config.set_flag("ps_timeout", 120.0)
    if os.environ.get("MV_PS_NATIVE", "") == "0":   # A/B: pure-python plane
        config.set_flag("ps_native", False)
    ctx = PSContext(rank, world,
                    PSService(rank, world, FileRendezvous(rdv_dir)))
    rows, dim, batch = 100_000, 128, 1024
    t = AsyncMatrixTable(rows, dim, name="bench_async", wire=wire,
                         ctx=ctx)
    # the table's OWN routing decision, not a re-derivation: bf16 wires
    # and native-setup failures run the python plane regardless of flags
    native_plane = t._native_ok
    rng = np.random.default_rng(rank)
    # this worker's ids: strided so every batch spans BOTH shards (half
    # the traffic crosses the socket, half short-circuits — the realistic
    # mix for world=2)
    vals = rng.normal(size=(batch, dim)).astype(np.float32)
    ids = (np.arange(batch) * (rows // batch) + rank) % rows
    t.add_rows(ids, vals)       # compile both shards' programs
    t.get_rows(ids)
    file_barrier(rdv_dir, world, rank, "warm", timeout=60)

    ops = 0
    start = time.monotonic()
    mids, get_lat = [], []
    while time.monotonic() - start < seconds:
        mids.append(t.add_rows_async(ids, vals))
        if len(mids) >= 4:      # bounded pipeline depth
            t.wait(mids.pop(0))
        g0 = time.monotonic()
        t.get_rows(ids)
        get_lat.append(time.monotonic() - g0)
        ops += 2
    for m in mids:
        t.wait(m)
    dt = time.monotonic() - start
    file_barrier(rdv_dir, world, rank, "done", timeout=60)
    shard = t._shard
    # snapshot BEFORE close: natively-served shards keep their counters in
    # the C++ server, which dies with the service
    stat_adds, stat_applies = shard.stat_adds, shard.stat_applies
    ctx.close()
    print("RESULT " + json.dumps({
        "rank": rank, "ops": ops, "rows": ops * batch, "seconds": dt,
        # adds this shard received vs. updates actually run: >1 means
        # server-side coalescing merged concurrent adds (ps_coalesce)
        "coalesce_ratio": round(stat_adds / max(stat_applies, 1), 2),
        "rows_per_sec": ops * batch / dt,
        # the strided row sets span every owner, so each op fans out to
        # `world` messages: rows/s divides by world as world grows while
        # the plane's actual request rate RISES — report both. On the
        # native plane every owner (incl. self) is a real loopback-TCP
        # message; the python plane short-circuits the local owner
        # in-process, so it gets world-1.
        "msgs_per_sec": ops * (world if native_plane else world - 1) / dt,
        "mb_per_sec": ops * batch * dim * 4 / dt / 1e6,
        "get_p50_ms": float(np.percentile(get_lat, 50) * 1e3),
        "get_p99_ms": float(np.percentile(get_lat, 99) * 1e3),
        "batch_rows": batch, "dim": dim, "wire": wire}), flush=True)


if __name__ == "__main__":
    main()
