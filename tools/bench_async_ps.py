"""Async-PS plane throughput: two real processes hammering row traffic.

Worker body for bench.bench_async_ps(): rank 0 and rank 1 each own half of
a (rows, dim) table and push/pull batches of their OWN row sets for a
fixed duration — uncoordinated, so the measured rate is the plane's
(serialization + TCP + shard update) throughput, not a collective's.

Invoked as: python tools/bench_async_ps.py <rdv> <world> <rank> <seconds>
           [wire] [pattern]
Prints "RESULT {...}" with ops, rows moved, and get-latency percentiles.

``pattern``:
  strided (default) — every batch spans ALL owners, so one op fans out to
      `world` messages; measures the full fanout path but conflates
      server capacity with O(world) client work on a small host.
  local — every batch lives entirely in the NEXT rank's shard (one real
      TCP message per op, never the self short-circuit); the per-op cost
      is world-independent, so the aggregate curve isolates what the
      SERVERS sustain as the plane grows (the load-controlled variant the
      r4 verdict asked for).
  paced — owner-local ids AND a fixed TOTAL offered load across the
      plane (each worker throttles to its 1/world share), held well
      under the 1-core host's capacity: the aggregate throughput then
      measures whether the plane SUSTAINS the load at every world size
      (flat = yes), and the latency percentiles measure serving latency
      rather than saturation queueing.
"""

PACED_TOTAL_OPS = 150.0   # add+get pairs/s across the whole plane

import json
import os
import sys
import time


def main():
    rdv_dir, world, rank, seconds = (sys.argv[1], int(sys.argv[2]),
                                     int(sys.argv[3]), float(sys.argv[4]))
    wire = sys.argv[5] if len(sys.argv) > 5 else "none"
    pattern = sys.argv[6] if len(sys.argv) > 6 else "strided"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                           PSService)
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    from multiverso_tpu.utils import config
    from multiverso_tpu.utils.filesync import file_barrier

    config.set_flag("ps_timeout", 120.0)
    if os.environ.get("MV_PS_NATIVE", "") == "0":   # A/B: pure-python plane
        config.set_flag("ps_native", False)
    ctx = PSContext(rank, world,
                    PSService(rank, world, FileRendezvous(rdv_dir)))
    rows, dim, batch = 100_000, 128, 1024
    t = AsyncMatrixTable(rows, dim, name="bench_async", wire=wire,
                         ctx=ctx)
    # the table's OWN routing decision, not a re-derivation: bf16 wires
    # and native-setup failures run the python plane regardless of flags
    native_plane = t._native_ok
    rng = np.random.default_rng(rank)
    vals = rng.normal(size=(batch, dim)).astype(np.float32)
    if pattern in ("local", "paced"):
        # whole batch inside the NEXT rank's contiguous shard: one real
        # TCP message per op at every world size (see module docstring)
        rows_per = -(-rows // world)
        peer = (rank + 1) % world
        lo = peer * rows_per
        span = min(rows_per, rows - lo)
        ids = lo + (np.arange(batch) % span)
    else:
        # strided so every batch spans ALL shards (1/world of the traffic
        # short-circuits — the realistic mix for a shared embedding table)
        ids = (np.arange(batch) * (rows // batch) + rank) % rows
    t.add_rows(ids, vals)       # compile both shards' programs
    t.get_rows(ids)
    file_barrier(rdv_dir, world, rank, "warm", timeout=60)

    ops = 0
    start = time.monotonic()
    mids, get_lat = [], []
    interval = world / PACED_TOTAL_OPS if pattern == "paced" else 0.0
    while time.monotonic() - start < seconds:
        if interval:
            # fixed-offered-load: next slot on the global schedule; a
            # slow op eats into the following sleep, not the rate. The
            # rank/world phase offset interleaves the plane's slots —
            # workers leave the warm barrier near-simultaneously, so
            # unoffset schedules would fire all `world` ops in one burst
            # every interval (measured: np8 p50 2.2 ms from intra-burst
            # queueing alone; interleaved, ops never collide by design)
            next_t = start + (ops // 2 + 1 + rank / world) * interval
            now = time.monotonic()
            if next_t > now:
                time.sleep(next_t - now)
        if interval:
            # paced mode measures SERVING latency: the add completes
            # before the get issues, so the get never queues behind its
            # own 512 KB add payload on the conn (head-of-line)
            t.add_rows(ids, vals)
        else:
            mids.append(t.add_rows_async(ids, vals))
            if len(mids) >= 4:      # bounded pipeline depth
                t.wait(mids.pop(0))
        g0 = time.monotonic()
        t.get_rows(ids)
        get_lat.append(time.monotonic() - g0)
        ops += 2
    for m in mids:
        t.wait(m)
    dt = time.monotonic() - start
    file_barrier(rdv_dir, world, rank, "done", timeout=60)
    shard = t._shard
    # snapshot BEFORE close: natively-served shards keep their counters in
    # the C++ server, which dies with the service
    stat_adds, stat_applies = shard.stat_adds, shard.stat_applies
    ctx.close()
    print("RESULT " + json.dumps({
        "rank": rank, "ops": ops, "rows": ops * batch, "seconds": dt,
        # adds this shard received vs. updates actually run: >1 means
        # server-side coalescing merged concurrent adds (ps_coalesce)
        "coalesce_ratio": round(stat_adds / max(stat_applies, 1), 2),
        "rows_per_sec": ops * batch / dt,
        # the strided row sets span every owner, so each op fans out to
        # `world` messages: rows/s divides by world as world grows while
        # the plane's actual request rate RISES — report both. On the
        # native plane every owner (incl. self) is a real loopback-TCP
        # message; the python plane short-circuits the local owner
        # in-process, so it gets world-1.
        "msgs_per_sec": (ops / dt if pattern in ("local", "paced") else
                         ops * (world if native_plane else world - 1) / dt),
        "mb_per_sec": ops * batch * dim * 4 / dt / 1e6,
        "get_p50_ms": float(np.percentile(get_lat, 50) * 1e3),
        "get_p99_ms": float(np.percentile(get_lat, 99) * 1e3),
        "batch_rows": batch, "dim": dim, "wire": wire,
        "pattern": pattern,
        # paced mode: raw samples so the collector can compute PLANE-WIDE
        # percentiles (max-of-worker-p99s over median-of-worker-p50s is
        # not a percentile of anything; with ~20 samples/s/worker the
        # worker-level p99 is just its 2nd-worst sample)
        **({"get_lat_ms": [round(x * 1e3, 3) for x in get_lat]}
           if pattern in ("paced", "local") else {})}), flush=True)


if __name__ == "__main__":
    main()
