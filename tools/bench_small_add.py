"""Bench worker: small-add (1-row) per-call latency with the client send
window on vs off — the PR-2 coalescing headline (ISSUE 2 acceptance:
window-on p50 improves >= 5x vs window-off on this microbench).

Two PSContexts in one process (2-rank world over real localhost sockets,
the tier-2 fuzz fixture shape); two tables fed the SAME 1-row adds
interleaved so load drift between arms cancels:

  off — every add_rows_async ships its own frame immediately (the
        pre-PR-2 path; rides the native C++ transport where built,
        i.e. the FASTEST window-off baseline available)
  on  — send_window_ms=2 (TUNING.md's bench-derived default): the call
        enqueues client-side and returns; the flusher ships each owner's
        queue as one MSG_BATCH frame

Every add targets the REMOTE rank's rows, so the off arm's cost is a real
socket send, not the local short-circuit. Both tables drain with flush()
(untimed) every 50 calls and the final states are compared bit-for-bit —
the latency number is only reported if the semantics held.

Invoked as: python tools/bench_small_add.py [iters]
Prints "RESULT <json>".
"""

import json
import sys
import tempfile
import time


def main():
    iters = int(sys.argv[1]) if len(sys.argv) > 1 else 400
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                           PSService)
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    from multiverso_tpu.telemetry import aggregator
    from multiverso_tpu.utils import config
    from multiverso_tpu.utils.dashboard import Dashboard

    # ISSUE 6 acceptance config: the cluster aggregator polls BOTH ranks'
    # MSG_STATS + MSG_HEALTH at 1 Hz over one-shot probe conns, and the
    # hot-key sketch records every served op (default-on) — the band
    # assertion below then proves the whole cluster-observability plane
    # is free at the PR-2 latency floor
    config.set_flag("stats_poll_interval_s", 1.0)
    # ISSUE 12 acceptance config: the device-plane gauge set (transfer
    # chokepoint, collective spans, mesh-keyed compile listener) is
    # default-ON like the flight recorder — assert it is actually live
    # while the band below is measured, so the devstats plane is proven
    # free at the PR-2 latency floor (every aggregator poll also pulls
    # its MSG_STATS "devices" snapshot through stats_payload)
    from multiverso_tpu.telemetry import devstats
    devstats.configure(0)
    if not devstats.enabled():
        raise AssertionError(
            "devstats default-on gate is off: the band below would be "
            "measured without the device-observability plane")
    # ISSUE 10 acceptance config: the byte LEDGER is always on, and the
    # memstats sampler (host RSS + jax.live_arrays device census +
    # verdict sweep) runs live at 1 Hz while the timed loops measure —
    # the band assertion then proves the whole memory-observability
    # plane is also free at the PR-2 latency floor
    config.set_flag("memstats_interval_s", 1.0)
    # ISSUE 19 acceptance config: the SLO sentinel arms from the
    # declarative `slo_spec` flag (the production path — lazy arm on
    # the first aggregator poll) with a quiet availability objective on
    # the window-on table. Burn-rate math then runs on EVERY poll the
    # band is measured under, and the run must end with zero episodes:
    # a sentinel that pages on a healthy microbench is a broken
    # sentinel, and one that never evaluated proves nothing
    config.set_flag("slo_spec", json.dumps({"objectives": [
        {"name": "small_add_availability", "kind": "availability",
         "table": "sa_on", "target": 0.99}]}))

    rows, cols = 1024, 32
    rng = np.random.default_rng(5)
    with tempfile.TemporaryDirectory(prefix="mv_small_add_") as rdv_dir:
        rdv = FileRendezvous(rdv_dir)
        ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
        t_off = AsyncMatrixTable(rows, cols, name="sa_off", ctx=ctxs[0])
        AsyncMatrixTable(rows, cols, name="sa_off", ctx=ctxs[1])
        t_on = AsyncMatrixTable(rows, cols, name="sa_on",
                                send_window_ms=2.0, ctx=ctxs[0])
        AsyncMatrixTable(rows, cols, name="sa_on", ctx=ctxs[1])

        # remote-owned single rows: rank 1 owns [512, 1024)
        ids = rng.integers(rows // 2, rows, iters)
        vals = rng.normal(size=(iters, 1, cols)).astype(np.float32)
        for i in range(32):   # warm conns + compile the shard update
            t_off.add_rows_async([ids[i]], vals[i])
            t_on.add_rows_async([ids[i]], vals[i])
        t_off.flush()
        t_on.flush()

        def one_arm(table):
            """One arm's timed loop: each call's own latency, drains
            (untimed) every 50 calls so queues stay bounded. The arms run
            as separate loops — interleaving them per-iteration lets one
            arm's server-side storm (in-process threads) pollute the
            other's p50 — and alternate across passes so load drift
            cancels in the best-of-2."""
            samples = []
            for i in range(iters):
                row, v = [ids[i]], vals[i]
                t0 = time.perf_counter()
                table.add_rows_async(row, v)
                samples.append(time.perf_counter() - t0)
                if (i + 1) % 50 == 0:
                    table.flush()
            table.flush()
            return samples

        def one_pass():
            t_wall0 = time.perf_counter()
            on_s = one_arm(t_on)
            off_s = one_arm(t_off)
            wall = time.perf_counter() - t_wall0
            off_p50 = float(np.percentile(np.asarray(off_s) * 1e3, 50))
            on_p50 = float(np.percentile(np.asarray(on_s) * 1e3, 50))
            return {"window_off_p50_ms": round(off_p50, 5),
                    "window_on_p50_ms": round(on_p50, 5),
                    "speedup": (round(off_p50 / on_p50, 2)
                                if on_p50 > 0 else None),
                    "both_arms_wall_s": round(wall, 3)}

        # best-of-2, the repo's bench protocol for this box (single-shot
        # socket+GIL noise is ~±25%; see bench_async_ps's note) — both
        # passes stay on the record. The aggregator MUST be live (that's
        # the acceptance config, not an optional extra), and a full
        # cluster poll is forced between the passes: the short timed
        # loops can finish inside the first 1 Hz background wakeup, and
        # the band below must be measured with polling provably
        # interleaved, not merely enabled.
        agg = aggregator.global_aggregator()
        if agg is None:
            raise AssertionError(
                "stats aggregator did not start: the band below would "
                "be measured without the cluster-observability load")
        # same rule for the memory plane: a full ledger sample (RSS +
        # device census + verdict sweep) is forced between the passes —
        # the short timed loops can finish inside the sampler's first
        # 1 Hz wakeup, and the band must be measured with sampling
        # provably interleaved, not merely enabled
        from multiverso_tpu.telemetry import memstats
        passes = [one_pass()]
        agg.poll_once()
        if memstats.maybe_sample() is None:
            raise AssertionError(
                "memstats_interval_s=1 did not arm the sampler: the "
                "band below would be measured without the "
                "memory-observability load")
        passes.append(one_pass())
        best = max(passes, key=lambda p: p["speedup"] or 0.0)

        # every pass fed both tables the same logical stream, so parity
        # must be bit-for-bit — and a latency number without it is
        # meaningless, so parity failure is a FAILED run, not a field
        parity = bool(np.array_equal(t_on.get(), t_off.get()))
        if not parity:
            raise AssertionError(
                "send-window parity broke: window-on table diverged from "
                "window-off under the identical add stream")
        # ISSUE 18 acceptance, asserted in-run like parity: the tenant
        # attribution plane is COMPILED IN on the measured path. The
        # windowed MSG_BATCH frames punt to the python server even
        # where the native transport is built, so rank 1's shard meter
        # counted every timed window-on add via the default-tenant
        # fast path (one attribute read + one dict increment per op) —
        # the band below is measured WITH tenant accounting live, not
        # merely imported
        ten = (t_on.server_stats(1)["shards"]["sa_on"].get("tenants")
               or {})
        tenant_default_ops = int((ten.get("default") or {})
                                 .get("ops", 0))
        if tenant_default_ops <= 0:
            raise AssertionError(
                "tenant meter never counted on the window-on shard: "
                "the band below would be measured without the tenant "
                "accounting plane")
        # PR-4 acceptance, asserted in-run like parity: the ALWAYS-ON
        # flight recorder (one ring write on the windowed-add hot path,
        # begin/end-op tracking per wire frame) must be invisible at the
        # PR-2/PR-3 band — window-on p50 stays within 0.03-0.06 ms on
        # this box (best-of-2, the bench protocol's noise floor)
        flightrec_band = (0.03, 0.06)
        if best["window_on_p50_ms"] > flightrec_band[1]:
            raise AssertionError(
                f"window-on p50 {best['window_on_p50_ms']} ms left the "
                f"PR-2/PR-3 band (<= {flightrec_band[1]} ms): the "
                "always-on flight recorder / telemetry plane is no "
                "longer free on the hot path")
        mon = {k: Dashboard.get(f"table[sa_on].add_rows.{k}").count
               for k in ("windowed", "flushes", "merged_rows")}
        # telemetry-plane record: the monitors' own latency histograms
        # (every add_rows call both arms made, warmup included) ride
        # along with the timed-loop percentiles above — p50/p99/max per
        # arm instead of a bare mean
        hist = {arm: Dashboard.get(f"table[{arm}].add_rows")
                .snapshot().brief_dict()
                for arm in ("sa_on", "sa_off")}
        # cluster record: the final poll carries the merged 2-rank shard
        # stats, skew, and the hot-row sketch heads into the record
        final_rec = agg.poll_once()
        cluster = aggregator.compact_record(final_rec)
        cluster["polls"] = len(agg.history())
        # ISSUE 19 acceptance, asserted in-run like parity: the flag-
        # armed sentinel must have actually judged the polls the band
        # was measured under (evals > 0 proves the lazy arm fired and
        # burn-rate math ran), and a healthy microbench must end with
        # ZERO episodes — the false-fire guard at the latency floor
        slo_snap = final_rec.get("slo") or {}
        if int(slo_snap.get("evals") or 0) < 1:
            raise AssertionError(
                "slo_spec flag never armed the sentinel: the band "
                "above would be measured without the SLO plane")
        if int(slo_snap.get("episodes") or 0) > 0:
            raise AssertionError(
                "SLO sentinel fired %r on a healthy small-add bench: "
                "false alarm at the latency floor" % (
                    slo_snap.get("recent"),))
        slo_extra = {
            "evals": int(slo_snap.get("evals") or 0),
            "episodes": {name: int(o.get("episodes") or 0)
                         for name, o in (slo_snap.get("objectives")
                                         or {}).items()},
            "firing": list(slo_snap.get("firing") or []),
        }
        # memory plane, asserted live like the aggregator above: the
        # sampler must have actually sampled during the timed loops
        # (memstats_interval_s=1 was the acceptance config, and the
        # band above was measured WITH it running, not merely set)
        mem_samples = len(memstats.LEDGER.samples())
        if mem_samples < 1:
            raise AssertionError(
                "memstats sampler never sampled: the band above would "
                "be measured without the memory-observability load")
        mem = memstats.bench_extra()
        for c in ctxs:
            c.close()

    print("RESULT " + json.dumps(dict(
        best, iters=iters, passes=passes, window_counters=mon,
        latency_hist=hist, parity_bit_for_bit=parity,
        flightrec_band_ms=list(flightrec_band),
        memstats_samples=mem_samples, memory=mem,
        devstats_live=devstats.enabled(),
        tenant_default_ops=tenant_default_ops,
        slo=slo_extra,
        # ISSUE 14 acceptance evidence: the fault-injection plane is
        # COMPILED IN (ps/service.py imports it unconditionally; its
        # hook guards ran on every timed add above) but DISARMED —
        # the band assertion above therefore proves the disarmed
        # plane costs nothing measurable on the hot path
        fault_plane_armed=_fault_plane_armed(),
        cluster=cluster)), flush=True)


def _fault_plane_armed() -> bool:
    from multiverso_tpu.ps import faults
    if faults.PLANE.armed:
        raise AssertionError(
            "fault plane is ARMED during the small-add band bench: "
            "the band would measure chaos, not the hot path")
    return False


if __name__ == "__main__":
    main()
