"""Worker for bench.bench_aggregate_path: np=N jax.distributed CPU
processes timing mv.aggregate through (a) the device process_sum path and
(b) the legacy allgather+numpy-sum, on the same payload.

Invoked: python tools/bench_aggregate.py <coord_port> <world> <rank> <mb>
Rank 0 prints "RESULT {...}".
"""
import json
import sys
import time


def main():
    port, world, rank, mb = (int(sys.argv[1]), int(sys.argv[2]),
                             int(sys.argv[3]), float(sys.argv[4]))
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(f"127.0.0.1:{port}", world, rank)
    import numpy as np

    from multiverso_tpu.parallel.collectives import process_sum

    n = int(mb * 1e6 / 4)
    arr = np.full(n, float(rank + 1), np.float32)

    def legacy(a):
        from jax.experimental import multihost_utils
        g = multihost_utils.process_allgather(a, tiled=False)
        return np.asarray(g).sum(axis=0).astype(a.dtype)

    out = {}
    for name, fn in (("process_sum", process_sum), ("allgather", legacy)):
        fn(arr)                     # warm/compile
        reps, t0 = 5, time.monotonic()
        for _ in range(reps):
            got = fn(arr)
        dt = (time.monotonic() - t0) / reps
        assert got[0] == world * (world + 1) / 2, got[0]
        out[name + "_ms"] = round(dt * 1e3, 2)
    out["speedup"] = round(out["allgather_ms"] / out["process_sum_ms"], 2)
    if rank == 0:
        print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
