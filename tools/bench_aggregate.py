"""Worker for bench.bench_aggregate_path: np=N jax.distributed CPU
processes timing mv.aggregate through (a) the device process_sum path and
(b) the legacy allgather+numpy-sum, on the same payload.

Invoked: python tools/bench_aggregate.py <coord_port> <world> <rank> <mb>
Rank 0 prints "RESULT {...}".
"""
import json
import sys
import time


def main():
    port, world, rank, mb = (int(sys.argv[1]), int(sys.argv[2]),
                             int(sys.argv[3]), float(sys.argv[4]))
    import jax
    jax.config.update("jax_platforms", "cpu")
    # without this, N coordinated CPU processes initialize fine and
    # then every cross-process computation (all four variants below)
    # raises "Multiprocess computations aren't implemented on the CPU
    # backend" — jaxlib has gloo, it just doesn't select it by default
    from multiverso_tpu.utils.platform import enable_cpu_collectives
    enable_cpu_collectives()
    jax.distributed.initialize(f"127.0.0.1:{port}", world, rank)
    import numpy as np

    from multiverso_tpu.parallel.collectives import process_sum

    n = int(mb * 1e6 / 4)
    arr = np.full(n, float(rank + 1), np.float32)

    def legacy(a):
        from jax.experimental import multihost_utils
        g = multihost_utils.process_allgather(a, tiled=False)
        return np.asarray(g).sum(axis=0).astype(a.dtype)

    # Compressed-wire variants of the host aggregation (VERDICT r4 item 5:
    # the 1-bit filter's design point is a slow wire; the cross-process
    # delta aggregation is the seam where its 29x byte reduction could
    # dominate encode cost — measure it against bf16 and plain here).
    def bf16_agg(a):
        import ml_dtypes
        from jax.experimental import multihost_utils
        g = multihost_utils.process_allgather(
            a.astype(ml_dtypes.bfloat16), tiled=False)
        return np.asarray(g).astype(np.float32).sum(axis=0)

    from multiverso_tpu.utils.filters import OneBitsFilter
    onebit = OneBitsFilter()

    def onebit_agg(a):
        from jax.experimental import multihost_utils
        header, bits, scales = onebit.filter_in(a)
        gb = np.asarray(multihost_utils.process_allgather(bits,
                                                          tiled=False))
        gs = np.asarray(multihost_utils.process_allgather(scales,
                                                          tiled=False))
        acc = np.zeros_like(a)
        for r in range(world):
            acc += onebit.filter_out(header, gb[r], gs[r])
        return acc

    out = {}
    want = world * (world + 1) / 2
    for name, fn, exact in (("process_sum", process_sum, True),
                            ("allgather", legacy, True),
                            ("allgather_bf16", bf16_agg, False),
                            ("allgather_1bit", onebit_agg, False)):
        fn(arr)                     # warm/compile
        reps, t0 = 5, time.monotonic()
        for _ in range(reps):
            got = fn(arr)
        dt = (time.monotonic() - t0) / reps
        if exact:
            assert got[0] == want, got[0]
        else:
            # lossy wires: constant positive blocks decode near-exactly
            assert abs(got[0] - want) < 0.1 * want, (name, got[0])
        out[name + "_ms"] = round(dt * 1e3, 2)
    out["speedup"] = round(out["allgather_ms"] / out["process_sum_ms"], 2)
    out["bf16_vs_plain"] = round(out["allgather_ms"]
                                 / out["allgather_bf16_ms"], 2)
    out["1bit_vs_plain"] = round(out["allgather_ms"]
                                 / out["allgather_1bit_ms"], 2)
    out["1bit_vs_bf16"] = round(out["allgather_bf16_ms"]
                                / out["allgather_1bit_ms"], 2)
    if rank == 0:
        print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
