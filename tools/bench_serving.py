#!/usr/bin/env python
"""Online-serving bench: DLRM training writes vs a zipf inference storm.

The read-dominated half of the PS story (ROADMAP open item 3;
docs/SERVING.md). One process hosts a 2-rank async-PS world (every
cross-rank op crosses a real localhost socket, the tier-2 fixture
shape); the DLRM embedding table is row-sharded over both ranks, and
two traffic classes hit it concurrently:

* ``train_threads`` training workers run real DLRM steps — gather the
  minibatch rows from the shards, jitted grad, push row-gradient
  deltas as blocking adds (the ack means applied; its latency is the
  bench's PROTECTED metric);
* ``infer_threads`` inference clients hammer the bounded-staleness
  :class:`ReadReplica` with a zipf key distribution (hot users — ONE
  shared rank->id permutation, so training and inference agree on who
  is hot, as they do in production), recording per-request latency,
  the served snapshot's age, and admission sheds.

Three phases: **calibration** (unpaced, no admission — measures the
achievable inference rate UNDER the concurrent training load, which is
what the admission budget must be set against; an unloaded calibration
would pick a limit the loaded plane never reaches), then **steady**
(paced inside the admission budget; shed-free), then **overload**
(unpaced — demand far over the token-bucket limit). The acceptance
contract is asserted IN-RUN:

* measured replica staleness <= the advertised bound on every served
  read;
* replica-served bytes bit-identical to a direct shard read at the
  advertised version (writes quiesced, one final refresh, full-table
  compare);
* the admission plane SHED inference load during overload while the
  training-write p50 degraded <= 2x its steady value.

It also closes the PR-6 loop: the Space-Saving sketch's
cache-hit-if-cached ESTIMATE (at the replica cache's size) is recorded
side by side with the cache's MEASURED hit rate (counted from overload
start, after the sketch-seeded cache has warmed).

    python tools/bench_serving.py [seconds] [infer_threads] [train_threads]

Prints ``RESULT <json>`` (the bench.py worker contract); exits nonzero
when an acceptance assert fails — a serving bench whose staleness or
parity story broke must fail loudly, not record a QPS number.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

TABLE_BASE = "dlrm_srv"
CACHE_ROWS = 128
REFRESH_S = 0.2
BOUND_S = 1.0
ZIPF_A = 1.2
# client backoff after a shed (retry-after): long enough that shed
# ATTEMPTS don't themselves churn the GIL against the training plane —
# shedding protects training only if refused clients actually yield
SHED_BACKOFF_S = 0.005
PHASES = ("calib", "steady", "overload")


def _zipf_sampler(rng: np.random.Generator, n: int, perm: np.ndarray,
                  a: float = ZIPF_A):
    """Bounded zipf over [0, n): rank-frequency p(k) ~ 1/k^a. ``perm``
    is the rank->id mapping — SHARED across every sampler in the run,
    so all traffic classes agree on which ids are hot (each caller
    still draws from its own rng)."""
    p = 1.0 / np.arange(1, n + 1, dtype=np.float64) ** a
    p /= p.sum()

    def sample(size: int) -> np.ndarray:
        return perm[rng.choice(n, size=size, p=p)]

    return sample


def _pct(samples, q):
    return round(float(np.percentile(np.asarray(samples), q)), 4) \
        if len(samples) else None


def main(argv) -> int:
    seconds = float(argv[0]) if argv else 10.0
    infer_threads = int(argv[1]) if len(argv) > 1 else 4
    train_threads = int(argv[2]) if len(argv) > 2 else 2

    import jax
    jax.config.update("jax_platforms", "cpu")
    import tempfile

    from multiverso_tpu.apps.dlrm_serving import DLRMServing
    from multiverso_tpu.models import dlrm
    from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                           PSService)
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    from multiverso_tpu.serving.admission import SheddingError
    from multiverso_tpu.telemetry import hotkeys as hotkeys_mod
    from multiverso_tpu.utils import config

    config.set_flag("ps_timeout", 60.0)
    config.set_flag("serving_snapshot_chunk_rows", 2048)
    # sketch capacity sized to the workload's distinct-key count
    # (~5.4k): at the 128 default every eviction inherits the min and
    # the top-K counts overestimate several-fold — the estimate the
    # bench validates would be an artifact of sketch pressure, not of
    # the traffic (read BEFORE the shards construct)
    config.set_flag("hotkeys_capacity", 1024)
    rdv = FileRendezvous(tempfile.mkdtemp(prefix="mv_serving_"))
    ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
    cfg = dlrm.DLRMConfig(vocab_sizes=(4096, 1024, 256, 64),
                          embed_dim=16, dense_dim=8,
                          bottom_mlp=(32, 16), top_mlp=(16, 1))
    app = DLRMServing(cfg, ctx=ctxs[0], name=TABLE_BASE, lr=0.05,
                      cache_rows=CACHE_ROWS, refresh_s=REFRESH_S,
                      staleness_s=BOUND_S)
    # rank 1's half of the sharded embedding table (same seed: each
    # shard inits its own rows from (seed, lo))
    peer = AsyncMatrixTable(dlrm.total_rows(cfg), cfg.embed_dim,
                            updater="adagrad", seed=0, init_scale=0.05,
                            name=app.emb.name, ctx=ctxs[1])
    table = app.emb.name

    cat, dense, labels = dlrm.synthetic_ctr(cfg, 8192, seed=2)
    # the ONE hot-user permutation every sampler shares
    perm = np.random.default_rng(13).permutation(cfg.vocab_sizes[0])
    zipf_train = _zipf_sampler(np.random.default_rng(11),
                               cfg.vocab_sizes[0], perm)
    # training's field-0 traffic rides the SAME zipf head as inference
    # (hot users are hot everywhere), so the shard-side sketch — which
    # only ever sees shard traffic, never replica-served reads — ranks
    # the head the inference mix hits
    cat[:, 0] = zipf_train(len(cat))

    # ---------------- warmup: compile everything once ----------------- #
    app.train_step(cat[:64], dense[:64], labels[:64])
    app.replica.refresh()
    app.infer(cat[:16], dense[:16])

    # ---------------- the two-class traffic run ----------------------- #
    stop = threading.Event()
    ctl = {"phase": "calib", "pace": 0.0}   # workers read, main writes
    results = []   # per-thread dicts, merged after the join
    losses = []

    def train_worker(j: int) -> None:
        r = np.random.default_rng(100 + j)
        my = {"write_ms": {p: [] for p in PHASES}, "errors": 0}
        results.append(my)
        bs = 64
        while not stop.is_set():
            idx = r.integers(0, len(labels), bs)
            try:
                loss, write_ms = app.train_step(cat[idx], dense[idx],
                                                labels[idx])
            except Exception:   # noqa: BLE001 — counted, not fatal
                my["errors"] += 1
                continue
            losses.append(loss)
            my["write_ms"][ctl["phase"]].append(write_ms)

    def infer_worker(j: int) -> None:
        from multiverso_tpu.telemetry.tenants import tenant_scope
        r = np.random.default_rng(200 + j)
        zipf = _zipf_sampler(np.random.default_rng(300 + j),
                             cfg.vocab_sizes[0], perm)
        # tenant attribution (ISSUE 18): worker 0 is the "victim"
        # tenant, the rest are one "storm" tenant — the per-tenant
        # served/shed/p99 split in extra.serving.tenants is what
        # run_bench's victim-tenant regression flags trend on
        tenant = "victim" if j == 0 else "storm"
        my = {"lat_ms": {p: [] for p in PHASES},
              "served": {p: 0 for p in PHASES},
              "shed": {p: 0 for p in PHASES},
              "age_max": 0.0, "errors": 0, "tenant": tenant}
        results.append(my)
        B = 16
        next_t = time.perf_counter()
        with tenant_scope(tenant):
            _infer_loop(j, r, zipf, my, B, next_t)

    def _infer_loop(j, r, zipf, my, B, next_t) -> None:
        while not stop.is_set():
            c = np.stack(
                [zipf(B)] + [r.integers(0, v, B)
                             for v in cfg.vocab_sizes[1:]], axis=1)
            ids = app._ids(c)
            ph = ctl["phase"]
            t0 = time.perf_counter()
            try:
                _rows, age = app.replica.get_rows(ids, with_age=True)
            except SheddingError:
                my["shed"][ph] += 1
                time.sleep(SHED_BACKOFF_S)
                continue
            except Exception:   # noqa: BLE001
                my["errors"] += 1
                continue
            my["lat_ms"][ph].append((time.perf_counter() - t0) * 1e3)
            my["served"][ph] += 1
            my["age_max"] = max(my["age_max"], age)
            if my["served"][ph] % 64 == 0:
                # every so often, the full app path (replica rows ->
                # jitted forward -> scores): the serving story is an
                # APP, not a gather microbench
                try:
                    app.infer(c, dense[: B])
                except SheddingError:
                    my["shed"][ph] += 1
                except Exception:   # noqa: BLE001 — a transient owner
                    # timeout in the deferred-refresh path must be
                    # COUNTED, not kill this daemon worker silently
                    # (the surviving threads would then report a
                    # phantom served-QPS drop with errors=0)
                    my["errors"] += 1
            pace = ctl["pace"]
            if pace > 0 and ph == "steady":
                next_t = max(next_t + pace, time.perf_counter() - pace)
                dt = next_t - time.perf_counter()
                if dt > 0:
                    time.sleep(dt)
            # calib/overload: unpaced — demand is whatever the loop
            # can push through

    threads = [threading.Thread(target=train_worker, args=(j,),
                                daemon=True)
               for j in range(train_threads)]
    threads += [threading.Thread(target=infer_worker, args=(j,),
                                 daemon=True)
                for j in range(infer_threads)]
    calib_s = 1.0
    steady_s = max(seconds * 0.5, 2.0)
    overload_s = max(seconds * 0.5, 2.0)
    for th in threads:
        th.start()
    # phase 1 — calibration: unpaced, no admission limit installed.
    # Measures the achievable inference rate UNDER the training load;
    # the budget derives from this, not from an unloaded microbench.
    time.sleep(calib_s)
    calib_served = sum(my["served"]["calib"] for my in results
                       if "served" in my)
    loaded_qps = max(calib_served / calib_s, 50.0)
    # budget well under the achievable rate, steady paced AT ~the
    # budget: overload then admits the same inference load steady
    # carried (so the training plane feels no extra admitted work) and
    # sheds the rest — which is exactly the protection contract the
    # overload phase asserts
    limit_qps = loaded_qps * 0.3
    steady_qps = limit_qps * 0.95
    # small burst: overload's admitted traffic then arrives nearly as
    # evenly as steady's paced traffic, so the two phases put the SAME
    # admitted load on the box and the degradation ratio isolates what
    # the shed path itself costs
    app.admission.set_limit(table, "infer", limit_qps,
                            burst=max(limit_qps * 0.1, 2.0))
    ctl["pace"] = infer_threads / steady_qps
    ctl["phase"] = "steady"
    time.sleep(steady_s)
    # cache-hit accounting baseline: measured hit rate is counted from
    # HERE (cache seeded + reseeded during steady; counting the cold
    # start would understate what the warmed cache absorbs)
    rs0 = app.replica.stats()
    h0, m0 = rs0["cache_hits"], rs0["cache_misses"]
    ctl["pace"] = 0.0
    ctl["phase"] = "overload"
    time.sleep(overload_s)
    stop.set()
    for th in threads:
        th.join(timeout=60)
    # cache measurement window closes HERE, before the parity sweep
    # below reads the whole table through the replica (a 5.4k-row
    # uniform sweep over a 128-row cache would dilute the measured
    # workload hit rate with a non-workload artifact)
    rs1 = app.replica.stats()
    dh = rs1["cache_hits"] - h0
    dm = rs1["cache_misses"] - m0

    # ---------------- merge + derive ---------------------------------- #
    train_ms = {p: [] for p in PHASES}
    infer_ms = {p: [] for p in PHASES}
    served = {p: 0 for p in PHASES}
    shed = {p: 0 for p in PHASES}
    age_max = 0.0
    errors = 0
    for my in results:
        errors += my.get("errors", 0)
        if "write_ms" in my:
            for p in PHASES:
                train_ms[p].extend(my["write_ms"][p])
        else:
            for p in PHASES:
                infer_ms[p].extend(my["lat_ms"][p])
                served[p] += my["served"][p]
                shed[p] += my["shed"][p]
            age_max = max(age_max, my["age_max"])

    # per-tenant split (ISSUE 18), same steady+overload window as the
    # aggregate infer percentiles: the victim keys feed run_bench's
    # floored regression flags, so their names are load-bearing
    tenants_acc = {}
    for my in results:
        if "lat_ms" not in my:
            continue
        e = tenants_acc.setdefault(
            my["tenant"], {"served": 0, "shed": 0, "lat": []})
        e["served"] += my["served"]["steady"] + my["served"]["overload"]
        e["shed"] += my["shed"]["steady"] + my["shed"]["overload"]
        e["lat"].extend(my["lat_ms"]["steady"] + my["lat_ms"]["overload"])
    tenants_res = {
        t: {"served": e["served"], "shed": e["shed"],
            "shed_rate": round(
                e["shed"] / max(e["served"] + e["shed"], 1), 4),
            "infer_p99_ms": _pct(e["lat"], 99)}
        for t, e in sorted(tenants_acc.items())}

    all_infer = infer_ms["steady"] + infer_ms["overload"]
    train_p50_steady = _pct(train_ms["steady"], 50)
    train_p50_overload = _pct(train_ms["overload"], 50)
    degradation = (round(train_p50_overload / train_p50_steady, 3)
                   if train_p50_steady and train_p50_overload else None)
    demand_overload = served["overload"] + shed["overload"]
    shed_rate_overload = (round(shed["overload"] / demand_overload, 4)
                          if demand_overload else 0.0)

    # ---------------- parity at the advertised version ---------------- #
    # writes are quiesced (threads joined, blocking adds all acked);
    # one final refresh pins the replica at the shards' final version,
    # and the full-table compare must be bit-for-bit
    app.emb.flush()
    app.replica.refresh()
    all_ids = np.arange(dlrm.total_rows(cfg))
    direct = app.emb.get_rows(all_ids)
    via_replica = app.replica.get_rows(all_ids, cls="train")
    parity = bool(np.array_equal(direct, via_replica))
    rep_stats = app.replica.stats()
    shard_versions = {}
    for rank in (0, 1):
        try:
            sh = app.emb.server_stats(rank)["shards"][table]
            shard_versions[str(rank)] = {
                "version": sh.get("version"),
                "snapshots": sh.get("snapshots"),
                "snapshots_unchanged": sh.get("snapshots_unchanged"),
            }
        except Exception as e:   # noqa: BLE001 — stats are best-effort
            shard_versions[str(rank)] = {"error": str(e)[:120]}
    versions_match = all(
        str(rep_stats["versions"].get(r)) == str(v.get("version"))
        for r, v in shard_versions.items() if "version" in v)

    # ---------------- PR-6 loop: estimate vs measured hit rate -------- #
    sketches = []
    for rank in (0, 1):
        try:
            sk = (app.emb.server_stats(rank)["shards"][table]
                  .get("hotkeys"))
            if sk:
                sketches.append(sk)
        except Exception:   # noqa: BLE001
            pass
    merged = hotkeys_mod.merge_sketches(sketches)
    k = rep_stats["cache_rows"]
    items = merged.get("items", [])
    total = merged.get("total") or 0
    # the sketch's two curves bracket the truth: raw counts are the
    # upper bound (overestimates within err), count-err the guaranteed
    # lower bound; the MEASURED replica-cache hit rate must land
    # between them (recorded side by side — the PR-6 loop closed)
    est_hi = (round(sum(c for _k2, c, _e in items[:k]) / total, 4)
              if k and total else None)
    est_lo = (round(sum(max(c - e, 0)
                        for _k2, c, e in items[:k]) / total, 4)
              if k and total else None)
    measured = round(dh / (dh + dm), 4) if (dh + dm) else None
    # the validation contract: the sketch estimate is a sizing FLOOR,
    # not a bracket. The sketch observes POST-dedupe shard traffic
    # (the client's _dedupe_batch collapses a batch's duplicate hot
    # ids to one, so a zipf head that appears 8x in a minibatch counts
    # once), while the cache absorbs the raw pre-dedupe request
    # stream — measured absorption therefore legitimately runs ABOVE
    # the estimate, and the thing that must hold for the sketch to be
    # a sound cache-sizing input is that it never OVER-promises:
    # measured >= the conservative (count - err) estimate, with noise
    # slack
    floor_ok = (est_lo is not None and measured is not None
                and measured >= est_lo - 0.05)
    hit_rate = {
        "cache_rows": k,
        "estimated_hit_rate": est_hi,
        "estimated_hit_rate_lower": est_lo,
        "measured_hit_rate": measured,
        "estimate_err": (round(measured - est_hi, 4)
                         if est_hi is not None and measured is not None
                         else None),
        "estimate_is_floor_ok": floor_ok,
        "hit_rate_curve": hotkeys_mod.hit_rate_curve(merged),
        "hit_rate_curve_lower": hotkeys_mod.hit_rate_curve(
            merged, conservative=True),
    }

    staleness_ok = age_max <= BOUND_S
    overload_ok = (shed["overload"] > 0 and degradation is not None
                   and degradation <= 2.0)
    result = {
        "served_qps": round((served["steady"] + served["overload"])
                            / (steady_s + overload_s), 1),
        "served_qps_steady": round(served["steady"] / steady_s, 1),
        "served_qps_overload": round(served["overload"] / overload_s, 1),
        "loaded_calib_qps": round(loaded_qps, 1),
        "admission_limit_qps": round(limit_qps, 1),
        "infer_p50_ms": _pct(all_infer, 50),
        "infer_p99_ms": _pct(all_infer, 99),
        "infer_p999_ms": _pct(all_infer, 99.9),
        "train_p50_steady_ms": train_p50_steady,
        "train_p50_overload_ms": train_p50_overload,
        "train_write_degradation_x": degradation,
        "shed_steady": shed["steady"], "shed_overload": shed["overload"],
        "shed_rate_overload": shed_rate_overload,
        "tenants": tenants_res,
        "staleness_bound_s": BOUND_S,
        "staleness_max_s": round(age_max, 4),
        "staleness_ok": staleness_ok,
        "parity_bit_for_bit": parity,
        "versions_match": versions_match,
        "overload_contract_ok": overload_ok,
        "cache": hit_rate,
        "replica": {k2: rep_stats[k2] for k2 in
                    ("epoch", "refresh_ms", "unchanged_pulls",
                     "deferred", "served", "versions")},
        "shards": shard_versions,
        "loss_first": round(float(losses[0]), 4) if losses else None,
        "loss_last": round(float(np.mean(losses[-16:])), 4)
        if losses else None,
        "errors": errors,
        "infer_threads": infer_threads, "train_threads": train_threads,
        "seconds": seconds,
    }
    app.close()
    for c in ctxs:
        c.close()
    del peer
    print("RESULT " + json.dumps(result), flush=True)
    # acceptance gates, asserted in-run: a serving bench whose
    # staleness, parity, or overload-protection story broke must fail
    # loudly rather than record a throughput number
    if not (parity and staleness_ok and overload_ok):
        sys.stderr.write(
            f"bench_serving: acceptance failed (parity={parity}, "
            f"staleness_ok={staleness_ok}, overload_ok={overload_ok})\n")
        return 3
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
