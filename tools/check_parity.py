"""Validate PARITY.md / ARCHITECTURE.md code citations.

Every `path/to/file.py:NN` (or `:NN-MM`) citation must point at an existing
file with at least NN lines, so the component-inventory claims stay
checkable as the code moves. Run: python tools/check_parity.py
(exit 0 = all citations resolve; also exercised by tests/test_utils.py).
"""

import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DOCS = ("PARITY.md", "ARCHITECTURE.md", "README.md")
# spans: NN, NN-MM, and comma lists thereof (e.g. `table.py:83,241`)
_CITE = re.compile(r"`([\w/\.]+\.(?:py|cpp|h|lua)):"
                   r"(\d+(?:-\d+)?(?:,\d+(?:-\d+)?)*)`")
# upstream-reference directory layout: these resolve against the read-only
# /root/reference mount and are skipped (not silently passed off as in-repo
# files) when the mount is absent
_REF_PREFIXES = ("src/", "include/", "binding/", "Applications/", "Test/")


def _line_count(path, cache={}):
    if path not in cache:
        with open(path) as f:
            cache[path] = sum(1 for _ in f)
    return cache[path]


def check(docs=_DOCS) -> list:
    """Return [(doc, citation, problem)] for every unresolvable citation."""
    problems = []
    for doc in docs:
        doc_path = os.path.join(_REPO, doc)
        if not os.path.exists(doc_path):
            continue
        with open(doc_path) as f:
            text = f.read()
        for fname, spans in set(_CITE.findall(text)):
            path = os.path.join(_REPO, fname)
            if not os.path.exists(path):
                # references into the package are often written relative
                # to multiverso_tpu/
                path = os.path.join(_REPO, "multiverso_tpu", fname)
            if not os.path.exists(path):
                if fname.startswith(_REF_PREFIXES):
                    ref = os.path.join("/root/reference", fname)
                    if os.path.exists(ref):
                        path = ref
                    elif os.path.isdir("/root/reference"):
                        problems.append((doc, fname, "missing file"))
                        continue
                    else:
                        continue  # no mount: reference cites unverifiable
                else:
                    problems.append((doc, fname, "missing file"))
                    continue
            n = _line_count(path)
            hi = max(int(x) for x in re.split(r"[-,]", spans))
            if hi > n:
                problems.append((doc, f"{fname}:{spans}",
                                 f"file has only {n} lines"))
    return problems


def main() -> int:
    problems = check()
    if problems:
        for doc, cite, why in problems:
            print(f"{doc}: `{cite}` -> {why}")
        return 1
    print("all documentation citations resolve")
    return 0


if __name__ == "__main__":
    sys.exit(main())
