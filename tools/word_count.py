"""Word-frequency generator: the WordEmbedding preprocess step
(ref Applications/WordEmbedding/preprocess/word_count.cpp — count the
train file's tokens, write ``word count`` lines for words at or above
min_count; the trainer then loads this via ``-read_vocab`` instead of
re-scanning the corpus on every run).

Usage:
    python tools/word_count.py -train_file <corpus> -save_vocab <out>
                               [-min_count N]
"""

from __future__ import annotations

import collections
import sys


def count_file(train_file: str, chunk_bytes: int = 1 << 22
               ) -> collections.Counter:
    counter: collections.Counter = collections.Counter()
    tail = b""
    with open(train_file, "rb") as f:
        for chunk in iter(lambda: f.read(chunk_bytes), b""):
            chunk = tail + chunk
            parts = chunk.split()
            # a token (or multi-byte char) straddling the chunk boundary
            # must not be counted as two fragments: carry the trailing
            # partial token into the next chunk
            if parts and not chunk[-1:].isspace():
                tail = parts.pop()
            else:
                tail = b""
            counter.update(
                t.decode("utf-8", errors="replace") for t in parts)
    if tail:
        counter[tail.decode("utf-8", errors="replace")] += 1
    return counter


def write_vocab(counter, save_vocab: str, min_count: int) -> int:
    """count-desc order (the word2vec vocab convention the Dictionary
    adopts as word ids; the reference wrote map order, which its own
    reader immediately re-sorted)."""
    items = sorted(((w, c) for w, c in counter.items() if c >= min_count),
                   key=lambda wc: (-wc[1], wc[0]))
    with open(save_vocab, "w") as f:
        for w, c in items:
            f.write(f"{w} {c}\n")
    return len(items)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    kw = {argv[i].lstrip("-"): argv[i + 1]
          for i in range(0, len(argv) - 1, 2) if argv[i].startswith("-")}
    train_file = kw.get("train_file")
    save_vocab = kw.get("save_vocab")
    if not train_file or not save_vocab:
        print(__doc__, file=sys.stderr)
        return 2
    n = write_vocab(count_file(train_file), save_vocab,
                    int(kw.get("min_count", "5")))
    print(f"wrote {n} words to {save_vocab}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
