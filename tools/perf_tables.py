"""Table micro-perf harness.

Parity with the reference's in-tree perf tests
(ref: Test/main.cpp:340-495 TestDensePerf/TestSparsePerf — timings of
whole-table Get, row-batch Add/Get on a 1M x 50 float matrix, plus a
Dashboard dump). Run on the real chip:

    python tools/perf_tables.py [rows] [cols]
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import multiverso_tpu as mv
from multiverso_tpu.utils.dashboard import Dashboard


def timeit(fn, n=10, warmup=True):
    """Differential (two-point slope) ms/op via bench._differential —
    single-shot timings are meaningless over the tunneled chip (see the
    bench.py docstring). ``warmup=False`` + ``n=1``: stateful one-shot op
    whose first call IS the measurement (wall time incl. the fixed tunnel
    round-trip; a warmup would consume the state being measured)."""
    from bench import _differential
    if warmup:
        fn()  # compile

    def run(k):
        t0 = time.perf_counter()
        for _ in range(k):
            fn()
        return time.perf_counter() - t0

    lo, hi = max(n // 4, 1), n
    if hi == lo:
        return run(1) * 1e3
    return _differential(run, lo, hi)[0] * 1e3


def main():
    args = sys.argv[1:]
    if any(not a.isdigit() for a in args):  # incl. -h/--help/negatives
        print(__doc__)
        return
    rows = int(args[0]) if len(args) > 0 else 1_000_000
    cols = int(args[1]) if len(args) > 1 else 50
    mv.init()
    rng = np.random.default_rng(0)

    print(f"== dense perf: {rows} x {cols} float32 "
          f"({rows * cols * 4 / 1e6:.0f} MB) ==")
    m = mv.MatrixTable(rows, cols, name="perf_dense")
    full = rng.normal(size=(rows, cols)).astype(np.float32)
    print(f"add all      : {timeit(lambda: m.add(full), 5):9.2f} ms")
    print(f"get all      : {timeit(lambda: m.get(), 5):9.2f} ms")

    for k in (10, 1000, 100_000):
        ids = rng.choice(rows, size=k, replace=False)
        vals = rng.normal(size=(k, cols)).astype(np.float32)
        print(f"add {k:7d} rows: {timeit(lambda: m.add_rows(ids, vals)):9.2f} ms")
        print(f"get {k:7d} rows: {timeit(lambda: m.get_rows(ids)):9.2f} ms")

    print(f"== sparse (stale-row) perf ==")
    s = mv.SparseMatrixTable(rows, cols, name="perf_sparse", num_workers=1)
    ids = rng.choice(rows, size=100_000, replace=False)
    s.get_rows_sparse(ids)  # first pull: everything stale
    t = timeit(lambda: s.get_rows_sparse(ids))
    print(f"sparse re-get of fresh 100k rows: {t:9.2f} ms "
          f"(stale fraction {s.stale_fraction(ids):.3f})")
    s.add_rows(ids[:1000], np.ones((1000, cols), np.float32))
    # no warmup: the dirty bits ARE the state being measured (the jit is
    # already warm from the fresh re-get above)
    t = timeit(lambda: s.get_rows_sparse(ids), n=1, warmup=False)
    print(f"sparse get after 1k-row dirty   : {t:9.2f} ms")

    Dashboard.display()
    mv.shutdown()


if __name__ == "__main__":
    main()
