"""Mesh scale-curve harness (ISSUE 12; measurement methodology and the
plane under test reworked by ISSUE 15): the async-PS workload at
1->2->4->8 server shards on a host-platform device mesh, judged by the
device-plane observability layer it ships with.

Each shard count ``n`` runs in its OWN subprocess ("--point" mode): an
n-rank in-process PS world with the ISSUE-15 mesh data plane ARMED
(``ps_fanout`` process-coalesced routing + multi-owner super-frames;
``ps_spmd_stack`` stacked SPMD apply/gather, exercised and
parity-gated by :func:`_parity_stage`) plus an n-device mesh slice of
the 8-virtual-device host platform. Process-per-point is load-bearing,
not convenience: two shard counts' collective executables coexisting
in one XLA CPU client raced the process-global rendezvous (observed
live: interleaved all_reduce participants wedged both worlds) — and it
also gives each point a process-fresh devstats/profiler reading.

**Constant offered load (ISSUE 15).** Every point drives the SAME
``M = min(cpu_count, 4)`` worker threads — the textbook scaling-curve
design: hold the load generators fixed, scale the resource under test.
The PR-12 harness scaled workers WITH shards (n workers at point n),
which conflated client-side thread-convoy costs (8 GIL-rotating
threads on a 2-core box) with the server plane's sharding behavior —
most of its E_8 = 0.02 was the client, not the shards. With M fixed,
E_n answers the production question directly: does adding server
shards relieve the serialization a loaded single shard exhibits? (It
does — a 1-shard server under M concurrent workers convoys on its one
lock domain, which is precisely the bottleneck Li et al.'s sharded-KV
design removes.) Ops are production-shaped (2048x128 row batches — a
~1 MB delta/pull per op) so the instrument measures the data plane,
not per-call python fixed costs.

Per point the child drives the M workers through a step-profiled
train-shaped loop (prepare / push / ps_wait over the sharded table),
then measures the model-average ``parallel/collectives.all_reduce``
QUIESCED (PS plane idle — host-platform virtual devices share one
in-process client whose collective executions must not interleave with
concurrent jit work). Recorded per point:

* **T_n** — aggregate row throughput; the parent computes
  **E_n = T_n / (n * T_1)** in-run via :func:`efficiency_curve`
  (pure; oracle-tested in tests/test_devstats.py).
* per-shard **skew** from the PR-6 aggregator's merged record;
* **stall fraction** from the PR-9 step profiler;
* per-direction **transfer bytes**, per-op **collective** tallies, and
  per-mesh-shape **compile** cost from ``telemetry/devstats.py`` —
  each compile keyed to the ``{'mv': n}`` configuration that fired it.

**Compile-hygiene gate:** every point's collective dryrun compiles
inside ``devstats.capture_hygiene``; the run FAILS (nonzero exit — a
failed sub-bench, not a degraded record) if any SPMD remat /
sharding-fallback warning classifies, or if any shard count escaped
the check. The merged report rides the RESULT for ``extra.scale`` and
dumps to ``compile-hygiene-rank<r>.json`` for ``mvprof`` when a
metrics dir is configured.

Invoked as: python tools/bench_scale.py [seconds] [shards_csv] [rows] [dim]
Prints "RESULT <json>".
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

DEFAULT_SHARDS = (1, 2, 4, 8)
# constant offered load at every point (see module docstring): the
# box's cores are its useful load generators, capped so a many-core
# host doesn't turn the curve into a client-thread study
DEFAULT_ROWS = 40_000
DEFAULT_DIM = 128
BATCH_ROWS = 2048


def worker_count() -> int:
    return max(2, min(os.cpu_count() or 2, 4))


def efficiency_curve(throughput_by_n):
    """T_n -> E_n = T_n / (n * T_1): 1.0 = perfect linear scaling.
    Pure (the E_n oracle test drives it directly). Returns
    ``{"efficiency": {n: E_n}, "efficiency_min": min E_n over n>1}`` —
    the min is the run_bench-tracked regression scalar (higher is
    better; the weakest point of the curve is the one that regressed).
    efficiency_min is None when no baseline point (n=1) exists."""
    ns = sorted(int(n) for n in throughput_by_n)
    t1 = float(throughput_by_n.get(1, throughput_by_n.get("1", 0)) or 0)
    if t1 <= 0 or not ns:
        return {"efficiency": {}, "efficiency_min": None}
    eff = {}
    for n in ns:
        t_n = float(throughput_by_n.get(n, throughput_by_n.get(str(n), 0))
                    or 0)
        eff[n] = round(t_n / (n * t1), 4)
    tail = [e for n, e in eff.items() if n > 1]
    return {"efficiency": eff,
            "efficiency_min": round(min(tail), 4) if tail else None}


def _parity_stage(n: int, dim: int, devstats) -> bool:
    """Drive a deterministic add/get sequence over an n-shard
    device-backed (adagrad) table — fan-out super-frames + the
    mesh-stacked SPMD apply/gather — and bit-compare the final table
    against a 1-shard oracle world running the CLASSIC path. Returns
    True only on an exact match; raises on plumbing failures."""
    import numpy as np

    from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                           PSService)
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    from multiverso_tpu.utils import config

    prows = 2048

    def _drive(tabs, nshards):
        rng = np.random.default_rng(99)
        for step in range(16):
            ids = np.sort(rng.choice(prows, size=96, replace=False))
            deltas = rng.normal(size=(96, dim)).astype(np.float32)
            t = tabs[step % nshards]
            if step == 0 and nshards > 1:
                sh = tabs[0]._shard
                plane = getattr(sh, "_plane", None)
                mesh = plane.mesh if plane is not None else None
                # the stacked program's first compile happens HERE:
                # capture it under the hygiene gate, keyed to the
                # plane's mesh shape
                with devstats.capture_hygiene("scale.spmd_apply",
                                              mesh=mesh):
                    t.add_rows(ids, deltas)
            else:
                t.add_rows(ids, deltas)
            t.get_rows(ids)   # grouped SPMD gather on the stacked path
        return tabs[0].get_rows(np.arange(prows))

    # the parity world rendezvouses in its OWN directory — the measured
    # world's rank addr files (and its colocation registry key) must
    # not collide with this stage's
    with tempfile.TemporaryDirectory(prefix="mv_scale_par_") as prdv:
        ctxs = [PSContext(r, n, PSService(r, n, FileRendezvous(prdv)))
                for r in range(n)]
        tabs = [AsyncMatrixTable(prows, dim, name="scale_par",
                                 updater="adagrad", ctx=ctxs[r])
                for r in range(n)]
        if n > 1 and getattr(tabs[0]._shard, "_plane", None) is None:
            raise AssertionError(
                "parity stage: the adagrad table did not group into a "
                "mesh-stacked plane (ps_spmd_stack armed?)")
        got = _drive(tabs, n)
        for c in ctxs:
            c.close()
    # 1-shard oracle world: classic storage, classic dispatch
    config.set_flag("ps_fanout", False)
    config.set_flag("ps_spmd_stack", False)
    try:
        with tempfile.TemporaryDirectory(prefix="mv_scale_orc_") as ordv:
            ctx = PSContext(0, 1, PSService(0, 1, FileRendezvous(ordv)))
            t1 = AsyncMatrixTable(prows, dim, name="scale_par_oracle",
                                  updater="adagrad", ctx=ctx)
            want = _drive([t1], 1)
            ctx.close()
    finally:
        config.set_flag("ps_fanout", True)
        config.set_flag("ps_spmd_stack", True)
    return bool(np.array_equal(got, want))


def run_point(n: int, seconds: float, rows: int, dim: int):
    """One shard count, measured in THIS (fresh) process. Returns the
    point record incl. this process's devstats snapshot and hygiene
    report — the parent merges across points."""
    from multiverso_tpu.utils.platform import force_cpu_mesh
    force_cpu_mesh(8)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from multiverso_tpu.parallel import collectives
    from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                           PSService)
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    from multiverso_tpu.telemetry import aggregator
    from multiverso_tpu.telemetry import devstats
    from multiverso_tpu.telemetry import profiler as prof
    from multiverso_tpu.utils import config

    devices = jax.devices()
    if len(devices) < n:
        raise AssertionError(
            f"host platform exposes {len(devices)} devices, need {n}: "
            "xla_force_host_platform_device_count did not take "
            "(backend initialized early?)")
    config.set_flag("ps_timeout", 120.0)
    # Local-device sharding OFF for the harness table: above
    # ps_local_shard_min_mb a shard device-shards its row range over
    # ALL local devices, making every apply an 8-participant collective
    # program — and two shards applying CONCURRENTLY race XLA-CPU's
    # process-global rendezvous and wedge the world (found by this
    # harness's own flightrec/devstats instrumentation; reproduced at
    # rows*dim*4 > 1MB, never below). The curve measures the PLANE's
    # shard scaling; single-shard intra-op sharding is a separate axis.
    config.set_flag("ps_local_shard_min_mb", 1e9)
    # the mesh data plane under measurement (ISSUE 15, ps/spmd.py):
    # process-coalesced fan-out routing + multi-owner super-frames for
    # the measured table, and the mesh-stacked SPMD apply/gather for
    # the parity stage's device-backed (adagrad) table — its grouped
    # dispatches serialize on the plane lock, so the XLA-CPU
    # rendezvous hazard above cannot recur (one multi-device program
    # in flight at a time)
    config.set_flag("ps_fanout", True)
    config.set_flag("ps_spmd_stack", True)
    # sketch sized to the workload's key set (the PR-8 bench rule): the
    # workers' strided batches touch BATCH_ROWS * M distinct hot rows,
    # and an UNDERSIZED Space-Saving sketch turns every observe into a
    # heap eviction — a worst-case pure-python tax the curve is not
    # here to measure (real deployments size the sketch to their hot
    # set)
    config.set_flag("hotkeys_capacity", 16384)
    # acceptance config: skew from the aggregator, stall fraction from
    # the step profiler, device costs from devstats — the whole
    # instrument live while the point is measured
    config.set_flag("stats_poll_interval_s", 1.0)
    config.set_flag("step_profile", True)
    prof.configure(0)
    devstats.configure(0)

    batch = BATCH_ROWS
    workers = worker_count()
    rng = np.random.default_rng(12)
    vals = rng.normal(size=(batch, dim)).astype(np.float32)
    mesh = Mesh(np.asarray(devices[:n]), ("mv",))
    # model-average payload: [n * chunk] sharded over the axis ->
    # replicated [chunk] sum (the reference Allreduce shape); the
    # upload is a real h2d transfer, counted at the chokepoint
    host_delta = rng.normal(size=(n * 2048,)).astype(np.float32)
    devstats.note_transfer(host_delta.nbytes, "h2d")
    delta = jnp.asarray(host_delta)
    # compile-hygiene gate: the dryrun compile for THIS mesh shape runs
    # inside a capture scope; SPMD remat / sharding-fallback warnings
    # become machine-readable findings the parent fails on
    with devstats.capture_hygiene("scale.all_reduce", mesh=mesh):
        collectives.all_reduce(delta, mesh=mesh).block_until_ready()

    with tempfile.TemporaryDirectory(prefix=f"mv_scale_{n}_") as rdv:
        ctxs = [PSContext(r, n, PSService(r, n, FileRendezvous(rdv)))
                for r in range(n)]
        tables = [AsyncMatrixTable(rows, dim, name="scale",
                                   ctx=ctxs[r]) for r in range(n)]
        # WARMUP (ISSUE 15 satellite): a short loop-shaped pass per
        # worker slot — strided route, both shard programs, the fan-out
        # super-frame path, the async-add/wait pipeline AND one
        # profiled step each — so point 1's first-compile +
        # first-dispatch cost stops polluting T_1 (a depressed T_1
        # inflated every E_n of the curve)
        for w in range(workers):
            t = tables[w % n]
            ids = (np.arange(batch) * (rows // batch) + w) % rows
            mids = []
            for k in range(4):
                mids.append(t.add_rows_async(ids, vals))
                t.get_rows(ids)
            with prof.step(f"scale.np{n}"):
                with prof.phase("push"):
                    mids.append(t.add_rows_async(ids, vals))
                with prof.phase("ps_wait"):
                    t.get_rows(ids)
            for m in mids:
                t.wait(m)

        # SPMD-apply parity stage (ISSUE 15 acceptance): a
        # device-backed (adagrad) parity table across ALL n shards —
        # grouped into ONE mesh-stacked plane by ps_spmd_stack — driven
        # with a deterministic op sequence through the fan-out
        # super-frame path, asserted BIT-IDENTICAL to a 1-shard oracle
        # in a separate world. The first add (the stacked program's
        # compile) runs inside a hygiene capture scope keyed to the
        # plane's mesh shape.
        parity_ok = _parity_stage(n, dim, devstats)

        stop = time.monotonic() + seconds
        counts = [0] * workers

        def worker(w):
            # constant offered load: M workers at EVERY point (module
            # docstring) — each drives a table view round-robin and a
            # strided id batch spanning every shard
            t = tables[w % n]
            ids = (np.arange(batch) * (rows // batch) + w) % rows
            mids = []
            while time.monotonic() < stop:
                with prof.step(f"scale.np{n}"):
                    with prof.phase("prepare"):
                        v = vals * (1.0 + 1e-4 * counts[w])
                    with prof.phase("push"):
                        mids.append(t.add_rows_async(ids, v))
                        if len(mids) >= 4:
                            with prof.phase("ps_wait"):
                                t.wait(mids.pop(0))
                    with prof.phase("ps_wait"):
                        t.get_rows(ids)
                counts[w] += 2
            for m in mids:
                t.wait(m)

        t0 = time.monotonic()
        threads = [threading.Thread(target=worker, args=(w,),
                                    name=f"scale-w{w}")
                   for w in range(workers)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        dt = time.monotonic() - t0

        # model-average collective cost at this shard count, measured
        # QUIESCED (workers joined, PS plane idle — see module
        # docstring; on real chips the phases overlap, here the
        # instrument separates them and attributes each honestly)
        coll_iters = 16
        c0 = time.monotonic()
        for _ in range(coll_iters):
            collectives.all_reduce(delta, mesh=mesh).block_until_ready()
        coll_ms = (time.monotonic() - c0) * 1e3 / coll_iters

        agg = aggregator.global_aggregator()
        skew = None
        straggler = None
        if agg is not None:
            rec = agg.poll_once()
            tbl = rec.get("tables", {}).get("scale") or {}
            skew = tbl.get("skew")
            # per-point straggler attribution (telemetry/slo.py): the
            # slowest rank at this shard count, named with its dominant
            # component (compute/wire/stall) — the scale curve's E_n
            # drop gets a who, not just a how-much
            from multiverso_tpu.telemetry import slo as _slo
            straggler = _slo.straggler(rec)
        summary = prof.summary()
        snap = devstats.stats_snapshot() or {}
        compiles = (snap.get("compiles_by_mesh") or {}).get(
            devstats.mesh_label(mesh)) or {}
        point = {
            "n": n,
            "rows_per_s": round(sum(counts) * batch / dt),
            "ops": sum(counts),
            "workers": workers,
            "batch_rows": batch,
            "skew": skew,
            "straggler": straggler,
            "stall_fraction": summary.get("stall_fraction"),
            "steps": summary.get("steps"),
            # zero steady-state recompiles is an ACCEPTANCE gate: the
            # warmed-up measured loop (and the stacked SPMD programs)
            # must never retrace past the warmup pass
            "steady_recompiles": summary.get("steady_recompiles", 0),
            # bit-parity of the mesh data plane (fan-out super-frames +
            # stacked SPMD apply/gather) vs the 1-shard classic oracle,
            # asserted in-run by the parent
            "parity_bit_for_bit": parity_ok,
            "all_reduce_ms": round(coll_ms, 3),
            "all_reduce_bytes": int(delta.nbytes),
            "compiles": compiles.get("compiles"),
            "compile_s": compiles.get("compile_s"),
            "devices": snap,
            "hygiene": devstats.hygiene_report(),
        }
        for c in ctxs:
            c.close()
    return point


def _merge_devices(points):
    """Sum the per-point devstats snapshots into one RESULT-level view
    (each point ran in its own process, so plain summation is exact)."""
    transfers = {}
    colls = {}
    compiles = {}
    for p in points:
        snap = p.get("devices") or {}
        for d, g in (snap.get("transfers") or {}).items():
            t = transfers.setdefault(d, {"ops": 0, "bytes": 0})
            t["ops"] += g.get("ops", 0)
            t["bytes"] += g.get("bytes", 0)
        for op, c in (snap.get("collectives") or {}).items():
            t = colls.setdefault(op, {"calls": 0, "bytes": 0})
            t["calls"] += c.get("calls", 0)
            t["bytes"] += c.get("bytes", 0)
        for label, c in (snap.get("compiles_by_mesh") or {}).items():
            t = compiles.setdefault(label,
                                    {"compiles": 0, "compile_s": 0.0})
            t["compiles"] += c.get("compiles", 0)
            t["compile_s"] = round(t["compile_s"]
                                   + c.get("compile_s", 0.0), 3)
    return transfers, colls, compiles


def main():
    if sys.argv[1:2] == ["--point"]:
        n, seconds, rows, dim = (int(sys.argv[2]), float(sys.argv[3]),
                                 int(sys.argv[4]), int(sys.argv[5]))
        print("POINT " + json.dumps(run_point(n, seconds, rows, dim)),
              flush=True)
        return

    seconds = float(sys.argv[1]) if len(sys.argv) > 1 else 3.0
    shards = (tuple(int(s) for s in sys.argv[2].split(","))
              if len(sys.argv) > 2 else DEFAULT_SHARDS)
    rows = int(sys.argv[3]) if len(sys.argv) > 3 else DEFAULT_ROWS
    dim = int(sys.argv[4]) if len(sys.argv) > 4 else DEFAULT_DIM

    points = []
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    for n in shards:
        print(f"bench_scale: shard point n={n}", file=sys.stderr,
              flush=True)
        # per-point budget well above the measured ~60-90 s/point; the
        # parent's caller (bench.bench_scale_curve) budgets MORE than
        # the sum of these, so a wedged point dies HERE with its
        # structured "scale point n=N" error, never as a generic
        # whole-worker timeout that hides which shard count hung
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--point",
             str(n), str(seconds), str(rows), str(dim)],
            capture_output=True, text=True, timeout=120 + 30 * n,
            env=env, cwd=_REPO)
        if out.returncode != 0:
            raise RuntimeError(
                f"scale point n={n} rc={out.returncode}: "
                f"{out.stderr[-400:]}")
        point = None
        for line in out.stdout.splitlines():
            if line.startswith("POINT "):
                point = json.loads(line[len("POINT "):])
        if point is None:
            raise RuntimeError(f"scale point n={n} produced no POINT "
                               f"line: {out.stderr[-400:]}")
        points.append(point)

    # the gate: a dirty compile is a FAILED run, and so is a point that
    # never entered a capture scope (an unchecked shape is not clean,
    # it is unmeasured — the MSG_SNAPSHOT lesson)
    findings = []
    checked = []
    for p in points:
        rep = p.get("hygiene") or {}
        if not rep.get("checked"):
            raise AssertionError(
                f"compile-hygiene gate: shard point n={p['n']} never "
                "entered a capture_hygiene scope — the report cannot "
                "vouch for it")
        checked.extend(rep["checked"])
        findings.extend(rep.get("findings") or [])
        # ISSUE 15 acceptance gates, per point: the mesh data plane's
        # bit-parity vs the 1-shard oracle, and zero steady-state
        # recompiles on the warmed measured loop
        if not p.get("parity_bit_for_bit"):
            raise AssertionError(
                f"parity gate: shard point n={p['n']} diverged from "
                "the 1-shard oracle (fan-out / SPMD apply broke "
                "bit-parity)")
        if p.get("steady_recompiles"):
            raise AssertionError(
                f"recompile gate: shard point n={p['n']} recompiled "
                f"{p['steady_recompiles']}x in steady state")
    if findings:
        raise AssertionError(
            "compile-hygiene gate: SPMD findings on the shipped "
            f"workload: {findings[:4]}")

    curve = {p["n"]: {k: v for k, v in p.items()
                      if k not in ("devices", "hygiene", "n")}
             for p in points}
    eff = efficiency_curve({n: c["rows_per_s"]
                            for n, c in curve.items()})
    transfers, colls, compiles = _merge_devices(points)

    # machine-readable report for tools/mvprof.py --report (beside the
    # profiler/trace files when a metrics dir is configured)
    from multiverso_tpu.utils import config
    mdir = config.get_flag("metrics_dir")
    if mdir:
        report = {"clean": not findings, "checked": checked,
                  "findings": findings, "rank": 0}
        os.makedirs(mdir, exist_ok=True)
        path = os.path.join(mdir, "compile-hygiene-rank0.json")
        with open(path + ".tmp", "w") as f:
            json.dump(report, f, indent=1)
        os.replace(path + ".tmp", path)

    print("RESULT " + json.dumps({
        "shards": list(shards),
        "seconds_per_point": seconds,
        "batch_rows": BATCH_ROWS, "dim": dim,
        "workers": worker_count(),
        "curve": {str(n): c for n, c in curve.items()},
        "efficiency": {str(n): e for n, e in
                       eff["efficiency"].items()},
        "efficiency_min": eff["efficiency_min"],
        # per-shard-count efficiency as first-class scalars, so the
        # BENCH_HISTORY headline (and run_bench's higher-is-better
        # flags) track each point of the curve, not just its min
        "e2": eff["efficiency"].get(2),
        "e4": eff["efficiency"].get(4),
        "e8": eff["efficiency"].get(8),
        "t1_rows_per_s": (curve.get(1) or {}).get("rows_per_s"),
        "parity_bit_for_bit": all(p.get("parity_bit_for_bit")
                                  for p in points),
        "steady_recompiles": sum(int(p.get("steady_recompiles") or 0)
                                 for p in points),
        "fanout": True, "spmd_stack": True,
        "hygiene_clean": not findings,
        "hygiene_checked": len(checked),
        "transfers": transfers,
        "collectives": colls,
        "compiles_by_mesh": compiles,
    }), flush=True)


if __name__ == "__main__":
    main()
