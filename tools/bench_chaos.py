#!/usr/bin/env python
"""Chaos bench: SIGKILL a server shard under sustained windowed traffic
and measure recovery-time-to-full-throughput + exactly-once parity.

The PR-4 2-OS-process fault test, promoted to a first-class bench
(ROADMAP open item 5; docs/FAILOVER.md). Topology:

* rank 0 — server shard + the traffic plane: N client threads issue
  blocking windowed 1-row adds (integer deltas, so float sums are
  order-independent and EXACT) round-robin over their own disjoint row
  sets, half the threads per shard, stamping each completion; periodic
  gets ride along. Runs its own heartbeat and feeds PS-plane deaths
  into the tombstone view (``elastic.bind_ps``).
* rank 1 — server shard only: heartbeat + flag-gated per-shard
  checkpointer (``failover_dir`` / ``failover_ckpt_interval_s``). This
  is the victim.
* parent (this script) — runs the :class:`FailoverSupervisor` with
  spawn/kill callbacks over the worker argv, SIGKILLs rank 1 mid-run,
  and shapes the result: ``recovery_s`` (kill → sustained ≥90% of the
  pre-fault completion rate), ``ops_lost`` / ``ops_double_applied``
  (final table vs the exact acked-op oracle — a fault-free run of the
  same acked ops produces exactly this state, so equality IS the
  bit-for-bit oracle check), replay/dup counters, and the supervisor's
  detect→rejoin spans.

    python tools/bench_chaos.py [seconds] [rows] [dim] [threads]

Prints ``RESULT <json>`` (the bench.py worker contract); exits nonzero
on lost or double-applied ops — a chaos bench that silently drops
acked writes must fail loudly, not record a latency number.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

BUCKET_S = 0.25
TABLE = "chaos"


# ---------------------------------------------------------------------- #
# worker body (both ranks): python tools/bench_chaos.py worker \
#     <rdv> <hb> <ck> <world> <rank> <rows> <dim> <threads>
# ---------------------------------------------------------------------- #
def worker(argv) -> None:
    rdv_dir, hb_dir, ck_dir = argv[0], argv[1], argv[2]
    world, rank = int(argv[3]), int(argv[4])
    rows, dim, n_threads = int(argv[5]), int(argv[6]), int(argv[7])
    import jax
    jax.config.update("jax_platforms", "cpu")

    from multiverso_tpu import elastic
    from multiverso_tpu.ps import failover
    from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                           PSService)
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    from multiverso_tpu.utils import config
    from multiverso_tpu.utils.dashboard import Dashboard

    restarted = os.environ.get("MV_RESTARTED") == "1"
    config.set_flag("ps_timeout", 60.0)
    config.set_flag("ps_connect_timeout", 5.0)
    config.set_flag("ps_reconnect_backoff", 0.3)
    config.set_flag("ps_replay", True)
    config.set_flag("ps_replay_backoff", 0.2)
    config.set_flag("ps_generation",
                    int(os.environ.get("MV_PS_GENERATION", "0")))
    config.set_flag("failover_dir", ck_dir)
    # a RESTARTED rank must restore BEFORE its first periodic save —
    # an empty-shard save racing the restore would become the newest
    # committed tag; the checkpointer starts manually after rejoin
    config.set_flag("failover_ckpt_interval_s",
                    0.0 if restarted else 0.5)
    # restarted ranks defer the rendezvous publish: the restore must
    # complete before any survivor can discover the fresh address
    svc = PSService(rank, world, FileRendezvous(rdv_dir),
                    defer_publish=restarted)
    ctx = PSContext(rank, world, svc)
    hb = elastic.Heartbeat(hb_dir, interval=0.2, rank=rank,
                           addr=svc.addr)
    elastic.bind_ps(hb_dir, ctx)
    t = AsyncMatrixTable(rows, dim, name=TABLE, send_window_ms=1.0,
                         ctx=ctx)
    if restarted:
        failover.rejoin(ck_dir, rank, [t], heartbeat=hb, service=svc)
        config.set_flag("failover_ckpt_interval_s", 0.5)
        failover.ensure_checkpointer(svc)
    hb.start()

    if rank != 0:
        # server only: hold the shard up until the driver is done
        done = os.path.join(rdv_dir, "done")
        while not os.path.exists(done):
            time.sleep(0.05)
        hb.stop()
        ctx.close()
        print("RESULT " + json.dumps(
            {"rank": rank, "restarted": restarted,
             "gen": svc.generation}), flush=True)
        return

    # ------------------------- traffic plane -------------------------- #
    half = rows // world
    stop = threading.Event()
    per_thread_counts = [np.zeros(rows, np.int64)
                         for _ in range(n_threads)]
    per_thread_stamps = [[] for _ in range(n_threads)]
    errs = [0] * n_threads

    def run_traffic(j: int) -> None:
        # even threads hammer shard 0's rows, odd threads shard 1's —
        # disjoint per-thread row sets, so the oracle is exact
        base = 0 if j % 2 == 0 else half
        mine = [base + (j // 2) + k * (n_threads // 2 + 1)
                for k in range(3)]
        mine = [r for r in mine if base <= r < base + half]
        ones = np.ones((1, dim), np.float32)
        counts, stamps = per_thread_counts[j], per_thread_stamps[j]
        i = 0
        while not stop.is_set():
            row = mine[i % len(mine)]
            try:
                t.add_rows([row], ones)   # blocking = acked
            except Exception:   # noqa: BLE001 — replay window exhausted
                errs[j] += 1
                time.sleep(0.05)
                continue
            counts[row] += 1
            stamps.append(time.time())
            if i % 32 == 31:
                try:
                    t.get_rows([mine[0]])
                except Exception:   # noqa: BLE001 — owner mid-failover
                    pass
            i += 1

    threads = [threading.Thread(target=run_traffic, args=(j,),
                                daemon=True) for j in range(n_threads)]
    t0 = time.time()
    for th in threads:
        th.start()
    open(os.path.join(rdv_dir, "traffic_started"), "w").close()
    stop_marker = os.path.join(rdv_dir, "stop_traffic")
    while not os.path.exists(stop_marker):
        time.sleep(0.05)
    stop.set()
    for th in threads:
        th.join(timeout=90)
    # drain every retained/replayed frame before the parity read
    t.flush()
    final = t.get_rows(np.arange(rows))
    acked = np.zeros(rows, np.int64)
    for c in per_thread_counts:
        acked += c
    oracle = np.repeat(acked[:, None], dim, axis=1).astype(np.float32)
    per_row = final[:, 0].astype(np.int64)
    lost = int(np.maximum(acked - per_row, 0).sum())
    double = int(np.maximum(per_row - acked, 0).sum())
    parity = bool(np.array_equal(final, oracle))
    # bucketized completion-rate series for the parent's recovery math
    stamps = np.sort(np.concatenate(
        [np.asarray(s) for s in per_thread_stamps if s] or
        [np.zeros(0)]))
    t_end = time.time()
    nb = max(int((t_end - t0) / BUCKET_S) + 1, 1)
    buckets = np.bincount(((stamps - t0) / BUCKET_S).astype(np.int64),
                          minlength=nb)
    # replay-plane counters + the restored victim's dedupe stats
    rep = {k: Dashboard.get(f"table[{TABLE}].replay.{k}").count
           for k in ("frames", "dups", "dropped")}
    victim_stats = {}
    try:
        victim_stats = t.server_stats(1)["shards"][TABLE]
        victim_stats = {k: victim_stats.get(k) for k in
                        ("dup_frames", "replay_clients", "adds",
                         "applies", "version")}
    except Exception as e:   # noqa: BLE001 — stats are best-effort
        victim_stats = {"error": f"{type(e).__name__}: {e}"[:120]}
    out = {
        "rank": 0, "t0": t0, "bucket_s": BUCKET_S,
        "buckets": buckets.tolist(),
        "acked_ops": int(acked.sum()), "ops_lost": lost,
        "ops_double_applied": double,
        "parity_bit_for_bit": parity,
        "add_errors": int(sum(errs)),
        "replay": rep, "victim_shard": victim_stats,
    }
    open(os.path.join(rdv_dir, "done"), "w").close()
    hb.stop()
    ctx.close()
    print("RESULT " + json.dumps(out), flush=True)


# ---------------------------------------------------------------------- #
# parent: orchestrate, SIGKILL, supervise, shape the record
# ---------------------------------------------------------------------- #
def _spawn_worker(rdv, hb, ck, world, rank, rows, dim, threads,
                  gen: int = 0, restarted: bool = False):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MV_PS_GENERATION"] = str(gen)
    if restarted:
        env["MV_RESTARTED"] = "1"
    else:
        env.pop("MV_RESTARTED", None)
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "worker", rdv, hb,
         ck, str(world), str(rank), str(rows), str(dim), str(threads)],
        stdout=subprocess.PIPE, text=True, env=env)


def _recovery_from_buckets(res: dict, kill_wall: float):
    """(pre_rate, post_rate, recovery_s) out of the driver's completion
    series: pre = mean rate over the 3 s before the kill; recovery =
    first second-long window after the kill sustaining >= 90% of it."""
    t0, bs = res["t0"], res["bucket_s"]
    buckets = np.asarray(res["buckets"], np.float64) / bs
    kb = int((kill_wall - t0) / bs)
    pre_lo = max(kb - int(3.0 / bs), 1)   # skip the warmup bucket 0
    pre = float(np.mean(buckets[pre_lo:kb])) if kb > pre_lo else 0.0
    post = float(np.mean(buckets[-max(int(2.0 / bs), 1):]))
    win = max(int(1.0 / bs), 1)
    recovery_s = None
    for i in range(max(kb, 0), len(buckets) - win + 1):
        # rolling-window MEAN: "sustained throughput ≥ 90%" is a rate
        # statement — requiring every 0.25 s bucket individually over
        # the bar would gate on scheduler noise, not recovery
        if np.mean(buckets[i:i + win]) >= 0.9 * pre:
            recovery_s = round((t0 + i * bs) - kill_wall, 3)
            break
    return pre, post, recovery_s


def main(argv) -> int:
    seconds = float(argv[0]) if argv else 18.0
    rows = int(argv[1]) if len(argv) > 1 else 64
    dim = int(argv[2]) if len(argv) > 2 else 8
    threads = int(argv[3]) if len(argv) > 3 else 4
    import tempfile

    from multiverso_tpu.ps import failover

    tmp = tempfile.mkdtemp(prefix="mv_chaos_")
    rdv = os.path.join(tmp, "rdv")
    hb = os.path.join(tmp, "hb")
    ck = os.path.join(tmp, "ck")
    os.makedirs(rdv)
    world = 2
    procs = {}
    procs[1] = _spawn_worker(rdv, hb, ck, world, 1, rows, dim, threads)
    procs[0] = _spawn_worker(rdv, hb, ck, world, 0, rows, dim, threads)

    def kill_rank(rank: int) -> None:
        p = procs.get(rank)
        if p is not None and p.poll() is None:
            p.kill()

    def spawn_rank(rank: int, gen: int) -> None:
        procs[rank] = _spawn_worker(rdv, hb, ck, world, rank, rows, dim,
                                    threads, gen=gen, restarted=True)

    sup = failover.FailoverSupervisor(
        hb, world, rendezvous_dir=rdv, spawn=spawn_rank, kill=kill_rank,
        timeout=2.0, poll_s=0.2, ranks=[1])
    try:
        deadline = time.time() + 120
        started = os.path.join(rdv, "traffic_started")
        while not os.path.exists(started):
            if time.time() > deadline:
                raise RuntimeError("traffic never started")
            for p in procs.values():
                if p.poll() not in (None, 0):
                    raise RuntimeError("worker died during startup")
            time.sleep(0.05)
        sup.start()
        pre_s = min(max(seconds * 0.3, 3.0), 8.0)
        time.sleep(pre_s)
        # chaos: SIGKILL the victim server shard mid-traffic
        kill_wall = time.time()
        kill_rank(1)
        # recovery time varies run to run (the respawn is dominated by
        # a JAX import: 2-8 s under load) — anchor the end of the run
        # to the OBSERVED rejoin, so the sustained-90% detector always
        # gets several seconds of post-recovery traffic to look at
        rejoin_deadline = time.time() + 60
        while not any(p == "rejoin" for _, p, _ in sup.events):
            if time.time() > rejoin_deadline:
                break
            time.sleep(0.2)
        time.sleep(max(seconds - pre_s - (time.time() - kill_wall),
                       6.0))
        open(os.path.join(rdv, "stop_traffic"), "w").close()
        out0, _ = procs[0].communicate(timeout=180)
        if procs[0].returncode != 0:
            sys.stderr.write(out0[-2000:])
            raise RuntimeError(f"driver rc={procs[0].returncode}")
        res = None
        for line in out0.splitlines():
            if line.startswith("RESULT "):
                res = json.loads(line[len("RESULT "):])
        if res is None:
            raise RuntimeError("driver produced no RESULT line")
    finally:
        sup.stop()
        open(os.path.join(rdv, "done"), "w").close()
        for p in procs.values():
            if p.poll() is None:
                try:
                    p.communicate(timeout=20)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    pre, post, recovery_s = _recovery_from_buckets(res, kill_wall)
    result = {
        "recovery_s": recovery_s,
        "pre_fault_ops_per_s": round(pre, 1),
        "post_fault_ops_per_s": round(post, 1),
        "recovered_to_90pct": recovery_s is not None,
        "acked_ops": res["acked_ops"],
        "ops_lost": res["ops_lost"],
        "ops_double_applied": res["ops_double_applied"],
        "parity_bit_for_bit": res["parity_bit_for_bit"],
        "add_errors": res["add_errors"],
        "replay": res["replay"],
        "victim_shard": res["victim_shard"],
        "supervisor": {
            "events": [{"ts": ts, "phase": ph, "rank": r}
                       for ts, ph, r in sup.events],
            "spans": sup.recovery_spans(),
        },
        "world": world, "rows": rows, "dim": dim, "threads": threads,
    }
    print("RESULT " + json.dumps(result), flush=True)
    # a chaos bench that lost or double-applied acked ops must FAIL —
    # the latency story is meaningless without the exactly-once one
    if res["ops_lost"] or res["ops_double_applied"] \
            or not res["parity_bit_for_bit"]:
        return 3
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "worker":
        worker(sys.argv[2:])
    else:
        raise SystemExit(main(sys.argv[1:]))
