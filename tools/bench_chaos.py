#!/usr/bin/env python
"""Chaos scenario matrix: prove the robustness planes against the
fault shapes they claim to survive (ISSUE 14; docs/FAILOVER.md "Chaos
scenarios").

PR 7's bench proved ONE fault (SIGKILL a shard). This matrix drives
the fault-injection wire plane (ps/faults.py) and the replica pool
(serving/pool.py) through six scenarios, each with its in-run gates:

* ``partition_heal`` — one-way client→shard partition for several
  seconds, then heal: every add issued before/during the cut lands
  exactly once after it (replay plane), and add throughput recovers
  to ≥90% of pre-fault within the recovery budget.
* ``dup_reorder`` — duplicate + bounded-reorder injection on the
  windowed add frames: the shard's sequence channels dedupe every
  duplicate and apply every frame exactly once (ledger vs the
  acked-op oracle, bit-for-bit), with injected counts asserted
  nonzero so a silently-disarmed plane cannot pass.
* ``slow_shard_shed`` — slow-serve injection on one shard while a
  ReplicaPool serves a read storm: served reads NEVER exceed the
  staleness bound (over-bound reads defer or refuse instead), and
  served QPS recovers after the heal.
* ``replica_kill`` — kill one pool member mid-storm: the pool demotes
  it, routes around, activates the warm spare, and served QPS
  recovers to ≥90%.
* ``noisy_neighbor`` — two tenants share one pool (ISSUE 18): a storm
  tenant drives far past its per-tenant infer budget while a victim
  runs modestly over its own. The per-tenant buckets (judged BEFORE
  the table-wide one) must cap the storm at its budget, keep admitting
  the victim, hold the victim's p99 within 2x its quiet-phase baseline
  and the staleness bound on every served read — and the tenant ledger
  must open EXACTLY ONE noisy-neighbor episode (flightrec and the
  MSG_STATS ``tenants`` block agree) and clear it after the storm.
* ``combined`` — the PR-7 OS-process SIGKILL of a server shard PLUS a
  replica kill at the same instant, under training writes and an
  inference storm: exactly-once ledger holds (ops_lost = 0,
  ops_double_applied = 0, parity bit-for-bit vs the acked oracle),
  no served read over bound, and served QPS recovers to ≥90% of
  steady — ``recovery_s`` recorded per scenario in
  ``extra.chaos.scenarios`` for run_bench trend tracking.

    python tools/bench_chaos.py [seconds] [rows] [dim] [threads]
    python tools/bench_chaos.py --scenario partition_heal   # one only

Prints ``RESULT <json>`` (the bench.py worker contract); exits nonzero
when any scenario's gate fails — a chaos bench that loses acked writes
or serves over-bound reads must fail loudly, not record a latency
number. All in-process scenarios run the python wire plane
(``ps_native`` off): the fault plane hooks the python peer/serve
boundaries by design.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

BUCKET_S = 0.25
TABLE = "chaos"


# ---------------------------------------------------------------------- #
# shared math
# ---------------------------------------------------------------------- #
def rate_buckets(stamps, t0: float, t_end: float):
    """Completion stamps -> per-BUCKET_S counts from t0 to t_end."""
    nb = max(int((t_end - t0) / BUCKET_S) + 1, 1)
    if not len(stamps):
        return np.zeros(nb, np.int64)
    s = np.sort(np.asarray(stamps, np.float64))
    return np.bincount(((s - t0) / BUCKET_S).astype(np.int64),
                       minlength=nb)[:nb]


def _recovery_core(rates, t0: float, bucket_s: float,
                   fault_wall: float, recover_from: float):
    """The ONE recovery detector every scenario uses (in-process and
    the OS-process combined alike — a tuning of the 90% bar or the
    floor must move them together): pre = mean rate over the 3 s
    before the fault (skipping warmup bucket 0); recovery_s = first
    1 s ROLLING-window mean at/after ``recover_from`` sustaining
    ≥90% of pre ("sustained throughput" is a rate statement — gating
    each 0.25 s bucket individually would measure scheduler noise),
    measured from ``recover_from`` and floored at 0 (a rate that
    never dropped below the bar — the bound covered the outage — is
    an instant recovery, not a negative one)."""
    rates = np.asarray(rates, np.float64)
    kb = int((fault_wall - t0) / bucket_s)
    rb = max(int((recover_from - t0) / bucket_s), 0)
    pre_lo = max(kb - int(3.0 / bucket_s), 1)
    pre = float(np.mean(rates[pre_lo:kb])) if kb > pre_lo else 0.0
    post = float(np.mean(rates[-max(int(2.0 / bucket_s), 1):]))
    win = max(int(1.0 / bucket_s), 1)
    if pre <= 0.0:
        # no pre-fault rate ⇒ nothing to recover TO: `mean >= 0.9*0`
        # would pass on the first window and a completely dead plane
        # would read as an instant recovery — the exact outcome the
        # gates exist to catch. None fails the recovery gate loudly.
        return pre, post, None
    recovery_s = None
    for i in range(rb, len(rates) - win + 1):
        if np.mean(rates[i:i + win]) >= 0.9 * pre:
            recovery_s = round(
                max((t0 + i * bucket_s) - recover_from, 0.0), 3)
            break
    return pre, post, recovery_s


def recovery_from_stamps(stamps, t0: float, t_end: float,
                         fault_wall: float,
                         recover_from: float | None = None):
    """Completion stamps → (pre_rate, post_rate, recovery_s). For
    heal-style scenarios recovery counts from the HEAL
    (``recover_from``), for kill-style from the kill (default)."""
    rates = rate_buckets(stamps, t0, t_end) / BUCKET_S
    return _recovery_core(rates, t0, BUCKET_S, fault_wall,
                          fault_wall if recover_from is None
                          else recover_from)


# ---------------------------------------------------------------------- #
# SLO sentinel plumbing (ISSUE 19, telemetry/slo.py): scenarios arm a
# scenario-scoped spec and drive an EXPLICIT in-process aggregator —
# nothing else in this process polls, so the judge schedule (and hence
# the episode lifecycle) is deterministic, the noisy-neighbor sweep
# discipline applied to burn rates.
# ---------------------------------------------------------------------- #
AVAIL_OBJECTIVE = {
    "name": "chaos_availability", "kind": "availability",
    "table": TABLE, "target": 0.9, "min": 1.0}


def _arm_sentinel(w: "World", objectives,
                  fast_window_s: float = 3.0):
    """Reset sentinel + bus, arm the scenario spec, and return
    (aggregator, metrics_dir). fast_burn=1.0 (one bad poll in the fast
    window pages — the scenario owns the schedule, noise guards live
    in the quiet-scenario gates), slow_burn low so the 60 s slow
    window confirms rather than delays. Pair with
    :func:`_disarm_sentinel` in the scenario's finally — the matrix
    (and the tier-1 smokes) share one process."""
    from multiverso_tpu.telemetry import aggregator
    from multiverso_tpu.telemetry import signals as sgn
    from multiverso_tpu.telemetry import slo as slo_mod
    from multiverso_tpu.utils import config
    slo_mod.reset()
    sgn.reset()
    # probes must stay snappy while a partition wedges the data plane
    # (one poll is bounded by ~2 health timeouts)
    config.set_flag("ps_health_timeout", 1.0)
    slo_mod.arm({"fast_window_s": fast_window_s, "slow_window_s": 60.0,
                 "fast_burn": 1.0, "slow_burn": 0.1,
                 "objectives": list(objectives)})
    mdir = os.path.join(w.tmp, "metrics")
    agg = aggregator.ClusterAggregator(w.ctx0.service, directory=mdir)
    return agg, mdir


def _disarm_sentinel() -> None:
    """Scenario-exit cleanup: a still-armed process-global sentinel
    would judge (and tag ``slo`` blocks onto) every later poll in this
    process — the matrix's other scenarios and the pytest smokes."""
    from multiverso_tpu.telemetry import signals as sgn
    from multiverso_tpu.telemetry import slo as slo_mod
    slo_mod.reset()
    sgn.reset()


def _sleep_poll(agg, seconds: float, cadence: float = 0.5,
                seqs: dict = None) -> None:
    """Sleep ``seconds`` while polling every ``cadence`` — each poll is
    one sentinel judgment. ``seqs``: scan the flightrec ring for SLO
    events right after EVERY poll — post-heal traffic wraps the ring in
    well under a phase, so an end-of-phase scan arrives after eviction
    (measured: the slo.cleared slot was gone ~0.3 s later)."""
    end = time.monotonic() + float(seconds)
    while True:
        left = end - time.monotonic()
        if left <= 0:
            return
        time.sleep(min(cadence, left))
        try:
            agg.poll_once()
        except Exception:   # noqa: BLE001 — telemetry never kills chaos
            pass
        if seqs is not None:
            _scan_slo_events(seqs)


def _scan_slo_events(seqs: dict) -> None:
    """Ring-scan dedup by seq (the verdict-scan discipline: the ring
    wraps many times in a matrix run, so scan at every poll via
    ``_sleep_poll(seqs=...)``, not once at the end)."""
    from multiverso_tpu.telemetry import flightrec as flight
    for s in flight.RECORDER.snapshot():
        if s[2] in (flight.EV_SLO_FIRED, flight.EV_SLO_CLEARED):
            seqs[s[0]] = {"ev": flight.EV_NAMES.get(s[2]),
                          "note": s[7]}


def _read_alerts(mdir: str) -> list:
    """alerts.jsonl lines (telemetry/slo.py episode log) as dicts."""
    out = []
    try:
        with open(os.path.join(mdir, "alerts.jsonl")) as f:
            for ln in f:
                ln = ln.strip()
                if ln:
                    out.append(json.loads(ln))
    except (OSError, ValueError):
        pass
    return out


def _slo_block(agg) -> dict:
    """The scenario RESULT's ``slo`` summary: per-objective episode
    counts (what run_bench compares run-over-run) + eval count."""
    snap = ((agg.last() or {}).get("slo")) or {}
    return {
        "episodes": {name: int(o.get("episodes") or 0)
                     for name, o in (snap.get("objectives")
                                     or {}).items()},
        "evals": snap.get("evals", 0),
        "firing": list(snap.get("firing") or []),
    }


# ---------------------------------------------------------------------- #
# in-process world: 2 ranks, python wire plane, replay armed
# ---------------------------------------------------------------------- #
class World:
    """2 in-process PSServices + one replay-armed windowed table; the
    unit the four in-process scenarios run against. Rows split across
    both shards; rank 0 hosts the client plane (its shard-0 traffic is
    the local short-circuit, shard-1 traffic rides the real socket —
    where the fault plane hooks)."""

    def __init__(self, tmp: str, rows: int = 32, dim: int = 8,
                 staleness_s: float = 2.0):
        import tempfile

        from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                               PSService)
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        from multiverso_tpu.utils import config
        config.set_flag("ps_native", False)
        config.set_flag("ps_replay", True)
        config.set_flag("ps_timeout", 60.0)
        config.set_flag("ps_connect_timeout", 5.0)
        config.set_flag("ps_reconnect_backoff", 0.2)
        config.set_flag("ps_replay_backoff", 0.1)
        config.set_flag("ps_replay_backoff_cap", 0.5)
        self.rows, self.dim = rows, dim
        self.staleness_s = staleness_s
        self.tmp = tmp or tempfile.mkdtemp(prefix="mv_chaos_")
        # the failover checkpointer advances the shards' durable replay
        # floor — without it the clients' retained-frame tails grow for
        # the whole run and per-ack pruning decays throughput (exactly
        # the hoard the PR-10 ledger flags)
        config.set_flag("failover_dir", os.path.join(self.tmp, "ck"))
        config.set_flag("failover_ckpt_interval_s", 0.5)
        rdv = FileRendezvous(os.path.join(self.tmp, "rdv"))
        self.ctx0 = PSContext(0, 2, PSService(0, 2, rdv))
        self.ctx1 = PSContext(1, 2, PSService(1, 2, rdv))
        self.t0 = AsyncMatrixTable(rows, dim, name=TABLE,
                                   send_window_ms=1.0, ctx=self.ctx0)
        self.t1 = AsyncMatrixTable(rows, dim, name=TABLE,
                                   send_window_ms=1.0, ctx=self.ctx1)
        self.pool = None

    def make_pool(self, replicas=2, spares=0, refresh_s=0.15,
                  admission=None):
        from multiverso_tpu.serving.pool import ReplicaPool
        self.pool = ReplicaPool(
            self.t0, replicas=replicas, spares=spares,
            refresh_s=refresh_s, staleness_s=self.staleness_s,
            admission=admission, probe_s=0.2, start=True)
        return self.pool

    def close(self):
        from multiverso_tpu.ps import faults
        faults.disarm()
        if self.pool is not None:
            self.pool.close()
        self.ctx0.close()
        self.ctx1.close()


class Traffic:
    """N blocking-windowed-add threads over disjoint rows spanning both
    shards, stamping each acked completion — the exactly-once oracle's
    acked side AND the recovery detector's completion series."""

    def __init__(self, world: World, n_threads: int = 3):
        self.w = world
        self.n = n_threads
        self.counts = [np.zeros(world.rows, np.int64)
                       for _ in range(n_threads)]
        self.stamps = [[] for _ in range(n_threads)]
        self.errors = [0] * n_threads
        self._stop = threading.Event()
        self._threads = []
        half = world.rows // 2

        def run(j):
            # thread j's disjoint rows: one on each shard
            mine = [j % half, half + (j % half)]
            ones = np.ones((1, world.dim), np.float32)
            i = 0
            while not self._stop.is_set():
                row = mine[i % len(mine)]
                try:
                    self.w.t0.add_rows([row], ones)   # blocking = acked
                except Exception:   # noqa: BLE001 — replay exhausted
                    self.errors[j] += 1
                    time.sleep(0.05)
                    continue
                self.counts[j][row] += 1
                self.stamps[j].append(time.time())
                i += 1

        self._threads = [threading.Thread(target=run, args=(j,),
                                          daemon=True)
                         for j in range(n_threads)]

    def start(self):
        self.t_start = time.time()
        for t in self._threads:
            t.start()
        return self

    def stop(self, timeout: float = 90.0):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=timeout)
        self.t_end = time.time()

    def ledger(self):
        """Drain the window, read the final table, settle the
        exactly-once ledger vs the acked oracle."""
        self.w.t0.flush()
        final = self.w.t0.get_rows(np.arange(self.w.rows))
        acked = np.zeros(self.w.rows, np.int64)
        for c in self.counts:
            acked += c
        oracle = np.repeat(acked[:, None], self.w.dim,
                           axis=1).astype(np.float32)
        per_row = final[:, 0].astype(np.int64)
        return {
            "acked_ops": int(acked.sum()),
            "ops_lost": int(np.maximum(acked - per_row, 0).sum()),
            "ops_double_applied": int(
                np.maximum(per_row - acked, 0).sum()),
            "parity_bit_for_bit": bool(np.array_equal(final, oracle)),
            "add_errors": int(sum(self.errors)),
        }

    def all_stamps(self):
        return np.concatenate(
            [np.asarray(s) for s in self.stamps if s]
            or [np.zeros(0)])


class InferStorm:
    """M reader threads against the pool: zipf-ish hot-set reads with
    ``with_age=True`` — every SERVED read's age is evidence for the
    staleness gate, every refusal (shed / over-bound / outage) counts
    but never violates it."""

    def __init__(self, pool, rows: int, n_threads: int = 2,
                 pace_s: float = 0.002):
        self.pool = pool
        self._stop = threading.Event()
        self.stamps = [[] for _ in range(n_threads)]
        self.max_age = [0.0] * n_threads
        self.over_bound = [0] * n_threads
        self.refused = [0] * n_threads
        self.shed = [0] * n_threads
        hot = np.arange(min(8, rows))

        def run(j):
            from multiverso_tpu.serving.admission import SheddingError
            rng = np.random.default_rng(j)
            while not self._stop.is_set():
                ids = (hot[rng.integers(0, len(hot), 3)]
                       if rng.random() < 0.8
                       else rng.integers(0, rows, 3))
                try:
                    _rows, age = self.pool.get_rows(
                        np.unique(ids), with_age=True)
                except SheddingError:
                    self.shed[j] += 1
                    time.sleep(0.005)
                    continue
                except Exception:   # noqa: BLE001 — outage / over
                    self.refused[j] += 1     # bound: refused, not stale
                    time.sleep(0.02)
                    continue
                self.max_age[j] = max(self.max_age[j], age)
                if age > self.pool.staleness_s + 1e-9:
                    self.over_bound[j] += 1
                self.stamps[j].append(time.time())
                if pace_s:
                    time.sleep(pace_s)

        self._threads = [threading.Thread(target=run, args=(j,),
                                          daemon=True)
                         for j in range(n_threads)]

    def start(self):
        for t in self._threads:
            t.start()
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)

    def report(self):
        return {
            "served": int(sum(len(s) for s in self.stamps)),
            "refused": int(sum(self.refused)),
            "shed": int(sum(self.shed)),
            "max_served_age_s": round(max(self.max_age), 3),
            "over_bound_serves": int(sum(self.over_bound)),
        }

    def all_stamps(self):
        return np.concatenate(
            [np.asarray(s) for s in self.stamps if s]
            or [np.zeros(0)])


class TenantReader:
    """One tenant's paced read loop against the pool (the noisy-neighbor
    scenario's unit): every admitted read's wall latency and served age
    is per-tenant evidence for the p99/staleness gates, every shed is
    the per-tenant budget doing its job. ``pace_s`` bounds the ATTEMPT
    rate (sheds sleep it too) so over-budget pressure is deliberate,
    not a spin loop."""

    def __init__(self, pool, rows: int, tenant: str,
                 pace_s: float = 0.0, n_threads: int = 1):
        self.pool = pool
        self.tenant = tenant
        self._stop = threading.Event()
        self.lat = [[] for _ in range(n_threads)]   # (wall_ts, ms)
        self.shed = [0] * n_threads
        self.refused = [0] * n_threads
        self.over_bound = [0] * n_threads
        self.max_age = [0.0] * n_threads
        hot = np.arange(min(8, rows))

        def run(j):
            from multiverso_tpu.serving.admission import SheddingError
            rng = np.random.default_rng(97 + j)
            while not self._stop.is_set():
                ids = np.unique(hot[rng.integers(0, len(hot), 3)])
                t0 = time.perf_counter()
                try:
                    _rows, age = self.pool.get_rows(
                        ids, with_age=True, tenant=self.tenant)
                except SheddingError:
                    self.shed[j] += 1
                    time.sleep(pace_s or 0.001)
                    continue
                except Exception:   # noqa: BLE001 — outage/over bound
                    self.refused[j] += 1
                    time.sleep(0.02)
                    continue
                ms = (time.perf_counter() - t0) * 1e3
                self.max_age[j] = max(self.max_age[j], age)
                if age > self.pool.staleness_s + 1e-9:
                    self.over_bound[j] += 1
                self.lat[j].append((time.time(), ms))
                if pace_s:
                    time.sleep(pace_s)

        self._threads = [threading.Thread(target=run, args=(j,),
                                          daemon=True)
                         for j in range(n_threads)]

    def start(self):
        for t in self._threads:
            t.start()
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)

    def all_lat(self):
        return [p for l in self.lat for p in l]

    def report(self):
        return {
            "served": int(sum(len(l) for l in self.lat)),
            "shed": int(sum(self.shed)),
            "refused": int(sum(self.refused)),
            "max_served_age_s": round(max(self.max_age), 3),
            "over_bound_serves": int(sum(self.over_bound)),
        }


# ---------------------------------------------------------------------- #
# in-process scenarios
# ---------------------------------------------------------------------- #
def scenario_partition_heal(seconds: float = 10.0,
                            tmp: str = "") -> dict:
    """One-way 0→1 partition under windowed-add traffic, then heal.
    The SLO sentinel judges a chaos-table availability objective on
    every explicit poll: the cut stalls every add thread on its
    shard-1 row while replay retains their frames — zero windowed
    progress against provably pent demand — so the objective must
    FIRE during the cut and CLEAR after the heal, asserted on BOTH
    evidence surfaces (the flightrec ring and alerts.jsonl)."""
    from multiverso_tpu.ps import faults
    w = World(tmp, rows=32, dim=8)
    slo_seqs: dict = {}
    try:
        agg, mdir = _arm_sentinel(w, [AVAIL_OBJECTIVE])
        plane = faults.arm({"seed": 11, "rules": [
            {"kind": "partition", "src": 0, "dst": 1,
             "phase": "cut"}]}, rank=0)
        tr = Traffic(w, n_threads=3).start()
        pre_s = min(max(seconds * 0.3, 2.5), 4.0)
        # ≥2.2 s cut: the judge needs two in-cut polls (the first
        # cut-interval can still hold pre-cut acks)
        cut_s = max(min(max(seconds * 0.2, 1.5), 3.0), 2.2)
        _sleep_poll(agg, pre_s, seqs=slo_seqs)
        fault_wall = time.time()
        plane.set_phase("cut")
        _sleep_poll(agg, cut_s, seqs=slo_seqs)
        heal_wall = time.time()
        plane.set_phase(None)
        _sleep_poll(agg, max(seconds - pre_s - cut_s, 4.5),
                    seqs=slo_seqs)
        _scan_slo_events(slo_seqs)
        tr.stop()
        led = tr.ledger()
        pre, post, rec = recovery_from_stamps(
            tr.all_stamps(), tr.t_start, tr.t_end, fault_wall,
            recover_from=heal_wall)
        alerts = _read_alerts(mdir)
        a_fired = [a for a in alerts
                   if a.get("kind") == "slo.fired"
                   and a.get("objective") == "chaos_availability"]
        a_cleared = [a for a in alerts
                     if a.get("kind") == "slo.cleared"
                     and a.get("objective") == "chaos_availability"]
        ring_fired = sum(1 for e in slo_seqs.values()
                         if e["ev"] == "slo.fired")
        ring_cleared = sum(1 for e in slo_seqs.values()
                           if e["ev"] == "slo.cleared")
        return {
            "recovery_s": rec, "recovered_to_90pct": rec is not None,
            "pre_fault_ops_per_s": round(pre, 1),
            "post_fault_ops_per_s": round(post, 1),
            "partition_s": round(heal_wall - fault_wall, 2),
            "injected": plane.stats()["injected"],
            "slo": {**_slo_block(agg),
                    "alerts_fired": len(a_fired),
                    "alerts_cleared": len(a_cleared),
                    "ring_fired": ring_fired,
                    "ring_cleared": ring_cleared},
            **led,
            "gates": {
                "exactly_once": led["ops_lost"] == 0
                and led["ops_double_applied"] == 0
                and led["parity_bit_for_bit"],
                "recovery": rec is not None,
                "injected_nonzero":
                    plane.stats()["injected"].get("partition", 0) > 0,
                # the alert carries the judging poll's wall clock: the
                # fire must land inside the cut (small slack for the
                # poll that straddles the heal), the clear after it
                "slo_fired_during_cut": ring_fired > 0 and any(
                    fault_wall <= (a.get("ts") or 0)
                    <= heal_wall + 0.75 for a in a_fired),
                "slo_cleared_after_heal": ring_cleared > 0 and any(
                    (a.get("ts") or 0) >= heal_wall
                    for a in a_cleared),
            },
        }
    finally:
        _disarm_sentinel()
        w.close()


def scenario_dup_reorder(seconds: float = 8.0, tmp: str = "") -> dict:
    """Duplicate + bounded-reorder injection on the replay-stamped add
    frames: the shard's sequence channels must hold exactly-once. A
    QUIET scenario for the SLO sentinel: dups and reorders never stall
    progress, so the same availability objective that fires under a
    partition must log ZERO episodes here — the false-fire guard."""
    from multiverso_tpu.ps import faults
    w = World(tmp, rows=32, dim=8)
    try:
        agg, mdir = _arm_sentinel(w, [AVAIL_OBJECTIVE])
        plane = faults.arm({"seed": 7, "rules": [
            {"kind": "duplicate", "src": 0, "dst": 1, "p": 0.35,
             "msg_types": ["MSG_ADD_ROWS", "MSG_BATCH"]},
            {"kind": "reorder", "src": 0, "dst": 1, "p": 0.25,
             "depth": 2, "msg_types": ["MSG_ADD_ROWS", "MSG_BATCH"]},
        ]}, rank=0)
        tr = Traffic(w, n_threads=3).start()
        _sleep_poll(agg, max(seconds, 4.0))
        tr.stop()
        faults.disarm()   # the settle flush runs clean
        led = tr.ledger()
        slo_blk = _slo_block(agg)
        dup_frames = 0
        try:
            dup_frames = int(w.t0.server_stats(1)["shards"][TABLE]
                             .get("dup_frames") or 0)
        except Exception:   # noqa: BLE001 — stats are best-effort
            pass
        inj = plane.stats()["injected"]
        return {
            "recovery_s": None,   # no heal phase in this scenario
            "injected": inj, "dup_frames_deduped": dup_frames,
            "slo": slo_blk,
            **led,
            "gates": {
                "exactly_once": led["ops_lost"] == 0
                and led["ops_double_applied"] == 0
                and led["parity_bit_for_bit"],
                "injected_nonzero": inj.get("duplicate", 0) > 0
                and inj.get("reorder", 0) > 0,
                "dups_reached_shard": dup_frames > 0,
                # false-fire guard: the sentinel judged every poll and
                # nothing fired — chaos that never stalls progress is
                # not an availability episode
                "slo_quiet": slo_blk["evals"] > 0
                and sum(slo_blk["episodes"].values()) == 0
                and not _read_alerts(mdir),
            },
        }
    finally:
        _disarm_sentinel()
        w.close()


def scenario_slow_shard_shed(seconds: float = 12.0,
                             tmp: str = "") -> dict:
    """Slow-serve injection on shard 1 under a pooled read storm +
    training writes: the staleness bound must hold on every served
    read while the slow phase sheds/defers, and QPS recovers after
    the heal. The pool carries one warm spare so the autoscaling seam
    closes end-to-end: mid-slow, the storm's admission shedding rides
    the signal bus (``shed_rate`` ≫ policy, ``spares_left`` = 1) and
    ``tools/mvautoscale.recommend`` must say GROW — without actuating.
    Also a QUIET scenario for the availability objective (reads slow,
    writes never stall)."""
    from multiverso_tpu.ps import faults
    from multiverso_tpu.serving.admission import AdmissionController
    _tools = os.path.dirname(os.path.abspath(__file__))
    if _tools not in sys.path:
        sys.path.insert(0, _tools)
    import mvautoscale
    w = World(tmp, rows=32, dim=8, staleness_s=2.0)
    try:
        agg, mdir = _arm_sentinel(w, [AVAIL_OBJECTIVE])
        adm = AdmissionController()
        adm.set_limit(TABLE, "infer", 400.0)   # sheds the burst after
        plane = faults.arm({"seed": 13, "rules": [  # a slow unblock
            {"kind": "slow_serve", "rank": 1, "delay_ms": 350,
             "jitter_ms": 100, "phase": "slow"}]}, rank=0)
        pool = w.make_pool(replicas=2, spares=1, refresh_s=0.15,
                           admission=adm)
        tr = Traffic(w, n_threads=2).start()
        storm = InferStorm(pool, w.rows, n_threads=2).start()
        # ≥4.5 s pre: the admission bucket opens FULL, so the storm's
        # first ~1.25 s is a ~2x token burst (measured 680-790 QPS vs
        # 400 steady) — a shorter pre puts the burst inside the 3 s
        # pre-fault window and sets a recovery bar steady state can
        # never reach (the gate then flips on heal-burst luck)
        pre_s = min(max(seconds * 0.25, 4.5), 6.0)
        slow_s = min(max(seconds * 0.3, 2.5), 4.0)
        _sleep_poll(agg, pre_s)
        fault_wall = time.time()
        plane.set_phase("slow")
        _sleep_poll(agg, slow_s)
        # mid-storm verdict off the freshest record (rates derived vs
        # the poll one cadence earlier): the autoscaler's exact input
        verdict = mvautoscale.recommend(
            mvautoscale.snapshot_from_record(agg.last() or {}))
        heal_wall = time.time()
        plane.set_phase(None)
        _sleep_poll(agg, max(seconds - pre_s - slow_s, 4.0))
        storm.stop()
        tr.stop()
        led = tr.ledger()
        srv = storm.report()
        slo_blk = _slo_block(agg)
        pre, post, rec = recovery_from_stamps(
            storm.all_stamps(), tr.t_start, time.time(), fault_wall,
            recover_from=heal_wall)
        return {
            "recovery_s": rec, "recovered_to_90pct": rec is not None,
            "pre_fault_qps": round(pre, 1),
            "post_fault_qps": round(post, 1),
            "slow_s": round(heal_wall - fault_wall, 2),
            "injected": plane.stats()["injected"],
            "serving": srv, "pool": pool.stats_entry()["pool"],
            "slo": slo_blk,
            "autoscale": {"action": verdict["action"],
                          "actionable": verdict["actionable"],
                          "reason": verdict["reason"]},
            **led,
            "gates": {
                "exactly_once": led["ops_lost"] == 0
                and led["ops_double_applied"] == 0
                and led["parity_bit_for_bit"],
                "served_nonzero": srv["served"] > 0,
                "staleness": srv["over_bound_serves"] == 0,
                "recovery": rec is not None,
                "injected_nonzero":
                    plane.stats()["injected"].get("slow_serve", 0) > 0,
                "autoscale_grow": verdict["action"] == "grow"
                and verdict["actionable"],
                # the injected slow-serve genuinely stalls the data
                # plane, so the availability objective MAY fire during
                # the slow phase (correct detection, not noise) — but
                # the sentinel must judge throughout and be CLEAR again
                # once the heal's polls age the stall out of the fast
                # window
                "slo_judged_and_clear": slo_blk["evals"] > 0
                and slo_blk["firing"] == [],
            },
        }
    finally:
        _disarm_sentinel()
        w.close()


def scenario_replica_kill(seconds: float = 10.0,
                          tmp: str = "") -> dict:
    """Kill one pool member mid-storm: demotion + warm-spare
    activation keep served QPS up; the bound holds throughout."""
    w = World(tmp, rows=32, dim=8, staleness_s=2.0)
    try:
        pool = w.make_pool(replicas=2, spares=1, refresh_s=0.15)
        tr = Traffic(w, n_threads=2).start()
        storm = InferStorm(pool, w.rows, n_threads=2).start()
        pre_s = min(max(seconds * 0.3, 2.5), 4.0)
        time.sleep(pre_s)
        kill_wall = time.time()
        pool.kill_replica(0)
        time.sleep(max(seconds - pre_s, 5.0))
        storm.stop()
        tr.stop()
        led = tr.ledger()
        srv = storm.report()
        pre, post, rec = recovery_from_stamps(
            storm.all_stamps(), tr.t_start, time.time(), kill_wall)
        pstats = pool.stats_entry()["pool"]
        return {
            "recovery_s": rec, "recovered_to_90pct": rec is not None,
            "pre_fault_qps": round(pre, 1),
            "post_fault_qps": round(post, 1),
            "serving": srv, "pool": pstats,
            "pool_events": [{"ts": ts, "phase": p, "member": m}
                            for ts, p, m in pool.events],
            **led,
            "gates": {
                "exactly_once": led["ops_lost"] == 0
                and led["ops_double_applied"] == 0
                and led["parity_bit_for_bit"],
                "served_nonzero": srv["served"] > 0,
                "staleness": srv["over_bound_serves"] == 0,
                "recovery": rec is not None,
                "spare_activated": any(
                    p == "spare_activated"
                    for _, p, _ in pool.events),
            },
        }
    finally:
        w.close()


def scenario_noisy_neighbor(seconds: float = 12.0,
                            tmp: str = "") -> dict:
    """Two tenants share one pool (ISSUE 18): the storm tenant drives
    far past its per-tenant infer budget while the victim is paced
    modestly over its own. Quiet phase (victim alone) measures the
    victim's baseline p99 on ADMITTED reads — with one active tenant
    no verdict can fire, by construction. Storm phase adds the storm
    tenant; the sweep must open exactly one noisy-neighbor episode
    and clear it after the cool-down. Sweeps run only on our explicit
    ``stats_snapshot`` pulls here — nothing else in this process asks
    for MSG_STATS — so the episode lifecycle is deterministic."""
    from multiverso_tpu.serving.admission import AdmissionController
    from multiverso_tpu.telemetry import flightrec as flight
    from multiverso_tpu.telemetry import tenants
    w = World(tmp, rows=32, dim=8, staleness_s=2.0)
    # flightrec verdict records deduped by ring seq across scans: the
    # python wire plane wraps the 4096-slot ring many times in a run,
    # so one scan at the end could miss an evicted record
    verdict_seqs = {}

    def scan_verdicts():
        for s in flight.RECORDER.snapshot():
            if s[2] == flight.EV_TENANT_VERDICT:
                verdict_seqs[s[0]] = s[7]

    try:
        # the full matrix runs scenarios in ONE process: drop the
        # neighbors' ledger entries and tape before the verdict gates
        tenants.reset()
        flight.reset()
        VICTIM_QPS, STORM_QPS = 30.0, 50.0
        STORM_BURST = 10.0
        adm = AdmissionController()
        adm.set_tenant_limit(TABLE, "victim", "infer", VICTIM_QPS,
                             burst=8.0)
        adm.set_tenant_limit(TABLE, "storm", "infer", STORM_QPS,
                             burst=STORM_BURST)
        pool = w.make_pool(replicas=2, refresh_s=0.15, admission=adm)
        quiet_s = min(max(seconds * 0.3, 2.5), 4.0)
        storm_s = min(max(seconds * 0.4, 3.0), 5.0)
        # victim: ~90 attempts/s vs a 30 qps budget — sheds steadily in
        # BOTH phases, so the storm interval always has a degraded
        # second tenant (the verdict's victim condition)
        victim = TenantReader(pool, w.rows, "victim",
                              pace_s=1.0 / 90.0).start()
        time.sleep(quiet_s)
        tenants.stats_snapshot()   # sweep 1: quiet interval — victim
        scan_verdicts()            # alone, no verdict possible
        storm_wall = time.time()
        # storm: 2 threads ~250 attempts/s each vs a 50 qps budget
        storm = TenantReader(pool, w.rows, "storm", pace_s=0.004,
                             n_threads=2).start()
        time.sleep(max(storm_s * 0.6, 1.5))
        snap_mid = tenants.stats_snapshot()   # sweep 2: verdict fires
        scan_verdicts()
        time.sleep(max(storm_s * 0.4, 1.0))
        tenants.stats_snapshot()   # sweep 3: episode stays open (dedup)
        scan_verdicts()
        storm.stop()
        storm_end = time.time()
        victim.stop()
        tenants.stats_snapshot()   # sweep 4: residual deltas
        time.sleep(0.25)
        final = tenants.stats_snapshot()   # sweep 5: zero deltas clear
        scan_verdicts()            # the episode

        v_lat = victim.all_lat()
        base = [ms for ts, ms in v_lat if ts < storm_wall]
        stormp = [ms for ts, ms in v_lat if ts >= storm_wall]
        base_p99 = float(np.percentile(base, 99)) if base else 0.0
        storm_p99 = (float(np.percentile(stormp, 99)) if stormp
                     else float("inf"))
        # sub-ms baselines on the in-process pool are scheduler noise,
        # not a serving-latency statement: floor before the 2x gate
        p99_bound = 2.0 * max(base_p99, 1.5)
        T = storm_end - storm_wall
        srv_v, srv_s = victim.report(), storm.report()
        # the budget cap: served <= qps*T + burst + slack (one second
        # of rate + a constant for sweep/timing jitter); equivalently
        # shed >= attempts - allowed — "shed at the budget"
        allowed = STORM_QPS * T + STORM_BURST + STORM_QPS + 20.0
        ver = final.get("verdict") or {}
        return {
            "recovery_s": None,   # no heal phase: caps + verdicts gate
            "quiet_s": round(quiet_s, 2), "storm_s": round(T, 2),
            "victim": {
                "qps_limit": VICTIM_QPS, **srv_v,
                "base_p99_ms": round(base_p99, 3),
                "storm_p99_ms": round(storm_p99, 3),
                "storm_served": len(stormp),
            },
            "storm": {
                "qps_limit": STORM_QPS, **srv_s,
                "allowed_at_budget": round(allowed, 1),
            },
            "storm_share": (snap_mid.get("shares") or {}).get("storm"),
            "tenants_block": {k: final.get(k) for k in
                              ("shares", "episodes", "active",
                               "verdict")},
            "flight_verdicts": len(verdict_seqs),
            "episodes": tenants.LEDGER.episodes(),
            "gates": {
                "served_nonzero": len(base) > 0 and srv_s["served"] > 0,
                "storm_capped": srv_s["served"] <= allowed,
                "storm_shed_nonzero": srv_s["shed"] > 0,
                "victim_admitted": len(stormp) > 0,
                "victim_p99": storm_p99 <= p99_bound,
                "staleness": srv_v["over_bound_serves"] == 0
                and srv_s["over_bound_serves"] == 0,
                "verdict_once": tenants.LEDGER.episodes() == 1
                and len(verdict_seqs) == 1,
                "verdict_in_stats": final.get("episodes") == 1
                and final.get("active") is False
                and ver.get("tenant") == "storm",
            },
        }
    finally:
        w.close()


# ---------------------------------------------------------------------- #
# combined scenario: OS-process SIGKILL of a shard + replica kill,
# under training writes + an inference storm (the PR-7 flow, extended
# with the serving plane)
# ---------------------------------------------------------------------- #
def worker(argv) -> None:
    """Worker body (both ranks): python tools/bench_chaos.py worker
    <rdv> <hb> <ck> <world> <rank> <rows> <dim> <threads>"""
    rdv_dir, hb_dir, ck_dir = argv[0], argv[1], argv[2]
    world, rank = int(argv[3]), int(argv[4])
    rows, dim, n_threads = int(argv[5]), int(argv[6]), int(argv[7])
    import jax
    jax.config.update("jax_platforms", "cpu")

    from multiverso_tpu import elastic
    from multiverso_tpu.ps import failover
    from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                           PSService)
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    from multiverso_tpu.serving.pool import ReplicaPool
    from multiverso_tpu.utils import config
    from multiverso_tpu.utils.dashboard import Dashboard

    restarted = os.environ.get("MV_RESTARTED") == "1"
    config.set_flag("ps_timeout", 60.0)
    config.set_flag("ps_connect_timeout", 5.0)
    config.set_flag("ps_reconnect_backoff", 0.3)
    config.set_flag("ps_replay", True)
    config.set_flag("ps_replay_backoff", 0.2)
    config.set_flag("ps_replay_backoff_cap", 1.0)
    config.set_flag("ps_generation",
                    int(os.environ.get("MV_PS_GENERATION", "0")))
    config.set_flag("failover_dir", ck_dir)
    # a RESTARTED rank must restore BEFORE its first periodic save —
    # an empty-shard save racing the restore would become the newest
    # committed tag; the checkpointer starts manually after rejoin
    config.set_flag("failover_ckpt_interval_s",
                    0.0 if restarted else 0.5)
    # restarted ranks defer the rendezvous publish: the restore must
    # complete before any survivor can discover the fresh address
    svc = PSService(rank, world, FileRendezvous(rdv_dir),
                    defer_publish=restarted)
    ctx = PSContext(rank, world, svc)
    hb = elastic.Heartbeat(hb_dir, interval=0.2, rank=rank,
                           addr=svc.addr)
    elastic.bind_ps(hb_dir, ctx)
    t = AsyncMatrixTable(rows, dim, name=TABLE, send_window_ms=1.0,
                         ctx=ctx)
    if restarted:
        failover.rejoin(ck_dir, rank, [t], heartbeat=hb, service=svc)
        config.set_flag("failover_ckpt_interval_s", 0.5)
        failover.ensure_checkpointer(svc)
    hb.start()

    if rank != 0:
        # server only: hold the shard up until the driver is done
        done = os.path.join(rdv_dir, "done")
        while not os.path.exists(done):
            time.sleep(0.05)
        hb.stop()
        ctx.close()
        print("RESULT " + json.dumps(
            {"rank": rank, "restarted": restarted,
             "gen": svc.generation}), flush=True)
        return

    # ------------------------- traffic plane -------------------------- #
    half = rows // world
    stop = threading.Event()
    per_thread_counts = [np.zeros(rows, np.int64)
                         for _ in range(n_threads)]
    per_thread_stamps = [[] for _ in range(n_threads)]
    errs = [0] * n_threads

    def run_traffic(j: int) -> None:
        # even threads hammer shard 0's rows, odd threads shard 1's —
        # disjoint per-thread row sets, so the oracle is exact
        base = 0 if j % 2 == 0 else half
        mine = [base + (j // 2) + k * (n_threads // 2 + 1)
                for k in range(3)]
        mine = [r for r in mine if base <= r < base + half]
        ones = np.ones((1, dim), np.float32)
        counts, stamps = per_thread_counts[j], per_thread_stamps[j]
        i = 0
        while not stop.is_set():
            row = mine[i % len(mine)]
            try:
                t.add_rows([row], ones)   # blocking = acked
            except Exception:   # noqa: BLE001 — replay window exhausted
                errs[j] += 1
                time.sleep(0.05)
                continue
            counts[row] += 1
            stamps.append(time.time())
            i += 1

    # ------------------------- serving plane -------------------------- #
    # the replica pool + inference storm (ISSUE 14): 2 actives + 1
    # warm spare; the parent's kill_replica marker fells member 0 at
    # the same instant it SIGKILLs the rank-1 shard
    pool = ReplicaPool(t, replicas=2, spares=1, refresh_s=0.2,
                       staleness_s=2.5, probe_s=0.3, start=True)
    storm = InferStorm(pool, rows, n_threads=2, pace_s=0.004).start()
    kill_marker = os.path.join(rdv_dir, "kill_replica")

    def watch_kill():
        while not stop.is_set():
            if os.path.exists(kill_marker):
                pool.kill_replica(0)
                return
            time.sleep(0.05)

    threads = [threading.Thread(target=run_traffic, args=(j,),
                                daemon=True) for j in range(n_threads)]
    killer = threading.Thread(target=watch_kill, daemon=True)
    t0 = time.time()
    for th in threads:
        th.start()
    killer.start()
    open(os.path.join(rdv_dir, "traffic_started"), "w").close()
    stop_marker = os.path.join(rdv_dir, "stop_traffic")
    while not os.path.exists(stop_marker):
        time.sleep(0.05)
    stop.set()
    storm.stop()
    for th in threads:
        th.join(timeout=90)
    # drain every retained/replayed frame before the parity read
    t.flush()
    final = t.get_rows(np.arange(rows))
    acked = np.zeros(rows, np.int64)
    for c in per_thread_counts:
        acked += c
    oracle = np.repeat(acked[:, None], dim, axis=1).astype(np.float32)
    per_row = final[:, 0].astype(np.int64)
    lost = int(np.maximum(acked - per_row, 0).sum())
    double = int(np.maximum(per_row - acked, 0).sum())
    parity = bool(np.array_equal(final, oracle))
    # bucketized completion-rate series for the parent's recovery math
    t_end = time.time()
    stamps = np.concatenate(
        [np.asarray(s) for s in per_thread_stamps if s] or
        [np.zeros(0)])
    buckets = rate_buckets(stamps, t0, t_end)
    serve_buckets = rate_buckets(storm.all_stamps(), t0, t_end)
    # replay-plane counters + the restored victim's dedupe stats
    rep = {k: Dashboard.get(f"table[{TABLE}].replay.{k}").count
           for k in ("frames", "dups", "dropped")}
    victim_stats = {}
    try:
        victim_stats = t.server_stats(1)["shards"][TABLE]
        victim_stats = {k: victim_stats.get(k) for k in
                        ("dup_frames", "replay_clients", "adds",
                         "applies", "version")}
    except Exception as e:   # noqa: BLE001 — stats are best-effort
        victim_stats = {"error": f"{type(e).__name__}: {e}"[:120]}
    out = {
        "rank": 0, "t0": t0, "bucket_s": BUCKET_S,
        "buckets": buckets.tolist(),
        "serve_buckets": serve_buckets.tolist(),
        "serving": storm.report(),
        "pool": pool.stats_entry()["pool"],
        "pool_events": [{"ts": ts, "phase": p, "member": m}
                        for ts, p, m in pool.events],
        "acked_ops": int(acked.sum()), "ops_lost": lost,
        "ops_double_applied": double,
        "parity_bit_for_bit": parity,
        "add_errors": int(sum(errs)),
        "replay": rep, "victim_shard": victim_stats,
    }
    open(os.path.join(rdv_dir, "done"), "w").close()
    pool.close()
    hb.stop()
    ctx.close()
    print("RESULT " + json.dumps(out), flush=True)


def _spawn_worker(rdv, hb, ck, world, rank, rows, dim, threads,
                  gen: int = 0, restarted: bool = False):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MV_PS_GENERATION"] = str(gen)
    if restarted:
        env["MV_RESTARTED"] = "1"
    else:
        env.pop("MV_RESTARTED", None)
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "worker", rdv, hb,
         ck, str(world), str(rank), str(rows), str(dim), str(threads)],
        stdout=subprocess.PIPE, text=True, env=env)


def _recovery_from_buckets(res: dict, kill_wall: float,
                           key: str = "buckets"):
    """The combined worker's RESULT bucket series → the shared
    recovery detector (recovery measured from the kill)."""
    bs = res["bucket_s"]
    return _recovery_core(np.asarray(res[key], np.float64) / bs,
                          res["t0"], bs, kill_wall, kill_wall)


def scenario_combined(seconds: float = 18.0, rows: int = 64,
                      dim: int = 8, threads: int = 4) -> dict:
    """SIGKILL the rank-1 shard (real OS process) AND kill a pool
    replica at the same instant, mid-storm; the FailoverSupervisor
    respawns the shard, the pool activates its spare."""
    import tempfile

    from multiverso_tpu.ps import failover

    tmp = tempfile.mkdtemp(prefix="mv_chaos_")
    rdv = os.path.join(tmp, "rdv")
    hb = os.path.join(tmp, "hb")
    ck = os.path.join(tmp, "ck")
    os.makedirs(rdv)
    world = 2
    procs = {}
    procs[1] = _spawn_worker(rdv, hb, ck, world, 1, rows, dim, threads)
    procs[0] = _spawn_worker(rdv, hb, ck, world, 0, rows, dim, threads)

    def kill_rank(rank: int) -> None:
        p = procs.get(rank)
        if p is not None and p.poll() is None:
            p.kill()

    def spawn_rank(rank: int, gen: int) -> None:
        procs[rank] = _spawn_worker(rdv, hb, ck, world, rank, rows, dim,
                                    threads, gen=gen, restarted=True)

    sup = failover.FailoverSupervisor(
        hb, world, rendezvous_dir=rdv, spawn=spawn_rank, kill=kill_rank,
        timeout=2.0, poll_s=0.2, ranks=[1])
    try:
        deadline = time.time() + 120
        started = os.path.join(rdv, "traffic_started")
        while not os.path.exists(started):
            if time.time() > deadline:
                raise RuntimeError("traffic never started")
            for p in procs.values():
                if p.poll() not in (None, 0):
                    raise RuntimeError("worker died during startup")
            time.sleep(0.05)
        sup.start()
        pre_s = min(max(seconds * 0.3, 3.0), 8.0)
        time.sleep(pre_s)
        # chaos: SIGKILL the victim server shard AND fell a pool
        # replica in the driver, mid-traffic, same instant
        kill_wall = time.time()
        kill_rank(1)
        open(os.path.join(rdv, "kill_replica"), "w").close()
        # recovery time varies run to run (the respawn is dominated by
        # a JAX import: 2-8 s under load) — anchor the end of the run
        # to the OBSERVED rejoin, so the sustained-90% detector always
        # gets several seconds of post-recovery traffic to look at
        rejoin_deadline = time.time() + 60
        while not any(p == "rejoin" for _, p, _ in sup.events):
            if time.time() > rejoin_deadline:
                break
            time.sleep(0.2)
        time.sleep(max(seconds - pre_s - (time.time() - kill_wall),
                       6.0))
        open(os.path.join(rdv, "stop_traffic"), "w").close()
        out0, _ = procs[0].communicate(timeout=180)
        if procs[0].returncode != 0:
            sys.stderr.write(out0[-2000:])
            raise RuntimeError(f"driver rc={procs[0].returncode}")
        res = None
        for line in out0.splitlines():
            if line.startswith("RESULT "):
                res = json.loads(line[len("RESULT "):])
        if res is None:
            raise RuntimeError("driver produced no RESULT line")
    finally:
        sup.stop()
        open(os.path.join(rdv, "done"), "w").close()
        for p in procs.values():
            if p.poll() is None:
                try:
                    p.communicate(timeout=20)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    pre, post, train_rec = _recovery_from_buckets(res, kill_wall)
    srv_pre, srv_post, srv_rec = _recovery_from_buckets(
        res, kill_wall, key="serve_buckets")
    srv = res.get("serving", {})
    return {
        # the combined scenario's headline: served-QPS recovery (the
        # acceptance gate); train-add recovery rides beside it (the
        # PR-7 legacy trend, still the top-level extra.chaos key)
        "recovery_s": srv_rec,
        "recovered_to_90pct": srv_rec is not None,
        "train_recovery_s": train_rec,
        "train_recovered_to_90pct": train_rec is not None,
        "pre_fault_qps": round(srv_pre, 1),
        "post_fault_qps": round(srv_post, 1),
        "pre_fault_ops_per_s": round(pre, 1),
        "post_fault_ops_per_s": round(post, 1),
        "acked_ops": res["acked_ops"],
        "ops_lost": res["ops_lost"],
        "ops_double_applied": res["ops_double_applied"],
        "parity_bit_for_bit": res["parity_bit_for_bit"],
        "add_errors": res["add_errors"],
        "serving": srv, "pool": res.get("pool"),
        "pool_events": res.get("pool_events"),
        "replay": res["replay"],
        "victim_shard": res["victim_shard"],
        "supervisor": {
            "events": [{"ts": ts, "phase": ph, "rank": r}
                       for ts, ph, r in sup.events],
            "spans": sup.recovery_spans(),
        },
        "world": world, "rows": rows, "dim": dim, "threads": threads,
        "gates": {
            "exactly_once": res["ops_lost"] == 0
            and res["ops_double_applied"] == 0
            and res["parity_bit_for_bit"],
            "served_nonzero": srv.get("served", 0) > 0,
            "staleness": srv.get("over_bound_serves", 0) == 0,
            "recovery": srv_rec is not None and train_rec is not None,
            "spare_activated": any(
                e.get("phase") == "spare_activated"
                for e in res.get("pool_events") or []),
        },
    }


# ---------------------------------------------------------------------- #
SCENARIOS = {
    "partition_heal": scenario_partition_heal,
    "dup_reorder": scenario_dup_reorder,
    "slow_shard_shed": scenario_slow_shard_shed,
    "replica_kill": scenario_replica_kill,
    "noisy_neighbor": scenario_noisy_neighbor,
}


def main(argv) -> int:
    args, only = [], None
    it = iter(argv)
    for a in it:
        if a.startswith("--scenario"):
            # both spellings: --scenario=name and --scenario name
            only = (a.split("=", 1)[1] if "=" in a
                    else next(it, None))
        elif not a.startswith("--"):
            args.append(a)
    if only is not None and only != "combined" \
            and only not in SCENARIOS:
        print(f"unknown scenario {only!r} (one of "
              f"{sorted(SCENARIOS) + ['combined']})", file=sys.stderr)
        return 2
    seconds = float(args[0]) if args else 18.0
    rows = int(args[1]) if len(args) > 1 else 64
    dim = int(args[2]) if len(args) > 2 else 8
    threads = int(args[3]) if len(args) > 3 else 4

    scenarios = {}
    failed = []
    run_list = ([only] if only and only != "combined"
                else list(SCENARIOS) if only is None else [])
    for name in run_list:
        fn = SCENARIOS[name]
        t0 = time.time()
        try:
            rec = fn(seconds=max(seconds * 0.6, 8.0))
        except Exception as e:   # noqa: BLE001 — one scenario's crash
            rec = {"error": f"{type(e).__name__}: {e}"[:300],
                   "gates": {"ran": False}}
        rec["wall_s"] = round(time.time() - t0, 1)
        scenarios[name] = rec
        bad = [g for g, ok in rec.get("gates", {}).items() if not ok]
        if bad:
            failed.append(f"{name}: {','.join(bad)}")
        print(f"# scenario {name}: "
              + ("FAILED " + ",".join(bad) if bad else "ok")
              + f" ({rec['wall_s']}s)", file=sys.stderr, flush=True)
    combined = None
    if only in (None, "combined"):
        t0 = time.time()
        try:
            combined = scenario_combined(seconds=seconds, rows=rows,
                                         dim=dim, threads=threads)
        except Exception as e:   # noqa: BLE001
            combined = {"error": f"{type(e).__name__}: {e}"[:300],
                        "gates": {"ran": False}}
        combined["wall_s"] = round(time.time() - t0, 1)
        scenarios["combined"] = combined
        bad = [g for g, ok in combined.get("gates", {}).items()
               if not ok]
        if bad:
            failed.append(f"combined: {','.join(bad)}")
        print("# scenario combined: "
              + ("FAILED " + ",".join(bad) if bad else "ok")
              + f" ({combined['wall_s']}s)", file=sys.stderr,
              flush=True)

    result = {"scenarios": scenarios,
              "gates_failed": failed}
    # SLO sentinel roll-up (telemetry/slo.py): per-objective episode
    # counts summed across scenarios — the extra.slo block bench.py
    # lifts and run_bench compares run-over-run by objective name
    slo_eps: dict = {}
    slo_evals = 0
    for rec in scenarios.values():
        blk = rec.get("slo")
        if not isinstance(blk, dict):
            continue
        for name, n in (blk.get("episodes") or {}).items():
            slo_eps[name] = slo_eps.get(name, 0) + int(n or 0)
        slo_evals += int(blk.get("evals") or 0)
    if slo_evals:
        result["slo"] = {"episodes": slo_eps, "evals": slo_evals}
    if combined is not None and "error" not in combined:
        # legacy PR-7 trend keys at the top level (run_bench's
        # chaos.recovery_s baseline was train-add recovery)
        result.update({
            "recovery_s": combined.get("train_recovery_s"),
            "recovered_to_90pct":
                combined.get("train_recovered_to_90pct"),
            "serve_recovery_s": combined.get("recovery_s"),
            "acked_ops": combined.get("acked_ops"),
            "ops_lost": combined.get("ops_lost"),
            "ops_double_applied": combined.get("ops_double_applied"),
            "parity_bit_for_bit": combined.get("parity_bit_for_bit"),
        })
    print("RESULT " + json.dumps(result), flush=True)
    return 3 if failed else 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "worker":
        worker(sys.argv[2:])
    else:
        raise SystemExit(main(sys.argv[1:]))
