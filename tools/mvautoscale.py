#!/usr/bin/env python
"""mvautoscale — the autoscaling seam over the typed signal bus.

    python tools/mvautoscale.py --rdv RDV_DIR --dry-run [--json]

Polls a live cluster twice (mvtop's one-shot probe path, so it answers
even when the data plane is wedged), derives windowed rates between the
polls, runs the record through ``telemetry/signals.from_record`` — the
SAME pure derivation the aggregator publishes on every poll — and feeds
the resulting signal snapshot to :func:`recommend`, the one policy
function that turns bus signals into a ReplicaPool grow/shrink/hold
verdict.

This tool NEVER actuates (ROADMAP 5b keeps actuation behind an explicit
controller); ``--dry-run`` is mandatory and the exit code carries the
verdict for scripts: 0 = hold, 10 = grow, 11 = shrink, 2 = no cluster.

The policy is deliberately small and legible:

* **grow** — shed pressure (any table shedding above ``shed_max``, an
  SLO burn rate at/above ``burn_fire``, or a queue above ``queue_max``)
  AND at least one warm spare to promote (``spares_left > 0``). A
  pressured pool with no spares is a **hold** with
  ``actionable: false`` — the recommendation a capacity planner reads,
  not one a controller can execute.
* **shrink** — more than ``min_active`` replicas while every pressure
  signal is quiet (no shed, burn ≈ 0, empty queues): the cluster is
  paying replica fan-out for serving demand that is not there.
* **hold** — anything else, including "no signals at all".

:func:`recommend` is pure (snapshot dict in, verdict dict out) and is
what the chaos harness and tests call directly; the CLI exists so an
operator can point it at any rendezvous directory.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

_TOOLS = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_TOOLS)
for _p in (_REPO, _TOOLS):
    if _p not in sys.path:
        sys.path.insert(0, _p)

# grow/shrink thresholds — see module docstring for what each gates
DEFAULT_POLICY = {
    "shed_max": 0.02,    # tolerated shed fraction before grow pressure
    "burn_fire": 1.0,    # SLO burn rate that counts as pressure
    "queue_max": 256.0,  # queue depth that counts as pressure
    "burn_quiet": 0.1,   # burn rate below this is "quiet" for shrink
    "min_active": 1,     # never recommend shrinking below this
}

_EXIT_BY_ACTION = {"hold": 0, "grow": 10, "shrink": 11}


def _values(snapshot: Dict, name: str) -> List[Tuple[str, float]]:
    """(table, value) pairs for one signal name; non-numeric entries
    are skipped (a malformed payload must not crash the policy)."""
    out: List[Tuple[str, float]] = []
    for table, ent in sorted((snapshot.get(name) or {}).items()):
        v = ent.get("value") if isinstance(ent, dict) else None
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out.append((table, float(v)))
    return out


def recommend(snapshot: Dict, policy: Optional[Dict] = None) -> Dict:
    """Signal-bus snapshot (``signals.SignalBus.snapshot()`` shape:
    ``{name: {table-or-"": {"value", "ts", "detail"}}}``) -> one
    verdict dict ``{"action", "actionable", "reason", "signals"}``.
    Pure — no I/O, no clocks — so tests drive it on synthetic
    snapshots and the chaos harness on live ones."""
    pol = dict(DEFAULT_POLICY)
    if policy:
        pol.update(policy)
    sheds = _values(snapshot, "shed_rate")
    burns = _values(snapshot, "burn_rate")
    queues = _values(snapshot, "queue_depth")
    spares = max((v for _, v in _values(snapshot, "spares_left")),
                 default=None)
    active = max((v for _, v in _values(snapshot, "active_replicas")),
                 default=None)
    used = {
        "shed_rate": {t: v for t, v in sheds},
        "burn_rate": {t: v for t, v in burns},
        "queue_depth": {t: v for t, v in queues},
        "spares_left": spares,
        "active_replicas": active,
    }

    pressure = []
    for t, v in sheds:
        if v > pol["shed_max"]:
            pressure.append(f"shed_rate[{t}]={v:.3f}>{pol['shed_max']}")
    for t, v in burns:
        if v >= pol["burn_fire"]:
            pressure.append(f"burn_rate[{t}]={v:.1f}>={pol['burn_fire']}")
    for t, v in queues:
        if v > pol["queue_max"]:
            pressure.append(f"queue_depth[{t}]={v:.0f}>{pol['queue_max']}")

    if pressure:
        if spares is not None and spares > 0:
            return {"action": "grow", "actionable": True,
                    "reason": "; ".join(pressure)
                    + f"; spares_left={spares:.0f}",
                    "signals": used}
        return {"action": "hold", "actionable": False,
                "reason": "; ".join(pressure)
                + "; no warm spares to promote",
                "signals": used}

    quiet = (all(v <= 0.0 for _, v in sheds)
             and all(v < pol["burn_quiet"] for _, v in burns)
             and all(v <= 0.0 for _, v in queues))
    if (quiet and active is not None and active > pol["min_active"]
            and (sheds or burns or queues)):
        return {"action": "shrink", "actionable": True,
                "reason": f"active_replicas={active:.0f}>"
                f"{pol['min_active']} with no shed/burn/queue pressure",
                "signals": used}
    return {"action": "hold", "actionable": False,
            "reason": "no pressure and no idle surplus",
            "signals": used}


def snapshot_from_record(rec: Dict) -> Dict:
    """One merged cluster record -> the bus-snapshot shape
    :func:`recommend` consumes, via the same pure
    ``signals.from_record`` the aggregator publishes."""
    from multiverso_tpu.telemetry import signals as _signals
    snap: Dict[str, Dict] = {}
    for s in _signals.from_record(rec):
        snap.setdefault(s.name, {})[s.table or ""] = {
            "value": s.value, "ts": s.ts, "detail": s.detail}
    return snap


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mvautoscale",
        description="recommend ReplicaPool grow/shrink from the "
                    "telemetry signal bus (never actuates)")
    ap.add_argument("--rdv", required=True,
                    help="file-rendezvous directory (<rank>.addr files)")
    ap.add_argument("--world", type=int, default=None,
                    help="rank count (default: every published addr)")
    ap.add_argument("--dry-run", action="store_true",
                    help="required: print the recommendation, touch "
                         "nothing (actuation lives behind a future "
                         "controller, not this tool)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="seconds between the two rate-derivation polls")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-rank probe timeout seconds")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict as one JSON object")
    args = ap.parse_args(argv)
    if not args.dry_run:
        print("mvautoscale: refusing to run without --dry-run "
              "(this tool only recommends; it never actuates)",
              file=sys.stderr)
        return 2

    import mvtop
    from multiverso_tpu.telemetry import aggregator
    addrs = mvtop.read_addrs(args.rdv, args.world)
    if not addrs:
        print(f"mvautoscale: no <rank>.addr files under {args.rdv}",
              file=sys.stderr)
        return 2
    prev = mvtop.poll(addrs, args.timeout)
    time.sleep(max(args.interval, 0.05))
    rec = mvtop.poll(addrs, args.timeout)
    aggregator.derive_rates(prev, rec)
    verdict = recommend(snapshot_from_record(rec))
    if args.json:
        print(json.dumps(verdict))
    else:
        print(f"mvautoscale: {verdict['action'].upper()}"
              f"{'' if verdict['actionable'] else ' (not actionable)'}"
              f" — {verdict['reason']}")
    return _EXIT_BY_ACTION.get(verdict["action"], 0)


if __name__ == "__main__":
    raise SystemExit(main())
