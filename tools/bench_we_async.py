"""Bench worker: WordEmbedding PS-block training on the UNCOORDINATED
async plane — the reference's actual product shape (ref
distributed_wordembedding.cpp:147-252 block pipeline over N independent
processes + server.cpp async applies).

Same config/corpus as bench.bench_wordembedding_ps()'s 1M-token run
(seed 12), so the recorded async loss is directly comparable to the sync
plane's ``loss_1M``. Each rank trains blocks[rank::world] of the shared
corpus against async tables owned across the plane.

The MEASURED epoch runs with the step profiler live (flag
``step_profile``, telemetry/profiler.py): every block is one step with
``prepare``/``ps_wait``/``compute``/``push`` phases and per-op
``ps.get``/``ps.add`` async spans, and the RESULT carries the phase
breakdown, stall fraction, overlap credit, and compile counts (bench
``extra.profile``). Two in-run assertions (ISSUE 9 acceptance):
the profiler must attribute >= 90% of per-step wall time (phases +
async spans vs wall clock — interval-union math, so the number is
honest about gaps), and the steady state must not recompile (warm
epoch owns every compile; a mid-measure retrace is exactly the silent
regression the profiler exists to catch).

Invoked as: python tools/bench_we_async.py <rdv_dir> <world> <rank>
            <n_tokens>
Prints "RESULT <json>".
"""

import json
import sys


def main():
    rdv_dir, world, rank, n_tokens = (sys.argv[1], int(sys.argv[2]),
                                      int(sys.argv[3]), int(sys.argv[4]))
    import jax
    jax.config.update("jax_platforms", "cpu")

    import multiverso_tpu as mv
    from multiverso_tpu.apps.word_embedding import (WEConfig, WordEmbedding,
                                                    synthetic_corpus)
    from multiverso_tpu.data.dictionary import Dictionary
    from multiverso_tpu.telemetry import profiler as _prof
    from multiverso_tpu.utils import config
    from multiverso_tpu.utils.filesync import file_barrier

    config.set_flag("ps_rank", rank)
    config.set_flag("ps_world", world)
    config.set_flag("ps_rendezvous", rdv_dir)
    config.set_flag("ps_timeout", 180.0)
    mv.init()

    # data_presplit=1 + every rank fed the FULL corpus = the reference's
    # layout (each process sweeps all blocks, deltas divided by N,
    # communicator.cpp:154 / distributed_wordembedding.cpp block loop):
    # N sweeps x 1/N deltas net one epoch's learning, so the loss is
    # comparable to the sync plane's at the same epoch count.
    cfg = WEConfig(size=128, min_count=5, batch_size=8192, negative=5,
                   window=5, epoch=1, data_block_size=50_000,
                   use_ps="1", async_ps="1", data_presplit="1", seed=12)
    tokens = synthetic_corpus(n_tokens, vocab=5_000, seed=12)
    dictionary = Dictionary.build(tokens, cfg.min_count)
    we = WordEmbedding(cfg, dictionary)
    ids = we.prepare_ids(tokens)
    file_barrier(rdv_dir, world, rank, "tables", timeout=180)
    we.train_ps_blocks(ids)               # warm: compile block programs
    file_barrier(rdv_dir, world, rank, "warm", timeout=180)
    # profile the MEASURED epoch only: the warm epoch's compiles belong
    # to warmup; steady-state steps must attribute >= 90% of wall and
    # recompile zero times (both asserted below)
    config.set_flag("step_profile", True)
    _prof.configure()
    stats = we.train_ps_blocks(ids)       # measured epoch
    config.set_flag("step_profile", False)
    _prof.configure()
    file_barrier(rdv_dir, world, rank, "trained", timeout=180)
    prof = _prof.summary()
    profile = None
    if prof["steps"]:
        # ISSUE 9 acceptance, asserted IN-RUN: the phase/span instrument
        # must account for >= 90% of the measured epoch's wall clock —
        # a profiler that misses a tenth of the step cannot name the
        # critical path. Interval-union math (profiler._finalize), so
        # overlapping phases cannot inflate the fraction past 1.
        assert prof["attributed_fraction"] >= 0.90, (
            f"profiler attributed only "
            f"{prof['attributed_fraction']:.1%} of step wall time")
        # steady state must not recompile: every block program compiled
        # during the warm epoch, and a silent mid-measure retrace is a
        # perf regression the profiler exists to name
        assert prof["steady_recompiles"] == 0, (
            f"{prof['steady_recompiles']} steady-state recompiles "
            "during the measured epoch")
        phases = prof["phases"]
        steps = max(prof["steps"], 1)
        profile = {
            "steps": prof["steps"],
            "wall_ms_per_step": round(prof["wall_ms"] / steps, 2),
            "attributed_fraction": prof["attributed_fraction"],
            "stall_fraction": prof["stall_fraction"],
            "overlap_ms_per_step": round(prof["overlap_ms"] / steps, 2),
            # per-step EXCLUSIVE phase means — the ROADMAP item-2
            # headline ("prepare dominates block") read off directly
            "phase_ms_per_step": {n: round(v / steps, 2)
                                  for n, v in phases.items()},
            "prepare_dominates": bool(
                phases.get("prepare", 0.0)
                > phases.get("compute", 0.0)),
            "steady_recompiles": prof["steady_recompiles"],
            "compiles": prof["jax"]["compiles"],
            "transfer_mb": round(
                prof["jax"]["transfer_bytes"] / 1e6, 2),
        }
    mv.shutdown()
    out = {
        "rank": rank,
        "words_per_sec": round(stats["words_per_sec"], 1),
        "seconds": round(stats["seconds"], 3),
        "loss": stats["loss"],
    }
    if profile is not None:
        out["profile"] = profile
    print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
