"""Bench worker: WordEmbedding PS-block training on the UNCOORDINATED
async plane — the reference's actual product shape (ref
distributed_wordembedding.cpp:147-252 block pipeline over N independent
processes + server.cpp async applies).

Same config/corpus as bench.bench_wordembedding_ps()'s 1M-token run
(seed 12), so the recorded async loss is directly comparable to the sync
plane's ``loss_1M``. Each rank trains blocks[rank::world] of the shared
corpus against async tables owned across the plane.

Invoked as: python tools/bench_we_async.py <rdv_dir> <world> <rank>
            <n_tokens>
Prints "RESULT <json>".
"""

import json
import sys


def main():
    rdv_dir, world, rank, n_tokens = (sys.argv[1], int(sys.argv[2]),
                                      int(sys.argv[3]), int(sys.argv[4]))
    import jax
    jax.config.update("jax_platforms", "cpu")

    import multiverso_tpu as mv
    from multiverso_tpu.apps.word_embedding import (WEConfig, WordEmbedding,
                                                    synthetic_corpus)
    from multiverso_tpu.data.dictionary import Dictionary
    from multiverso_tpu.utils import config
    from multiverso_tpu.utils.filesync import file_barrier

    config.set_flag("ps_rank", rank)
    config.set_flag("ps_world", world)
    config.set_flag("ps_rendezvous", rdv_dir)
    config.set_flag("ps_timeout", 180.0)
    mv.init()

    # data_presplit=1 + every rank fed the FULL corpus = the reference's
    # layout (each process sweeps all blocks, deltas divided by N,
    # communicator.cpp:154 / distributed_wordembedding.cpp block loop):
    # N sweeps x 1/N deltas net one epoch's learning, so the loss is
    # comparable to the sync plane's at the same epoch count.
    cfg = WEConfig(size=128, min_count=5, batch_size=8192, negative=5,
                   window=5, epoch=1, data_block_size=50_000,
                   use_ps="1", async_ps="1", data_presplit="1", seed=12)
    tokens = synthetic_corpus(n_tokens, vocab=5_000, seed=12)
    dictionary = Dictionary.build(tokens, cfg.min_count)
    we = WordEmbedding(cfg, dictionary)
    ids = we.prepare_ids(tokens)
    file_barrier(rdv_dir, world, rank, "tables", timeout=180)
    we.train_ps_blocks(ids)               # warm: compile block programs
    file_barrier(rdv_dir, world, rank, "warm", timeout=180)
    stats = we.train_ps_blocks(ids)       # measured epoch
    file_barrier(rdv_dir, world, rank, "trained", timeout=180)
    mv.shutdown()
    print("RESULT " + json.dumps({
        "rank": rank,
        "words_per_sec": round(stats["words_per_sec"], 1),
        "seconds": round(stats["seconds"], 3),
        "loss": stats["loss"],
    }), flush=True)


if __name__ == "__main__":
    main()
