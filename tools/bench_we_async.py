"""Bench worker: WordEmbedding PS-block training on the UNCOORDINATED
async plane — the reference's actual product shape (ref
distributed_wordembedding.cpp:147-252 block pipeline over N independent
processes + server.cpp async applies).

Same config/corpus as bench.bench_wordembedding_ps()'s 1M-token run
(seed 12), so the recorded async loss is directly comparable to the sync
plane's ``loss_1M``. Each rank trains blocks[rank::world] of the shared
corpus against async tables owned across the plane.

The MEASURED epoch runs with the step profiler live (flag
``step_profile``, telemetry/profiler.py): every block is one step with
``prepare``/``ps_wait``/``compute``/``push`` phases (plus ``io_wait`` /
``we.pipeline`` on the ISSUE-11 pipelined path) and per-op
``ps.get``/``ps.add`` async spans, and the RESULT carries the phase
breakdown, stall fraction, overlap credit, and compile counts (bench
``extra.profile``). In-run assertions:

* ISSUE 9: the profiler must attribute >= 90% of per-step wall time, and
  the steady state must not recompile.
* ISSUE 11: stall fraction < 0.2 (the pipelined path's whole point is
  that the consumer never sits unattributed), and — on a real chip at
  the 1M-token config — the PS-backed path must clear the 2M
  words/s/chip floor. The floor is platform-gated: multi-process runs
  pin jax to CPU (N processes cannot share one TPU) and a CPU box
  cannot hit a chip target, so there the gate EXECUTES but records
  ``enforced: false`` in the result's ``perf_gate``. To actually
  enforce it, run single-process on a TPU host with
  ``MV_WE_BENCH_TPU=1`` — the worker then keeps the real backend and
  an under-floor run fails loudly.

Mode (optional 5th arg):

* ``pipeline`` (default) — the ISSUE-11 pipelined path: producer-thread
  prepared-block queue + hot-row training cache (write-through when
  eligible; multi-rank runs bound read staleness with a periodic
  refresh).
* ``oracle``  — the unpipelined/uncached path (``pipeline=0``, cache
  off): the bit-parity baseline. bench.bench_we_async runs both at
  world=1 and compares ``emb_sha`` — the pipelined path must be
  bit-identical to this oracle.

Invoked as: python tools/bench_we_async.py <rdv_dir> <world> <rank>
            <n_tokens> [mode]
Prints "RESULT <json>".
"""

import hashlib
import json
import sys

# ISSUE-11 acceptance floors, asserted in-run by _assert_perf_gates
WORDS_PER_S_CHIP_FLOOR = 2_000_000     # at the 1M-token config, on TPU
STALL_FRACTION_CEILING = 0.2
PERF_GATE_MIN_TOKENS = 1_000_000


def _assert_perf_gates(platform: str, words_per_sec: float,
                       n_tokens: int, mode: str) -> dict:
    """The ISSUE-11 words/s floor: enforced on a TPU at the 1M-token
    config, recorded (but not enforced) elsewhere — a CPU bench box
    cannot hit a per-chip target, and silently failing there would just
    train people to delete the gate. Only the ``pipeline`` mode is held
    to the floor: the ``oracle`` worker is the deliberately unpipelined
    serial-prepare baseline the floor exists to beat, so enforcing it
    there would fail the parity stage of every run that PASSES.
    Returns the ``perf_gate`` record for the RESULT json; raises
    AssertionError on an enforced miss."""
    enforced = (platform == "tpu" and n_tokens >= PERF_GATE_MIN_TOKENS
                and mode == "pipeline")
    gate = {"target_words_per_s": WORDS_PER_S_CHIP_FLOOR,
            "platform": platform, "enforced": enforced}
    if enforced:
        assert words_per_sec >= WORDS_PER_S_CHIP_FLOOR, (
            f"PS-backed WE path ran {words_per_sec:,.0f} words/s/chip — "
            f"under the {WORDS_PER_S_CHIP_FLOOR:,} floor (ISSUE 11 "
            "acceptance; profile the run: extra.profile + tools/mvprof)")
    return gate


def main():
    rdv_dir, world, rank, n_tokens = (sys.argv[1], int(sys.argv[2]),
                                      int(sys.argv[3]), int(sys.argv[4]))
    mode = sys.argv[5] if len(sys.argv) > 5 else "pipeline"
    assert mode in ("pipeline", "oracle"), mode
    import os

    import jax
    # N independent processes cannot share one TPU — the async-plane
    # bench is a host-wire bench and pins CPU (a chip run of the PS
    # block path is bench_wordembedding_ps's job). The ONE liftable
    # case: a single-process run with MV_WE_BENCH_TPU=1 keeps the real
    # backend, which is how the words/s floor below actually arms —
    # without this escape hatch the gate would be dead code on every
    # machine, TPU hosts included.
    if world > 1 or os.environ.get("MV_WE_BENCH_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import multiverso_tpu as mv
    from multiverso_tpu.apps.word_embedding import (WEConfig, WordEmbedding,
                                                    synthetic_corpus)
    from multiverso_tpu.data.dictionary import Dictionary
    from multiverso_tpu.telemetry import profiler as _prof
    from multiverso_tpu.utils import config
    from multiverso_tpu.utils.filesync import file_barrier

    config.set_flag("ps_rank", rank)
    config.set_flag("ps_world", world)
    config.set_flag("ps_rendezvous", rdv_dir)
    config.set_flag("ps_timeout", 180.0)
    if mode == "pipeline":
        # hot-row training cache (ISSUE 11): big enough for the bench
        # vocab, write-through when the table qualifies; multi-rank runs
        # bound the write-through read staleness with a periodic refresh
        # (remote pushes are invisible between refreshes — the async
        # plane's accepted bounded staleness, now with a knob on it)
        config.set_flag("train_cache_rows", 1 << 16)
        config.set_flag("train_cache_mode", "auto")
        config.set_flag("train_cache_refresh_gets",
                        16 if world > 1 else 0)
    mv.init()

    # data_presplit=1 + every rank fed the FULL corpus = the reference's
    # layout (each process sweeps all blocks, deltas divided by N,
    # communicator.cpp:154 / distributed_wordembedding.cpp block loop):
    # N sweeps x 1/N deltas net one epoch's learning, so the loss is
    # comparable to the sync plane's at the same epoch count.
    # block size scales down for tiny (tier-1 smoke / parity) corpora so
    # every run has >= ~4 blocks — the pipelined branch requires
    # len(schedule) > 1, and a single-block tiny run would smoke-test
    # only the inline fallback while claiming to cover the queue. The
    # 1M-token bench config keeps the canonical 50k blocks.
    block = min(50_000, max(4_000, n_tokens // 4))
    cfg = WEConfig(size=128, min_count=5, batch_size=8192, negative=5,
                   window=5, epoch=1, data_block_size=block,
                   use_ps="1", async_ps="1", data_presplit="1", seed=12,
                   pipeline="0" if mode == "oracle" else "1")
    tokens = synthetic_corpus(n_tokens, vocab=5_000, seed=12)
    dictionary = Dictionary.build(tokens, cfg.min_count)
    we = WordEmbedding(cfg, dictionary)
    ids = we.prepare_ids(tokens)
    file_barrier(rdv_dir, world, rank, "tables", timeout=180)
    we.train_ps_blocks(ids)               # warm: compile block programs
    file_barrier(rdv_dir, world, rank, "warm", timeout=180)
    # profile the MEASURED epoch only: the warm epoch's compiles belong
    # to warmup; steady-state steps must attribute >= 90% of wall and
    # recompile zero times (both asserted below)
    config.set_flag("step_profile", True)
    _prof.configure()
    stats = we.train_ps_blocks(ids)       # measured epoch
    config.set_flag("step_profile", False)
    _prof.configure()
    file_barrier(rdv_dir, world, rank, "trained", timeout=180)
    prof = _prof.summary()
    profile = None
    if prof["steps"]:
        # ISSUE 9 acceptance, asserted IN-RUN: the phase/span instrument
        # must account for >= 90% of the measured epoch's wall clock —
        # a profiler that misses a tenth of the step cannot name the
        # critical path. Interval-union math (profiler._finalize), so
        # overlapping phases cannot inflate the fraction past 1.
        assert prof["attributed_fraction"] >= 0.90, (
            f"profiler attributed only "
            f"{prof['attributed_fraction']:.1%} of step wall time")
        # ISSUE 11, asserted IN-RUN: the pipelined path exists to keep
        # the consumer off the floor — stall (unattributed wall: gaps
        # that are neither a phase nor an in-flight PS op) stays < 0.2
        assert prof["stall_fraction"] < STALL_FRACTION_CEILING, (
            f"stall fraction {prof['stall_fraction']:.1%} >= "
            f"{STALL_FRACTION_CEILING:.0%} — the prepare pipeline is "
            "not covering the step (see phases/io_wait in extra.we)")
        # steady state must not recompile: every block program compiled
        # during the warm epoch, and a silent mid-measure retrace is a
        # perf regression the profiler exists to name
        assert prof["steady_recompiles"] == 0, (
            f"{prof['steady_recompiles']} steady-state recompiles "
            "during the measured epoch")
        phases = prof["phases"]
        steps = max(prof["steps"], 1)
        profile = {
            "steps": prof["steps"],
            "wall_ms_per_step": round(prof["wall_ms"] / steps, 2),
            "attributed_fraction": prof["attributed_fraction"],
            "stall_fraction": prof["stall_fraction"],
            "overlap_ms_per_step": round(prof["overlap_ms"] / steps, 2),
            # per-step EXCLUSIVE phase means — the ROADMAP item-2
            # headline ("prepare dominates block") read off directly
            "phase_ms_per_step": {n: round(v / steps, 2)
                                  for n, v in phases.items()},
            "prepare_dominates": bool(
                phases.get("prepare", 0.0)
                > phases.get("compute", 0.0)),
            "steady_recompiles": prof["steady_recompiles"],
            "compiles": prof["jax"]["compiles"],
            "transfer_mb": round(
                prof["jax"]["transfer_bytes"] / 1e6, 2),
        }
    platform = jax.devices()[0].platform
    perf_gate = _assert_perf_gates(platform, stats["words_per_sec"],
                                   n_tokens, mode)
    out = {
        "rank": rank,
        "mode": mode,
        "words_per_sec": round(stats["words_per_sec"], 1),
        "seconds": round(stats["seconds"], 3),
        "loss": stats["loss"],
        "perf_gate": perf_gate,
    }
    tc = we.table_in.train_cache_stats()
    if tc is not None:
        out["train_cache"] = {"hit_rate": tc["hit_rate"],
                              "hits": tc["hits"], "misses": tc["misses"],
                              "mode": tc["mode"], "rows": tc["rows"]}
    if world == 1:
        # single-writer runs are bit-deterministic: the embedding digest
        # is the parity surface bench.bench_we_async compares between
        # this mode and the oracle (ISSUE-11 acceptance)
        h = hashlib.sha256()
        for t in (we.table_in, we.table_out):
            h.update(np.ascontiguousarray(
                t.get_rows(np.arange(t.shape[0]))).tobytes())
        out["emb_sha"] = h.hexdigest()
    if profile is not None:
        out["profile"] = profile
    mv.shutdown()
    print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
