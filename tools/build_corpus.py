"""Build a text8-style REAL-text corpus from English prose in this image.

The tier-4 convergence configs call for text8 (BASELINE.md), which cannot
be downloaded in a zero-egress environment. text8 is Wikipedia text piped
through Matt Mahoney's wikifil normalization: lowercase, a-z only,
everything else collapsed to single spaces. This tool applies the same
normalization to the real English documentation shipped inside the image
(package .rst/.md docs — numpy, jax, scipy, etc.), yielding a genuinely
real natural-language corpus with Zipfian vocabulary and topical
co-occurrence structure — the properties word2vec training exercises.

Usage: python tools/build_corpus.py [out_path] [max_mb]
Default: data/realtext.txt, 8 MB.
"""

from __future__ import annotations

import os
import re
import sys

SKIP_NAMES = re.compile(
    r"(license|copying|notice|authors|top_level|record|entry_points|"
    r"sources|installed-files|dependency_links)", re.I)
_AZ = re.compile(r"[^a-z]+")


def text8_normalize(raw: str) -> str:
    """wikifil-style: lowercase, a-z and single spaces only."""
    return _AZ.sub(" ", raw.lower()).strip()


def iter_doc_files(roots):
    for root in roots:
        for dirpath, _dirnames, filenames in os.walk(root):
            for fn in sorted(filenames):
                if not fn.endswith((".rst", ".md")):
                    continue
                if SKIP_NAMES.search(fn):
                    continue
                yield os.path.join(dirpath, fn)


def iter_docstrings(roots):
    """Docstrings of installed packages, extracted statically (ast) — the
    largest body of real English prose in the image (numpy/scipy/sklearn/
    torch document every function in full sentences)."""
    import ast
    for root in roots:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("test", "tests", "__pycache__")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                try:
                    with open(path, encoding="utf-8",
                              errors="ignore") as f:
                        tree = ast.parse(f.read(1 << 20))
                except (OSError, SyntaxError, ValueError):
                    continue
                parts = []
                for node in ast.walk(tree):
                    if isinstance(node, (ast.Module, ast.ClassDef,
                                         ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        doc = ast.get_docstring(node)
                        if doc and len(doc) > 80:
                            parts.append(doc)
                if parts:
                    yield path, "\n".join(parts)


def looks_english(text: str) -> bool:
    """Cheap prose filter: mostly letters, reasonable word lengths."""
    if len(text) < 500:
        return False
    words = text.split()
    if not words:
        return False
    avg = sum(len(w) for w in words) / len(words)
    return 2.5 <= avg <= 9.0


def build(out_path: str, max_bytes: int) -> int:
    import sysconfig
    roots = [sysconfig.get_paths()["purelib"]]
    for extra in ("/opt/venv/lib", "/usr/local/lib/python3.12"):
        if os.path.isdir(extra) and not any(
                r.startswith(extra) for r in roots):
            roots.append(extra)
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    total = 0
    with open(out_path, "w") as out:
        for path in iter_doc_files(roots):
            try:
                with open(path, encoding="utf-8", errors="ignore") as f:
                    raw = f.read(1 << 20)
            except OSError:
                continue
            norm = text8_normalize(raw)
            if not looks_english(norm):
                continue
            out.write(norm + " ")
            total += len(norm) + 1
            if total >= max_bytes:
                return total
        for _path, raw in iter_docstrings(roots):
            norm = text8_normalize(raw)
            if not looks_english(norm):
                continue
            out.write(norm + " ")
            total += len(norm) + 1
            if total >= max_bytes:
                return total
    return total


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "data", "realtext.txt")
    mb = float(sys.argv[2]) if len(sys.argv) > 2 else 8.0
    n = build(out, int(mb * 1e6))
    print(f"wrote {n/1e6:.1f} MB of normalized real text to {out}")
